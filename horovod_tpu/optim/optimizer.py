"""DistributedOptimizer / DistributedGradientTape for JAX training.

The reference wraps a framework optimizer so gradients are allreduced
before ``step()``: torch hooks per-parameter grad accumulators and fires
async allreduces as each gradient is produced
(``torch/optimizer.py:103-200``), TF rewrites ``compute_gradients``
(``tensorflow/__init__.py:289-316``), both honoring
``backward_passes_per_step`` accumulation and compression.

optax formulation: gradient averaging is itself a gradient transformation,
so ``DistributedOptimizer(opt)`` = ``chain(distributed_gradients(...),
opt)``, wrapped in ``optax.MultiSteps`` when ``backward_passes_per_step >
1``.  Three reduction modes, because JAX has three distribution idioms:

* ``"shard_map"`` (default): the transform runs inside
  ``shard_map``/``pmap`` with mesh axes bound; gradients are reduced with
  one fused in-graph collective per dtype
  (:func:`horovod_tpu.ops.collectives.grouped_allreduce`) which XLA
  overlaps with backward compute — the role of the reference's
  hook-fired async NCCL calls.
* ``"pjit"``: under global-array pjit the batch axis is sharded and XLA
  already inserts the gradient psum during autodiff; the transform is the
  identity (documented no-op, so user code is portable between modes).
* ``"process"``: host-level eager reduction across worker processes via
  the async-handle API (the closest literal analogue of the reference's
  per-tensor enqueue path).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple, Union

import os

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.collectives import Average, ReduceOp
from horovod_tpu.runtime.topology import (
    GLOBAL_AXES,
    HIERARCHY_MODES,
    TOPOLOGY_MODES,
    resolve_hierarchy,
    resolve_topology,
)

AxisSpec = Union[str, Sequence[str]]


def _sparse_leaf_reduce(g: jax.Array, max_rows: int, op: ReduceOp,
                        axis: AxisSpec,
                        prescale_factor: Optional[float] = None,
                        postscale_factor: Optional[float] = None
                        ) -> jax.Array:
    """Row-sparse reduction of one dense-shaped gradient leaf.

    JAX embedding gradients arrive dense (scatter-add of the used rows),
    so the IndexedSlices decomposition is recovered in-graph: the leaf's
    nonzero rows are extracted with a static ``max_rows`` bound
    (``jnp.nonzero(size=...)`` keeps shapes XLA-static) and exchanged via
    :func:`~horovod_tpu.ops.collectives.sparse_allreduce` — allgather of
    ``max_rows`` rows per shard instead of a dense allreduce of the full
    table (reference IndexedSlices path,
    ``tensorflow/__init__.py:100-110``).  Fill slots use the
    out-of-range index ``V``: their gathered values read as zero and the
    scatter drops them.  Rows beyond ``max_rows`` are silently dropped —
    the bound is the caller's promise about touched rows per step.
    """
    rows = g.shape[0]
    mask = jnp.any(g.reshape(rows, -1) != 0, axis=1)
    if os.environ.get("HOROVOD_DEBUG_SPARSE"):
        # opt-in: surface silent gradient truncation (rows beyond the
        # bound are dropped by design; misconfigured bounds degrade
        # training with no other signal)
        touched = jnp.sum(mask)
        jax.lax.cond(
            touched > max_rows,
            lambda: jax.debug.print(
                "sparse_params: {} touched rows exceed max_rows={}; "
                "excess gradients dropped", touched, max_rows),
            lambda: None)
    (idx,) = jnp.nonzero(mask, size=max_rows, fill_value=rows)
    vals = jnp.take(g, idx, axis=0, mode="fill", fill_value=0)
    vals = C._scale(vals, prescale_factor)
    out = C.sparse_allreduce(vals, idx, dense_rows=rows, axis=axis, op=op)
    return C._scale(out, postscale_factor)


def _path_components(path) -> list:
    """Flattened-path entries as plain strings (dict keys, attr names,
    sequence indices)."""
    out = []
    for entry in path:
        for attr in ("key", "name", "idx"):
            if hasattr(entry, attr):
                out.append(str(getattr(entry, attr)))
                break
        else:
            out.append(str(entry))
    return out


def _match_sparse(path, sparse_params) -> Optional[int]:
    """max_rows for a leaf whose path has a component equal to a
    configured name (or whose full '/'-joined path equals one), else
    None.  Whole-component matching: a pattern 'emb' must not
    accidentally route a dense leaf named 'member' through the
    truncating sparse path."""
    if not sparse_params:
        return None
    comps = _path_components(path)
    joined = "/".join(comps)
    for pat, max_rows in sparse_params.items():
        if pat == joined or pat in comps:
            return int(max_rows)
    return None


def distributed_gradients(op: ReduceOp = Average,
                          axis: AxisSpec = GLOBAL_AXES,
                          mode: str = "shard_map",
                          compression=None,
                          prescale_factor: Optional[float] = None,
                          postscale_factor: Optional[float] = None,
                          sparse_params: Optional[dict] = None
                          ) -> optax.GradientTransformation:
    """optax transform that cross-replica-reduces gradients.

    The composable core of :func:`DistributedOptimizer`; usable standalone
    in any optax chain.

    ``sparse_params`` maps leaf-path component names (e.g.
    ``"embedding"``, or a full ``"encoder/embedding"`` path) to a
    ``max_rows`` bound; matching leaves are reduced through the
    row-sparse allgather path instead of the dense allreduce — the
    reference's IndexedSlices routing (``tensorflow/__init__.py:100-110``,
    ``sparse_as_dense`` being the knob that turns it *off* there; here
    dense is already the default and ``sparse_params`` is the opt-in).
    Requires ``mode='shard_map'``.
    """
    if sparse_params and mode != "shard_map":
        raise ValueError(
            "sparse_params requires mode='shard_map' (pjit autodiff "
            "reduces densely; the process plane exchanges whole tensors)")
    if sparse_params and op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("sparse_params supports op=Sum/Average")

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if mode == "pjit":
            reduced = leaves  # XLA autodiff already reduced (see docstring)
        elif mode == "shard_map":
            sparse_rows: dict = {}
            if sparse_params:
                paths = jax.tree_util.tree_flatten_with_path(updates)[0]
                for i, (path, _) in enumerate(paths):
                    m = _match_sparse(path, sparse_params)
                    if m is not None:
                        sparse_rows[i] = m
            ins = [g for i, g in enumerate(leaves) if i not in sparse_rows]
            # Compression.int8 is a wire-*reduction* marker, not a
            # compressor: the shared-scale quantized psum runs inside
            # grouped_allreduce (see compression.Int8WireReduction)
            qbits = getattr(compression, "wire_reduce_bits", None)
            ctxs = None
            if compression is not None and qbits is None:
                pairs = [compression.compress(g) for g in ins]
                ins = [p[0] for p in pairs]
                ctxs = [p[1] for p in pairs]
            dense = C.grouped_allreduce(
                ins, op=op, axis=axis,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                quantized_bits=qbits)
            if ctxs is not None:
                dense = [compression.decompress(r, c)
                         for r, c in zip(dense, ctxs)]
            dense_iter = iter(dense)
            reduced = [
                _sparse_leaf_reduce(g, sparse_rows[i], op, axis,
                                    prescale_factor, postscale_factor)
                if i in sparse_rows else next(dense_iter)
                for i, g in enumerate(leaves)]
        elif mode == "process":
            from horovod_tpu.ops import eager

            handles = [
                eager.allreduce_async(g, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      compression=compression)
                for g in leaves]
            reduced = [eager.synchronize(h) for h in handles]
        else:
            raise ValueError(f"unknown mode {mode!r}")
        return jax.tree_util.tree_unflatten(treedef, reduced), state

    return optax.GradientTransformation(init_fn, update_fn)


class ShardedOptimizerState(NamedTuple):
    """State of :func:`sharded_distributed_update`: the wrapped
    optimizer's state over this rank's flat gradient shards — 1/N of
    the replicated-state footprint per rank.

    ``residuals`` (``error_feedback=True`` only, else None) carries the
    per-group quantization residuals of the low-precision wire — fp32,
    full padded buffer length per group (each rank compensates its own
    pre-reduction contribution, which is full-length)."""

    inner: object
    residuals: Optional[object] = None

    def reset_residuals(self) -> "ShardedOptimizerState":
        """Zeroed-residual copy of this state — the hygiene hook for
        switching the exchange's ``reduction`` operator (or wire codec)
        mid-run (degrade/promote, autotune re-measure): an EF residual
        telescopes against ONE operator's reduction structure, so a
        residual accumulated under sum is pure noise injected into the
        first adasum step (and vice versa).  No-op when error feedback
        is off."""
        if self.residuals is None:
            return self
        return self._replace(
            residuals=jax.tree_util.tree_map(jnp.zeros_like,
                                             self.residuals))


def _static_world(axis: AxisSpec) -> int:
    """World size of ``axis`` as a static int — from the bound mesh
    axes when tracing inside shard_map, else from the runtime mesh
    (init-time use outside the mesh context)."""
    try:
        return int(C.axis_size(axis))
    except Exception:
        pass
    from horovod_tpu.runtime import state as _rt

    if _rt.is_initialized():
        mesh = _rt.global_state().mesh
        names = (axis,) if isinstance(axis, str) else tuple(axis)
        n = 1
        for a in names:
            n *= mesh.shape[a]
        return n
    raise RuntimeError(
        "sharded optimizer needs a bound mesh axis (inside shard_map) "
        "or an initialized runtime to size its shards; call hvd.init() "
        "first")


def _static_axis_sizes(axis: AxisSpec) -> Tuple[int, ...]:
    """Per-axis extents of ``axis``, static — bound mesh axes when
    tracing inside shard_map, else the runtime mesh (the same two
    sources as :func:`_static_world`, kept per-axis so the hierarchy
    decision can see the (dp_outer, dp_inner) factorization)."""
    names = (axis,) if isinstance(axis, str) else tuple(axis)
    try:
        return tuple(int(C.axis_size(a)) for a in names)
    except Exception:
        pass
    from horovod_tpu.runtime import state as _rt

    if _rt.is_initialized():
        mesh = _rt.global_state().mesh
        return tuple(int(mesh.shape[a]) for a in names)
    raise RuntimeError(
        "hierarchy resolution needs a bound mesh axis (inside "
        "shard_map) or an initialized runtime; call hvd.init() first")


def sharded_distributed_update(optimizer: optax.GradientTransformation,
                               op: ReduceOp = Average,
                               axis: AxisSpec = GLOBAL_AXES,
                               prescale_factor: Optional[float] = None,
                               postscale_factor: Optional[float] = None,
                               quantized_bits: Optional[int] = None,
                               bucket_bytes: Optional[int] = None,
                               world: Optional[int] = None,
                               hierarchy: str = "auto",
                               fused_collectives: str = "auto",
                               error_feedback: bool = False,
                               level_codecs: Optional[
                                   Dict[str, Optional[int]]] = None,
                               reduction: Optional[str] = None
                               ) -> optax.GradientTransformation:
    """ZeRO-style sharded rewrite of ``chain(distributed_gradients,
    optimizer)``: reduce-scatter the gradients, run ``optimizer`` on
    this rank's 1/N flat shard only, allgather the resulting updates.

    ``hierarchy`` selects the exchange topology.  ``"flat"`` is the
    single-scope PR-1 exchange over the linearized ``axis`` tuple;
    ``"two_level"`` reduce-scatters within each ICI slice first and
    runs the cross-slice (DCN) phase on the 1/n_inner shards
    (:func:`horovod_tpu.ops.collectives.hierarchical_reducescatter`),
    requiring ``axis`` to name ``(dp_outer, dp_inner)`` mesh axes;
    ``"auto"`` (default) consults the axis factorization and picks
    two_level exactly when both extents exceed 1
    (:func:`horovod_tpu.runtime.topology.resolve_hierarchy`).  With
    ``quantized_bits``, the two-level form scopes the int8 wire codec
    to the DCN hop only — ICI hops stay full precision.

    ``"tree"`` generalizes to the N-level exchange: ``axis`` names the
    mesh axes outermost-first (cluster > pod > slice > chip), phase ℓ
    reduce-scatters the block surviving the inner phases over level
    ℓ's axis (:func:`horovod_tpu.ops.collectives.tree_reducescatter`),
    and ``level_codecs`` (``{axis_name: wire_bits|None}``, the parsed
    ``HOROVOD_EXCHANGE_LEVEL_CODECS`` grammar) places the codec per
    level; without it ``quantized_bits`` rides the outermost hop only,
    exactly the two-level convention.  A 2-axis tree IS two_level and
    a 1-axis tree IS flat — the degeneracies the parity pins hold.

    ``error_feedback=True`` (requires ``quantized_bits``) carries the
    codec's per-group rounding residual in the optimizer state and adds
    it back to the next step's pre-quantization buffer
    (:func:`horovod_tpu.ops.collectives.ef_quantized_reducescatter`),
    telescoping the wire's bias away.  In the flat topology EF wraps
    the single quantized reduce-scatter; in the two-level topology it
    additionally turns ON the ICI-hop codec (``quantize_inner``) — the
    compensated int8/fp8 ICI wire stays numerically pinned to the fp32
    path, which uncompensated quantization there would not.

    Numerically equivalent to allreduce-then-update for *elementwise*
    optimizers (SGD, momentum, Adam/AdamW, RMSProp, …): their update
    of element ``i`` depends only on the gradient/parameter history of
    element ``i``, so sharding the flat buffer commutes with the math
    (pinned by ``tests/test_optimizer.py``).  Transforms that couple
    elements globally (``clip_by_global_norm``, factored second
    moments) would see shard-local statistics — compose those *before*
    this wrapper or keep the replicated path.

    What it buys (the reduce-scatter decomposition of allreduce):

    * optimizer state is shard-sized — 1/N memory per rank;
    * optimizer math runs on 1/N elements — 1/N update FLOPs;
    * the wire carries the same ``2·(N-1)/N·B`` as a ring allreduce,
      but split into two phases XLA can schedule independently —
      reduce-scatter overlapping backward, allgather overlapping the
      shard update — and, with ``bucket_bytes``, further chunked in
      reverse-layer order for earlier overlap (arXiv:2305.06942's
      fused compute-collective argument).

    ``fused_collectives`` (``"auto"|"on"|"off"``,
    ``HOROVOD_FUSED_COLLECTIVES``) enables the tile-granular
    final-bucket exchange: the LAST bucket — whose wire no remaining
    backward work can hide — splits into independent sub-collectives
    the scheduler overlaps with the shard-update math
    (:func:`horovod_tpu.ops.collectives._tiled_psum_scatter`,
    docs/fused_kernels.md).  Numerics are identical; ``"auto"``
    resolves on only on TPU
    (:func:`horovod_tpu.ops.pallas_kernels.resolve_fused_collectives`).

    ``reduction`` selects the exchange's combine operator
    (``"sum"`` | ``"adasum"``; None resolves config >
    ``HOROVOD_EXCHANGE_REDUCTION`` > ``"sum"``).  ``"adasum"`` swaps
    the OUTERMOST topology level's combine for AdaSum adaptive
    summation (arXiv 2006.02924) — plain RS within ICI where replicas
    barely diverge, the adaptive rule on the DCN hop where they
    diverge most — enabling 2-4x larger global batches at the
    small-batch loss trajectory (docs/adasum.md).  Orthogonal to
    hierarchy, codec, and EF; a flat (single-level) topology has no
    outer hop, so adasum there degenerates to the bit-identical plain
    sum.

    ``params`` passed to ``update`` are sliced to matching shards, so
    parameter-coupled rules (weight decay) see co-located values.
    State caveat (shared with the delta-Adasum form): each rank's
    state covers only its shard, so a host read captures rank 0's
    shard — checkpoint/restore of sharded state must go through the
    exchange-aware helpers, not raw rank-0 convention.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("sharded_distributed_update supports "
                         "op=Sum/Average")
    if hierarchy not in TOPOLOGY_MODES:
        raise ValueError(
            f"hierarchy must be one of {TOPOLOGY_MODES}, got "
            f"{hierarchy!r}")
    if error_feedback and quantized_bits is None:
        raise ValueError(
            "error_feedback compensates the quantized wire's rounding; "
            "pass quantized_bits=8 (a wire-reduction compression) to "
            "enable it")
    reduction = C._resolve_reduction(reduction)
    axes_names = (axis,) if isinstance(axis, str) else tuple(axis)
    if hierarchy == "two_level" and len(axes_names) != 2:
        raise ValueError(
            "hierarchy='two_level' needs a 2-axis (dp_outer, dp_inner) "
            f"axis spec, got {axes_names}")
    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives

    fused_tail = resolve_fused_collectives(fused_collectives)

    def _spec(leaves):
        # ``world`` pins the shard sizing when init runs outside any
        # mesh context against a non-runtime mesh (DistributedTrainStep
        # passes its own mesh's size); otherwise derive it
        return C.make_fusion_spec(
            leaves, world if world is not None else _static_world(axis),
            bucket_bytes)

    def init_fn(params):
        leaves = jax.tree_util.tree_leaves(params)
        spec = _spec(leaves)
        template = {g.key: jnp.zeros((g.shard,), jnp.dtype(g.dtype))
                    for g in spec.groups}
        residuals = None
        if error_feedback:
            # full padded length per group: each rank compensates its
            # own pre-reduction contribution (only floating groups ride
            # the quantized wire)
            residuals = {
                g.key: jnp.zeros((g.padded,), jnp.float32)
                for g in spec.groups
                if jnp.issubdtype(jnp.dtype(g.dtype), jnp.floating)}
        return ShardedOptimizerState(inner=optimizer.init(template),
                                     residuals=residuals)

    def update_fn(updates, state, params=None):
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        # resolved at trace time: inside shard_map the axis extents are
        # static, so the branch compiles away and the program contains
        # exactly one exchange topology
        topo = resolve_topology(hierarchy, _static_axis_sizes(axis),
                                axis_names=axes_names,
                                wire_bits=quantized_bits,
                                level_codecs=level_codecs)
        mode = topo.mode
        residuals = state.residuals if error_feedback else None
        if mode == "tree":
            levels = [C.ExchangeLevel(lv.axis_spec, lv.wire_bits)
                      for lv in topo.effective().levels]
            if residuals is not None \
                    and levels[0].quantized_bits is None:
                # EF turns on the innermost codec — the tree twin of
                # quantize_inner (the residual pins that hop)
                levels[0] = C.ExchangeLevel(levels[0].axis,
                                            quantized_bits)
            if residuals is not None:
                shards, spec, residuals = C.tree_reducescatter(
                    leaves, levels, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail,
                    residuals=residuals,
                    reduction=reduction)
            else:
                shards, spec = C.tree_reducescatter(
                    leaves, levels, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail,
                    reduction=reduction)
            # shard ownership is row-major over the levels
            # innermost-FIRST — the N-level generalization of
            # exchange_index_axes
            own_axes = C.tree_index_axes(levels)
        elif mode == "two_level":
            outer, inner_ax = axes_names
            if residuals is not None:
                # EF turns on the ICI codec too — the residual pins it
                shards, spec, residuals = C.hierarchical_reducescatter(
                    leaves, op=op, outer_axis=outer, inner_axis=inner_ax,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    quantized_bits=quantized_bits,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail,
                    quantize_inner=True, inner_residuals=residuals,
                    reduction=reduction)
            else:
                shards, spec = C.hierarchical_reducescatter(
                    leaves, op=op, outer_axis=outer, inner_axis=inner_ax,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    quantized_bits=quantized_bits,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail,
                    reduction=reduction)
            # shard ownership is row-major over (inner, outer) — the
            # param slices and the reassembly must use that linearization
            own_axes = C.exchange_index_axes(outer, inner_ax)
        else:
            if residuals is not None:
                shards, spec, residuals = C.grouped_reducescatter(
                    leaves, op=op, axis=axis,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    quantized_bits=quantized_bits,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail,
                    residuals=residuals)
            else:
                shards, spec = C.grouped_reducescatter(
                    leaves, op=op, axis=axis,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    quantized_bits=quantized_bits,
                    bucket_bytes=bucket_bytes,
                    fused_tail=fused_tail)
            own_axes = axis
        p_shards = None
        if params is not None:
            p_leaves = jax.tree_util.tree_leaves(params)
            p_shards = C.local_fusion_shards(p_leaves, spec,
                                             axis=own_axes)
        upd_shards, inner = optimizer.update(shards, state.inner,
                                             p_shards)
        out = C.grouped_allgather(upd_shards, spec, axis=own_axes)
        return jax.tree_util.tree_unflatten(treedef, out), \
            ShardedOptimizerState(inner=inner,
                                  residuals=residuals
                                  if error_feedback else None)

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         op: ReduceOp = Average,
                         axis: AxisSpec = GLOBAL_AXES,
                         mode: str = "shard_map",
                         compression=None,
                         backward_passes_per_step: int = 1,
                         prescale_factor: Optional[float] = None,
                         postscale_factor: Optional[float] = None,
                         sparse_params: Optional[dict] = None,
                         gradient_predivide_factor: float = 1.0,
                         shard_optimizer_states: bool = False,
                         exchange_bucket_bytes: Optional[int] = None,
                         hierarchy: str = "auto",
                         fused_collectives: str = "auto",
                         error_feedback: bool = False,
                         level_codecs: Optional[
                             Dict[str, Optional[int]]] = None,
                         reduction: Optional[str] = None
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so each update uses cross-replica-reduced
    gradients (reference ``DistributedOptimizer`` factory,
    ``torch/optimizer.py:381``, ``tensorflow/__init__.py:356``).

    ``named_parameters`` is accepted for reference-signature parity (JAX
    pytrees carry structure; names are not needed).
    ``backward_passes_per_step`` accumulates N micro-batch gradients
    locally before one reduction+step — note the reduction lives *inside*
    MultiSteps, so skipped micro-steps do no communication, matching the
    reference's delayed-allreduce semantics (``torch/optimizer.py``
    backward_passes_per_step counting).

    ``shard_optimizer_states=True`` replaces allreduce-then-update with
    the ZeRO-style reduce-scatter → shard-local update → allgather
    exchange (:func:`sharded_distributed_update`): same parameters
    within dtype tolerance, 1/N optimizer memory and update FLOPs per
    rank, and a two-phase wire XLA overlaps with backward.
    ``exchange_bucket_bytes`` chunks that exchange into
    reverse-layer-order buckets for earlier overlap, and ``hierarchy``
    selects its topology — ``"auto"`` (default) runs the two-level
    ICI-then-DCN exchange whenever the dp axes factor into
    ``(dp_outer, dp_inner)`` extents both > 1, ``"flat"``/``"two_level"``
    force a mode (see :func:`sharded_distributed_update`).  Requires
    ``mode='shard_map'`` and an elementwise ``optimizer`` (see the
    sharded transform's docstring).  ``error_feedback=True`` (requires
    a wire-reduction ``compression``) carries the codec's rounding
    residual in the sharded state so the low-precision wire stays
    numerically pinned to the fp32 path (see
    :func:`sharded_distributed_update`).  ``reduction="adasum"`` puts
    the AdaSum combine on the exchange's outermost topology level —
    the large-batch scale-out operator (docs/adasum.md); requires
    ``shard_optimizer_states=True``.
    """
    del named_parameters
    if exchange_bucket_bytes is not None and not shard_optimizer_states:
        raise ValueError(
            "exchange_bucket_bytes buckets the sharded exchange; pass "
            "shard_optimizer_states=True to enable it")
    if hierarchy != "auto" and not shard_optimizer_states:
        raise ValueError(
            "hierarchy selects the sharded exchange topology; pass "
            "shard_optimizer_states=True to enable it")
    if level_codecs is not None and not shard_optimizer_states:
        raise ValueError(
            "level_codecs places wire codecs on the sharded exchange's "
            "tree levels; pass shard_optimizer_states=True to enable it")
    if fused_collectives != "auto" and not shard_optimizer_states:
        raise ValueError(
            "fused_collectives schedules the sharded exchange's final "
            "bucket; pass shard_optimizer_states=True to enable it")
    if reduction not in (None, "sum") and not shard_optimizer_states:
        raise ValueError(
            "reduction selects the sharded exchange's combine operator; "
            "pass shard_optimizer_states=True to enable it (the "
            "replicated path's adasum is DistributedAdasumOptimizer)")
    if shard_optimizer_states:
        if mode != "shard_map":
            raise ValueError(
                "shard_optimizer_states requires mode='shard_map' (the "
                "exchange is explicit per-device code; pjit autodiff "
                "already reduced the gradients densely)")
        if sparse_params:
            raise ValueError(
                "shard_optimizer_states is incompatible with "
                "sparse_params: sparse leaves bypass the fused flat "
                "buffer the shard slicing is defined over")
        if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
            raise ValueError(
                "shard_optimizer_states supports op=Sum/Average")
        qbits = getattr(compression, "wire_reduce_bits", None)
        if compression is not None and qbits is None:
            raise ValueError(
                "shard_optimizer_states supports only wire-reduction "
                "compression (Compression.int8); compressor-style "
                "codecs would decompress before the shard slicing")
    if error_feedback and not shard_optimizer_states:
        raise ValueError(
            "error_feedback carries the sharded exchange's quantization "
            "residual; pass shard_optimizer_states=True to enable it")
    if gradient_predivide_factor != 1.0:
        # reference semantics (torch/optimizer.py:119-123): split the
        # averaging across the sum — grads scale by 1/f before and f/size
        # after (our Average already applies the 1/size)
        if op != Average:
            raise ValueError(
                "gradient_predivide_factor requires op=Average")
        if prescale_factor is not None or postscale_factor is not None:
            raise ValueError(
                "pass either gradient_predivide_factor or explicit "
                "prescale/postscale factors, not both")
        prescale_factor = 1.0 / gradient_predivide_factor
        postscale_factor = gradient_predivide_factor
    if shard_optimizer_states:
        chained = sharded_distributed_update(
            optimizer, op=op, axis=axis,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            quantized_bits=qbits,
            bucket_bytes=exchange_bucket_bytes,
            hierarchy=hierarchy,
            fused_collectives=fused_collectives,
            error_feedback=error_feedback,
            level_codecs=level_codecs,
            reduction=reduction)
        if backward_passes_per_step > 1:
            return optax.MultiSteps(
                chained, every_k_schedule=backward_passes_per_step)
        return chained
    chained = optax.chain(
        distributed_gradients(op=op, axis=axis, mode=mode,
                              compression=compression,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              sparse_params=sparse_params),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


def adasum_updates(axis: AxisSpec = GLOBAL_AXES,
                   mode: str = "shard_map",
                   compression=None) -> optax.GradientTransformation:
    """optax transform that Adasum-reduces *updates* (weight deltas).

    The composable core of :func:`DistributedAdasumOptimizer`: placed
    *after* the local optimizer in an optax chain, it sees exactly the
    per-rank weight delta (optax updates are ``new - old``), which is the
    quantity the Adasum paper reduces.  Per-leaf coefficients match the
    reference's per-layer dot/norm treatment.  A thin, eagerly-validated
    facade over :func:`distributed_gradients` with ``op=Adasum`` — optax
    transforms don't care whether the pytree holds gradients or deltas.
    """

    if mode not in ("shard_map", "process"):
        # pjit's autodiff-inserted mean cannot express the adaptive rule,
        # so there is no identity-transform shortcut the way
        # distributed_gradients has
        raise ValueError(
            f"adasum_updates supports mode='shard_map' or 'process', got "
            f"{mode!r} (Adasum cannot be pjit's implicit mean reduction)")
    return distributed_gradients(op=ReduceOp.ADASUM, axis=axis, mode=mode,
                                 compression=compression)


def DistributedAdasumOptimizer(optimizer: optax.GradientTransformation,
                               named_parameters=None,
                               axis: AxisSpec = GLOBAL_AXES,
                               mode: str = "shard_map",
                               compression=None,
                               backward_passes_per_step: int = 1
                               ) -> optax.GradientTransformation:
    """Adasum in its *delta-optimizer* form (reference
    ``_DistributedAdasumOptimizer``, ``torch/optimizer.py:210-380``;
    TF variant ``tensorflow/__init__.py:334-506``).

    ``op=Adasum`` on raw gradients is only correct for plain SGD: for any
    stateful optimizer (momentum, Adam) the reference instead applies the
    *local* optimizer step first and Adasum-reduces the resulting weight
    delta::

        start  = params                      # stash
        local  = step(optimizer, grads)      # per-rank state update
        delta  = local - start
        params = start + adasum(delta)       # reduce the delta, not grads

    In optax the update returned by ``optimizer.update`` *is* that delta,
    so the whole dance is ``chain(optimizer, adasum_updates(...))`` — the
    reduction moves to the other side of the optimizer compared with
    :func:`DistributedOptimizer`.  Optimizer state (momenta, EMAs) evolves
    from local gradients on every rank, exactly as the reference's
    per-parameter local ``step()`` does.

    Hierarchical dispatch over the (dcn, ici) mesh averages deltas within
    ici and Adasums across dcn (``adasum_gpu_operations.cc:38``).

    Note the state semantics this implies: because momenta evolve from
    *local* gradients, optimizer state is per-rank, not replicated.
    Host reads and checkpoints capture rank 0's (device 0's) state — the
    reference's rank-0-checkpoint convention — and restore follows the
    broadcast-restore pattern (every rank resumes from rank 0's state).
    """
    del named_parameters  # JAX pytrees carry structure; parity-only arg
    chained = optax.chain(
        optimizer,
        adasum_updates(axis=axis, mode=mode, compression=compression),
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


class DistributedGradientTape:
    """Eager-style gradient wrapper (reference ``DistributedGradientTape``,
    ``tensorflow/__init__.py:508-572``).

    Wraps a JAX gradient function; calling ``.gradient`` computes local
    gradients then reduces them across worker processes with overlapped
    async allreduces::

        tape = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = tape.gradient(params, batch)
    """

    def __init__(self, grad_fn, op: ReduceOp = Average, compression=None,
                 prescale_factor: Optional[float] = None,
                 postscale_factor: Optional[float] = None):
        self._grad_fn = grad_fn
        self._op = op
        self._compression = compression
        self._prescale = prescale_factor
        self._postscale = postscale_factor

    def __call__(self, *args, **kwargs):
        return self.gradient(*args, **kwargs)

    def gradient(self, *args, **kwargs):
        from horovod_tpu.ops import eager

        grads = self._grad_fn(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        handles = [
            eager.allreduce_async(g, op=self._op,
                                  compression=self._compression,
                                  prescale_factor=self._prescale,
                                  postscale_factor=self._postscale)
            for g in leaves]
        reduced = [eager.synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, reduced)
