"""DistributedOptimizer / DistributedGradientTape for JAX training.

The reference wraps a framework optimizer so gradients are allreduced
before ``step()``: torch hooks per-parameter grad accumulators and fires
async allreduces as each gradient is produced
(``torch/optimizer.py:103-200``), TF rewrites ``compute_gradients``
(``tensorflow/__init__.py:289-316``), both honoring
``backward_passes_per_step`` accumulation and compression.

optax formulation: gradient averaging is itself a gradient transformation,
so ``DistributedOptimizer(opt)`` = ``chain(distributed_gradients(...),
opt)``, wrapped in ``optax.MultiSteps`` when ``backward_passes_per_step >
1``.  Three reduction modes, because JAX has three distribution idioms:

* ``"shard_map"`` (default): the transform runs inside
  ``shard_map``/``pmap`` with mesh axes bound; gradients are reduced with
  one fused in-graph collective per dtype
  (:func:`horovod_tpu.ops.collectives.grouped_allreduce`) which XLA
  overlaps with backward compute — the role of the reference's
  hook-fired async NCCL calls.
* ``"pjit"``: under global-array pjit the batch axis is sharded and XLA
  already inserts the gradient psum during autodiff; the transform is the
  identity (documented no-op, so user code is portable between modes).
* ``"process"``: host-level eager reduction across worker processes via
  the async-handle API (the closest literal analogue of the reference's
  per-tensor enqueue path).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Union

import jax
import optax

from horovod_tpu.ops import collectives as C
from horovod_tpu.ops.collectives import Average, ReduceOp
from horovod_tpu.runtime.topology import GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


def distributed_gradients(op: ReduceOp = Average,
                          axis: AxisSpec = GLOBAL_AXES,
                          mode: str = "shard_map",
                          compression=None,
                          prescale_factor: Optional[float] = None,
                          postscale_factor: Optional[float] = None
                          ) -> optax.GradientTransformation:
    """optax transform that cross-replica-reduces gradients.

    The composable core of :func:`DistributedOptimizer`; usable standalone
    in any optax chain.
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if mode == "pjit":
            reduced = leaves  # XLA autodiff already reduced (see docstring)
        elif mode == "shard_map":
            ins = leaves
            ctxs = None
            if compression is not None:
                pairs = [compression.compress(g) for g in ins]
                ins = [p[0] for p in pairs]
                ctxs = [p[1] for p in pairs]
            reduced = C.grouped_allreduce(
                ins, op=op, axis=axis,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor)
            if compression is not None:
                reduced = [compression.decompress(r, c)
                           for r, c in zip(reduced, ctxs)]
        elif mode == "process":
            from horovod_tpu.ops import eager

            handles = [
                eager.allreduce_async(g, op=op,
                                      prescale_factor=prescale_factor,
                                      postscale_factor=postscale_factor,
                                      compression=compression)
                for g in leaves]
            reduced = [eager.synchronize(h) for h in handles]
        else:
            raise ValueError(f"unknown mode {mode!r}")
        return jax.tree_util.tree_unflatten(treedef, reduced), state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         named_parameters=None,
                         op: ReduceOp = Average,
                         axis: AxisSpec = GLOBAL_AXES,
                         mode: str = "shard_map",
                         compression=None,
                         backward_passes_per_step: int = 1,
                         prescale_factor: Optional[float] = None,
                         postscale_factor: Optional[float] = None
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer so each update uses cross-replica-reduced
    gradients (reference ``DistributedOptimizer`` factory,
    ``torch/optimizer.py:381``, ``tensorflow/__init__.py:356``).

    ``named_parameters`` is accepted for reference-signature parity (JAX
    pytrees carry structure; names are not needed).
    ``backward_passes_per_step`` accumulates N micro-batch gradients
    locally before one reduction+step — note the reduction lives *inside*
    MultiSteps, so skipped micro-steps do no communication, matching the
    reference's delayed-allreduce semantics (``torch/optimizer.py``
    backward_passes_per_step counting).
    """
    del named_parameters
    chained = optax.chain(
        distributed_gradients(op=op, axis=axis, mode=mode,
                              compression=compression,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor),
        optimizer,
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


def adasum_updates(axis: AxisSpec = GLOBAL_AXES,
                   mode: str = "shard_map",
                   compression=None) -> optax.GradientTransformation:
    """optax transform that Adasum-reduces *updates* (weight deltas).

    The composable core of :func:`DistributedAdasumOptimizer`: placed
    *after* the local optimizer in an optax chain, it sees exactly the
    per-rank weight delta (optax updates are ``new - old``), which is the
    quantity the Adasum paper reduces.  Per-leaf coefficients match the
    reference's per-layer dot/norm treatment.  A thin, eagerly-validated
    facade over :func:`distributed_gradients` with ``op=Adasum`` — optax
    transforms don't care whether the pytree holds gradients or deltas.
    """

    if mode not in ("shard_map", "process"):
        # pjit's autodiff-inserted mean cannot express the adaptive rule,
        # so there is no identity-transform shortcut the way
        # distributed_gradients has
        raise ValueError(
            f"adasum_updates supports mode='shard_map' or 'process', got "
            f"{mode!r} (Adasum cannot be pjit's implicit mean reduction)")
    return distributed_gradients(op=ReduceOp.ADASUM, axis=axis, mode=mode,
                                 compression=compression)


def DistributedAdasumOptimizer(optimizer: optax.GradientTransformation,
                               named_parameters=None,
                               axis: AxisSpec = GLOBAL_AXES,
                               mode: str = "shard_map",
                               compression=None,
                               backward_passes_per_step: int = 1
                               ) -> optax.GradientTransformation:
    """Adasum in its *delta-optimizer* form (reference
    ``_DistributedAdasumOptimizer``, ``torch/optimizer.py:210-380``;
    TF variant ``tensorflow/__init__.py:334-506``).

    ``op=Adasum`` on raw gradients is only correct for plain SGD: for any
    stateful optimizer (momentum, Adam) the reference instead applies the
    *local* optimizer step first and Adasum-reduces the resulting weight
    delta::

        start  = params                      # stash
        local  = step(optimizer, grads)      # per-rank state update
        delta  = local - start
        params = start + adasum(delta)       # reduce the delta, not grads

    In optax the update returned by ``optimizer.update`` *is* that delta,
    so the whole dance is ``chain(optimizer, adasum_updates(...))`` — the
    reduction moves to the other side of the optimizer compared with
    :func:`DistributedOptimizer`.  Optimizer state (momenta, EMAs) evolves
    from local gradients on every rank, exactly as the reference's
    per-parameter local ``step()`` does.

    Hierarchical dispatch over the (dcn, ici) mesh averages deltas within
    ici and Adasums across dcn (``adasum_gpu_operations.cc:38``).
    """
    del named_parameters  # JAX pytrees carry structure; parity-only arg
    chained = optax.chain(
        optimizer,
        adasum_updates(axis=axis, mode=mode, compression=compression),
    )
    if backward_passes_per_step > 1:
        return optax.MultiSteps(chained,
                                every_k_schedule=backward_passes_per_step)
    return chained


class DistributedGradientTape:
    """Eager-style gradient wrapper (reference ``DistributedGradientTape``,
    ``tensorflow/__init__.py:508-572``).

    Wraps a JAX gradient function; calling ``.gradient`` computes local
    gradients then reduces them across worker processes with overlapped
    async allreduces::

        tape = hvd.DistributedGradientTape(jax.grad(loss_fn))
        grads = tape.gradient(params, batch)
    """

    def __init__(self, grad_fn, op: ReduceOp = Average, compression=None,
                 prescale_factor: Optional[float] = None,
                 postscale_factor: Optional[float] = None):
        self._grad_fn = grad_fn
        self._op = op
        self._compression = compression
        self._prescale = prescale_factor
        self._postscale = postscale_factor

    def __call__(self, *args, **kwargs):
        return self.gradient(*args, **kwargs)

    def gradient(self, *args, **kwargs):
        from horovod_tpu.ops import eager

        grads = self._grad_fn(*args, **kwargs)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        handles = [
            eager.allreduce_async(g, op=self._op,
                                  compression=self._compression,
                                  prescale_factor=self._prescale,
                                  postscale_factor=self._postscale)
            for g in leaves]
        reduced = [eager.synchronize(h) for h in handles]
        return jax.tree_util.tree_unflatten(treedef, reduced)
