"""Replica-consistency checksums: silent-data-corruption detection.

A diverged replica is the failure heartbeats cannot see: the process is
alive, beating, making "progress" — on wrong bits.  Every
``HOROVOD_GUARD_CHECK_INTERVAL`` steps each rank fingerprints its
post-allgather parameters (one cheap host reduction over the replicated
view every rank already holds), the scalar fingerprints are gathered
across the data-parallel axis (a few bytes — one tiny collective), and
a majority vote names the diverged rank (docs/guardian.md).

The fingerprint is Fletcher-style over the raw bytes: two 32-bit sums,
one plain and one position-weighted, packed into one int.  The weighted
sum makes the checksum order-sensitive (two swapped elements change
it), and byte-level bitcasting makes any flipped bit — including
NaN-payload bits equality would miss — change the value.

``gather_fn`` is injectable: single-process runs (and the CPU-twin
tests) pass a callable that returns every simulated rank's
fingerprint; the elastic worker wires one over the driver RPC channel.
Without one, a single-process run compares trivially against itself.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from collections import Counter
from typing import Any, Callable, List, Optional

import jax
import numpy as np

from horovod_tpu import telemetry

_MOD32 = np.uint64(0xFFFFFFFF)

_TEL_CHECKS = telemetry.counter(
    "hvd_guard_checks_total", "replica-checksum passes")
_TEL_CHECK_S = telemetry.histogram(
    "hvd_guard_checksum_seconds",
    "wall time of one replica-checksum pass (fingerprint + gather)")
_TEL_DIVERGED = telemetry.gauge(
    "hvd_guard_divergence_rank",
    "rank named by the most recent divergence verdict")


def _leaf_fingerprint(x: Any) -> int:
    a = np.ascontiguousarray(np.asarray(x))
    buf = a.tobytes()
    pad = (-len(buf)) % 4
    if pad:
        buf += b"\x00" * pad
    words = np.frombuffer(buf, np.uint32).astype(np.uint64)
    s1 = int(words.sum() & _MOD32)
    # position-weighted second sum (uint64 wraparound is deterministic):
    # reordered bytes hash differently
    weights = np.arange(1, words.size + 1, dtype=np.uint64)
    s2 = int((words * weights).sum() & _MOD32)
    return (s1 << 32) | s2


def fingerprint(tree: Any) -> int:
    """Order-sensitive 64-bit fingerprint of every array leaf in a
    pytree (non-array leaves hashed by repr).  Equal trees — same
    structure, same bytes — always agree; any flipped bit disagrees."""
    fp = 0
    leaves = jax.tree_util.tree_leaves(tree)
    for leaf in leaves:
        if hasattr(leaf, "shape"):
            h = _leaf_fingerprint(leaf)
        else:
            # builtin hash() is salted per-process (PYTHONHASHSEED) —
            # ranks comparing fingerprints need a stable digest
            h = int.from_bytes(
                hashlib.blake2b(repr(leaf).encode(),
                                digest_size=8).digest(), "big")
        # polynomial mix keeps leaf order significant across the tree
        fp = ((fp * 1000003) ^ h) & 0xFFFFFFFFFFFFFFFF
    return fp


def compare(fps: List[int]) -> List[int]:
    """Majority vote over per-rank fingerprints; returns the ranks that
    disagree with the majority (empty = consistent).  On an exact tie
    the first-seen value wins — deterministic, and with two ranks the
    higher rank is named (rank 0 is the checkpoint writer, so recovery
    treats it as the reference copy)."""
    if len(fps) <= 1:
        return []
    majority = Counter(fps).most_common(1)[0][0]
    return [i for i, f in enumerate(fps) if f != majority]


@dataclasses.dataclass
class DivergenceReport:
    """A detected SDC: who diverged, at which step, from what vote."""

    step: int
    fingerprints: List[int]
    diverged: List[int]

    @property
    def rank(self) -> int:
        """The (first) diverged rank the verdict names."""
        return self.diverged[0]


class ReplicaChecker:
    """Cadenced replica-consistency checker.

    ``interval`` in steps (0 disables); ``gather_fn(fp) -> [fp_rank0,
    ...]`` collects every rank's fingerprint (default: the local one
    alone — trivially consistent single-process)."""

    def __init__(self, interval: int = 10,
                 gather_fn: Optional[Callable[[int], List[int]]] = None):
        self.interval = max(int(interval), 0)
        self._gather = gather_fn
        self.last_report: Optional[DivergenceReport] = None
        self.last_check_s: Optional[float] = None

    def due(self, step: int) -> bool:
        return self.interval > 0 and step > 0 and step % self.interval == 0

    def check(self, step: int, params: Any) -> Optional[DivergenceReport]:
        """One checksum pass (call when :meth:`due`); returns a report
        on divergence, None when every rank agrees."""
        t0 = time.perf_counter()
        fp = fingerprint(params)
        fps = self._gather(fp) if self._gather is not None else [fp]
        self.last_check_s = time.perf_counter() - t0
        _TEL_CHECKS.inc()
        _TEL_CHECK_S.observe(self.last_check_s)
        diverged = compare(list(fps))
        if not diverged:
            self.last_report = None
            return None
        report = DivergenceReport(step=step, fingerprints=list(fps),
                                  diverged=diverged)
        self.last_report = report
        _TEL_DIVERGED.set(report.rank)
        return report
