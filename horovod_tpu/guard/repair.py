"""Peer state repair: restore a diverged-but-alive worker over RPC.

A worker named by a divergence verdict holds poisoned parameters, but
its process, its TPU slice and its driver registration are all fine.
Restarting it through the elastic path (generation bump, rendezvous,
cold checkpoint load) throws that away.  Instead the diverged worker:

1. asks the driver for a healthy peer (:func:`request_healthy_peer` —
   the driver picks a registered, non-suspect worker of another rank);
2. fetches that peer's committed ``(step, state)`` snapshot directly
   over the existing notification channel (:func:`fetch_peer_state` —
   the peer's :class:`WorkerNotificationManager` serves it from the
   provider installed via ``set_state_provider``);
3. adopts it and rejoins the lockstep replay.

Disk is never touched: the healthy peer's in-memory committed state is
newer than (or equal to) the last checkpoint and already verified by
the same checksum vote that caught the divergence.
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

from horovod_tpu import faults
from horovod_tpu.runner.network import (
    BasicClient,
    FetchStateRequest,
    GetHealthyPeerRequest,
    PeerAddressResponse,
    StateSnapshotResponse,
)

logger = logging.getLogger("horovod_tpu.guard")


def _split_addr(addr: str) -> Tuple[str, int]:
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def request_healthy_peer(driver_addr: str, key: bytes, host: str,
                         local_rank: int, rank: int,
                         timeout_s: float = 30.0
                         ) -> Optional[Tuple[str, int]]:
    """Ask the driver for a healthy peer's notification address;
    returns ``(host, port)`` or None when no healthy peer exists."""
    client = BasicClient(_split_addr(driver_addr), key, timeout_s=timeout_s)
    resp = client.request(
        GetHealthyPeerRequest(host=host, local_rank=local_rank, rank=rank))
    if not isinstance(resp, PeerAddressResponse) or resp.address is None:
        return None
    return tuple(resp.address)


def fetch_peer_state(peer_addr: Tuple[str, int], key: bytes,
                     timeout_s: float = 60.0
                     ) -> Optional[Tuple[int, Any]]:
    """Fetch the peer's committed ``(step, state)`` snapshot; returns
    None if the peer has no provider installed (no committed state)."""
    faults.inject("guard.repair")
    client = BasicClient(tuple(peer_addr), key, timeout_s=timeout_s)
    resp = client.request(FetchStateRequest())
    if not isinstance(resp, StateSnapshotResponse) or resp.state is None:
        return None
    return int(resp.step), resp.state


def repair_from_peer(driver_addr: str, key: bytes, host: str,
                     local_rank: int, rank: int,
                     timeout_s: float = 60.0
                     ) -> Optional[Tuple[int, Any]]:
    """Full repair round-trip: locate a healthy peer via the driver,
    then pull its committed snapshot.  Returns ``(step, state)`` to
    adopt, or None when no peer (or no snapshot) is available — the
    caller then falls back to checkpoint rollback."""
    peer = request_healthy_peer(driver_addr, key, host, local_rank, rank,
                                timeout_s=timeout_s)
    if peer is None:
        logger.warning("peer repair: no healthy peer available, "
                       "falling back to checkpoint rollback")
        return None
    snap = fetch_peer_state(peer, key, timeout_s=timeout_s)
    if snap is None:
        logger.warning("peer repair: peer %s had no committed state", peer)
        return None
    logger.info("peer repair: adopted state @ step %d from %s",
                snap[0], peer)
    return snap
