"""Seeded guard-chaos smoke for ``hvdci`` (analysis/ci.py gate 4).

A sub-second, CPU-only, two-replica lockstep simulation of the full
SDC story: a seeded ``corrupt`` fault flips one element of rank 1's
parameters at a known step, the replica-consistency vote names rank 1
within one check interval, rank 0 rolls back to its pinned last-good
checkpoint, rank 1 repairs by adopting rank 0's restored state (the
in-process stand-in for the peer-RPC path in guard/repair.py), and the
replayed trajectory lands bit-identical to a fault-free run — twice,
so determinism itself is gated.

Returns error strings (empty = pass) in the same idiom as
``analysis.metrics_schema`` so ci.py folds it straight into its exit
code.  Budget: well under a second — pure numpy, a tempdir
checkpointer, ~20 simulated steps.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, List

import numpy as np

from horovod_tpu import faults
from horovod_tpu.faults import FaultPlan
from horovod_tpu.guard import checksum
from horovod_tpu.guard.numerics import GuardRollback
from horovod_tpu.guard.rollback import RollbackManager

STEPS = 12
EVERY = 2          # checkpoint_every
INTERVAL = 2       # guard check interval
CORRUPT_AT = 5     # corruption strikes rank 1 at this step
SEED = 1234
RANKS = 2


def _batch(step: int) -> np.ndarray:
    # derived from the global step alone so replay sees identical data
    return np.random.RandomState(SEED + step).rand(4).astype(np.float32)


def _train(w: np.ndarray, batch: np.ndarray) -> np.ndarray:
    return w - 0.1 * (w - batch)


def _fault_free() -> np.ndarray:
    w = np.full((4,), 2.0, np.float32)
    for s in range(1, STEPS + 1):
        w = _train(w, _batch(s))
    return w


def _run_chaos(root: str) -> Dict[str, Any]:
    from horovod_tpu.checkpoint import Checkpointer
    from horovod_tpu.elastic.state import TpuState

    # rank 1's check at step CORRUPT_AT is the 2*CORRUPT_AT-th
    # guard.params hit (two ranks interleave, rank 0 first)
    plan = FaultPlan(seed=SEED).add(
        "guard.params", "corrupt", at=2 * CORRUPT_AT, arg=1.0)
    faults.set_plan(plan)
    try:
        ckpt = Checkpointer(root, use_orbax=False)
        state = TpuState(params={"w": np.full((4,), 2.0, np.float32)},
                         checkpointer=ckpt, checkpoint_every=EVERY)
        rb = RollbackManager(state)
        params = [np.asarray(state.params["w"]).copy()
                  for _ in range(RANKS)]
        checkers = [checksum.ReplicaChecker(INTERVAL) for _ in range(RANKS)]
        detected_at = None
        diverged_rank = None
        replayed = None
        trajectory: List[float] = []

        step = 0
        while step < STEPS:
            step = state._commit_count + 1
            batch = _batch(step)
            params = [_train(w, batch) for w in params]
            state.params = {"w": params[0].copy()}
            state.commit()
            rb.note_commit()
            try:
                for r in range(RANKS):
                    corrupted = faults.inject("guard.params",
                                              value={"w": params[r]})
                    if corrupted is not None:
                        params[r] = corrupted["w"]
                    if checkers[r].due(step):
                        fps = [checksum.fingerprint({"w": w})
                               for w in params]
                        report = checksum.compare(fps)
                        checkers[r].check(step, {"w": params[r]})
                        if report:
                            detected_at = step
                            diverged_rank = report[0]
                            raise GuardRollback("divergence", step=step)
                        rb.note_verified(step)
            except GuardRollback:
                replayed = rb.rollback(reason="divergence")
                restored = np.asarray(state.params["w"]).copy()
                # peer repair: the diverged rank adopts the healthy copy
                params = [restored.copy() for _ in range(RANKS)]
                continue
            trajectory.append(round(float(params[0].sum()), 6))
        state.wait()
        return {"detected_at": detected_at, "diverged_rank": diverged_rank,
                "steps_replayed": replayed, "trajectory": trajectory,
                "final": params[0].copy(),
                "pinned": sorted(ckpt.pinned_steps())}
    finally:
        faults.clear_plan()


def run_smoke() -> List[str]:
    """Run the seeded guard-chaos scenario twice; returns a list of
    error strings (empty = pass)."""
    errors: List[str] = []
    with tempfile.TemporaryDirectory(prefix="hvdguard-smoke-") as d:
        r1 = _run_chaos(os.path.join(d, "a"))
        r2 = _run_chaos(os.path.join(d, "b"))
    if r1["detected_at"] is None:
        errors.append("guard-smoke: corruption was never detected")
        return errors
    if r1["diverged_rank"] != 1:
        errors.append(f"guard-smoke: vote named rank "
                      f"{r1['diverged_rank']}, expected 1")
    if not CORRUPT_AT <= r1["detected_at"] <= CORRUPT_AT + INTERVAL:
        errors.append(f"guard-smoke: detected at step {r1['detected_at']}, "
                      f"outside one check interval of {CORRUPT_AT}")
    if r1["steps_replayed"] is None or \
            not 0 < r1["steps_replayed"] <= EVERY + INTERVAL:
        errors.append(f"guard-smoke: steps_replayed={r1['steps_replayed']} "
                      f"exceeds checkpoint_every+interval={EVERY + INTERVAL}")
    clean = _fault_free()
    if not np.array_equal(r1["final"], clean):
        errors.append("guard-smoke: recovered trajectory differs from the "
                      "fault-free run")
    if r1["detected_at"] != r2["detected_at"] or \
            r1["trajectory"] != r2["trajectory"] or \
            not np.array_equal(r1["final"], r2["final"]):
        errors.append("guard-smoke: two seeded runs were not identical")
    return errors
