"""Preemption grace: turn SIGTERM into a planned, stateless departure.

TPU reservations get reclaimed; the host gets SIGTERM and a short
grace window.  Without handling, the elastic driver sees the same thing
it sees for a crash — a missed heartbeat, then a death verdict, then
host blacklist and quarantine — and the cluster loses capacity it will
get back in minutes.  :class:`PreemptionHandler` converts the signal
into three ordered moves inside the grace window:

1. **drain** — the training loop polls :attr:`draining` and finishes
   the in-flight step instead of being killed mid-allreduce;
2. **commit** — a priority checkpoint commit that bypasses
   ``checkpoint_every`` (``commit_fn``), so zero steps are lost;
3. **notify** — a :class:`PlannedDepartureRequest` to the driver
   (``notify_fn``), which marks the worker departing: the
   HealthMonitor stops counting it toward death verdicts and
   ``record_worker_exit`` skips blacklist/quarantine entirely.

The signal handler itself only sets an event — every heavy action runs
on the training thread via :meth:`finalize`, keeping the handler
async-signal-safe.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Any, Callable, Optional

from horovod_tpu import faults, telemetry

logger = logging.getLogger("horovod_tpu.guard")

_TEL_DRAINS = telemetry.counter(
    "hvd_guard_preempt_drains_total",
    "preemption drains completed (commit + departure notice)")


class PreemptionHandler:
    """SIGTERM → drain → priority commit → planned-departure notice."""

    def __init__(self, commit_fn: Callable[[], Any],
                 notify_fn: Optional[Callable[[], Any]] = None,
                 signum: int = signal.SIGTERM):
        self._commit_fn = commit_fn
        self._notify_fn = notify_fn
        self._signum = signum
        self._event = threading.Event()
        self._prev_handler = None
        self._installed = False
        self.finalized = False

    def install(self) -> "PreemptionHandler":
        """Install the signal handler (main thread only, per the signal
        module's contract); returns self for chaining."""
        self._prev_handler = signal.signal(self._signum, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            signal.signal(self._signum, self._prev_handler or signal.SIG_DFL)
            self._installed = False

    def _on_signal(self, signum, frame) -> None:
        # async-signal-safe: set the flag, do nothing else
        self._event.set()

    def request(self) -> None:
        """Programmatic preemption (tests, cloud-metadata watchers)."""
        self._event.set()

    @property
    def draining(self) -> bool:
        """True once preemption was requested — the loop should finish
        the in-flight step and call :meth:`finalize`."""
        return self._event.is_set()

    def finalize(self) -> bool:
        """Run the grace sequence (idempotent): priority commit, then
        the planned-departure notice.  Returns True if it ran."""
        if not self._event.is_set() or self.finalized:
            return False
        self.finalized = True
        faults.inject("worker.preempt")
        logger.info("preemption drain: committing priority checkpoint")
        self._commit_fn()
        if self._notify_fn is not None:
            try:
                self._notify_fn()
            except Exception:
                # the departure notice is best-effort: a dead driver
                # must not stop the checkpoint from landing
                logger.warning("planned-departure notice failed",
                               exc_info=True)
        _TEL_DRAINS.inc()
        return True
