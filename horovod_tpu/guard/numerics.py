"""Numerics guardian: NaN/Inf and grad-norm-spike detection.

The in-graph half lives in ``DistributedTrainStep`` (``guard=`` kwarg):
when a guard is attached, the compiled step takes one extra traced
scalar — the spike *limit* — computes the global gradient norm, and
where-selects the pre-step ``(params, opt_state)`` whenever the norm is
non-finite or above the limit.  Because the select happens inside the
XLA program, a poisoned update is never applied, even with donated
buffers, and the limit is a runtime value so per-step threshold changes
never recompile.

This module is the host half: :class:`NumericsGuardian` keeps an EMA
baseline of the *log* gradient norm (mean and variance), hands the step
its current limit (``exp(mean + zscore·std)``; ``inf`` during warmup),
and turns each observed norm into a verdict + policy reaction:

``skip_step``
    the in-graph select already kept the old state — count it and move
    on (the reference world's "skip this batch" loss-scaling idiom);
``rollback``
    raise :class:`GuardRollback` so the training loop restores the
    last-good checkpoint and replays (docs/guardian.md);
``abort``
    raise :class:`GuardAbort` — stop the run, preserving the anomaly
    for a human.

Everything here is plain host float math: zero device traffic beyond
the one scalar the step already returns.
"""

from __future__ import annotations

import math
from typing import Optional

from horovod_tpu import telemetry

POLICIES = ("skip_step", "rollback", "abort")

# variance floor on the log-norm scale: a perfectly flat norm history
# must not make the limit collapse onto the mean (z·0.05 ≈ a 35% head
# room at the default z=6 — far below any real spike, far above noise)
_MIN_LOG_STD = 0.05
_LOG_EPS = 1e-30

_TEL_ANOMALIES = telemetry.counter(
    "hvd_guard_anomalies_total",
    "guardian anomaly verdicts by kind (nonfinite|spike|divergence)")
_TEL_SKIPPED = telemetry.counter(
    "hvd_guard_skipped_steps_total",
    "optimizer steps suppressed by the in-graph guard select")
_TEL_GNORM = telemetry.gauge(
    "hvd_guard_grad_norm", "most recent guarded global gradient norm")


class GuardAnomaly(Exception):
    """Base of the guardian's policy exceptions."""

    def __init__(self, kind: str, step: Optional[int] = None,
                 detail: str = ""):
        msg = f"guard anomaly: {kind}"
        if step is not None:
            msg += f" at step {step}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.kind = kind
        self.step = step
        self.detail = detail


class GuardRollback(GuardAnomaly):
    """Policy ``rollback``: restore the last-good checkpoint in place
    and replay — the catcher calls :meth:`TrainingGuard.rollback`."""


class GuardAbort(GuardAnomaly):
    """Policy ``abort``: stop the run, state preserved for diagnosis."""


class NumericsGuardian:
    """EMA z-score spike detector over the log gradient norm."""

    def __init__(self, policy: str = "rollback", zscore: float = 6.0,
                 warmup_steps: int = 10, ema: float = 0.99):
        if policy not in POLICIES:
            raise ValueError(f"guard policy must be one of {POLICIES}, "
                             f"got {policy!r}")
        if not 0.0 < ema < 1.0:
            raise ValueError(f"guard ema must be in (0, 1), got {ema}")
        self.policy = policy
        self.zscore = float(zscore)
        self.warmup_steps = max(int(warmup_steps), 1)
        self.ema = float(ema)
        # EMA of log-norm mean and second moment, with the usual
        # (1 - ema^n) bias correction so early estimates aren't pulled
        # toward the zero init
        self._m1 = 0.0
        self._m2 = 0.0
        self._n = 0
        self.last_verdict: Optional[str] = None
        self.last_gnorm: Optional[float] = None
        self.anomalies = 0

    @property
    def observed_steps(self) -> int:
        return self._n

    def _stats(self):
        corr = 1.0 - self.ema ** self._n
        mean = self._m1 / corr
        var = max(self._m2 / corr - mean * mean, 0.0)
        return mean, max(math.sqrt(var), _MIN_LOG_STD)

    def current_limit(self) -> float:
        """The spike threshold for the NEXT step — ``inf`` while the
        baseline warms up (nonfinite detection is always armed: the
        in-graph predicate checks ``isfinite`` regardless of limit)."""
        if self._n < self.warmup_steps:
            return math.inf
        mean, std = self._stats()
        return math.exp(mean + self.zscore * std)

    def observe(self, gnorm: float,
                limit: Optional[float] = None) -> str:
        """Record one step's gradient norm against the limit the step
        actually ran with; returns the verdict and applies the policy
        (may raise :class:`GuardRollback` / :class:`GuardAbort`)."""
        if limit is None:
            limit = self.current_limit()
        self.last_gnorm = gnorm
        if telemetry.enabled() and math.isfinite(gnorm):
            _TEL_GNORM.set(gnorm)
        if not math.isfinite(gnorm):
            verdict = "nonfinite"
        elif gnorm > limit:
            verdict = "spike"
        else:
            verdict = "ok"
        self.last_verdict = verdict
        if verdict == "ok":
            # baseline updates on clean steps only: an anomaly must not
            # poison the statistics it is judged against
            ln = math.log(max(gnorm, _LOG_EPS))
            self._m1 = self.ema * self._m1 + (1.0 - self.ema) * ln
            self._m2 = self.ema * self._m2 + (1.0 - self.ema) * ln * ln
            self._n += 1
            return verdict
        self.anomalies += 1
        _TEL_ANOMALIES.inc(kind=verdict)
        if self.policy == "abort":
            raise GuardAbort(verdict, detail=f"grad_norm={gnorm!r}")
        if self.policy == "rollback":
            raise GuardRollback(verdict, detail=f"grad_norm={gnorm!r}")
        _TEL_SKIPPED.inc()    # skip_step: the in-graph select did the work
        return verdict
