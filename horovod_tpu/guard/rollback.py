"""Rollback-and-replay: in-place restore to the last *verified* step.

The elastic plane already knows how to restore a checkpoint — but it
does so by bumping the generation and re-running rendezvous, because its
trigger is a dead worker.  A guardian anomaly is different: every worker
is alive, one of them just computed garbage.  :class:`RollbackManager`
restores the last-good checkpoint *in place* — same generation, same
assignment, no rendezvous — rewinds the :class:`ShardedDataset` to the
exact global sample position that checkpoint was cut at, and lets the
loop replay.  Replayed training is bit-deterministic (seeded shards,
seeded faults), so the recovered trajectory equals a fault-free run.

"Last good" is stronger than "last written": a checkpoint taken *after*
a silent corruption is itself poisoned.  A checkpoint is promoted to
last-good only once a replica-consistency check newer than it passes,
and the promoted step is pinned against the checkpointer's GC
(:meth:`Checkpointer.pin`) so retention can never reap the one rollback
target that matters.

Replay bound: corruption strikes in ``(c0, c1]`` between two checks; the
newest checkpoint at or before the clean check ``c0`` is clean, so
``steps_replayed ≤ checkpoint_every + check_interval``.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

from horovod_tpu import telemetry

logger = logging.getLogger("horovod_tpu.guard")

_TEL_ROLLBACKS = telemetry.counter(
    "hvd_guard_rollbacks_total", "guardian rollback-and-replay events")
_TEL_REPLAYED = telemetry.gauge(
    "hvd_guard_steps_replayed",
    "steps between the anomaly and the restored checkpoint")
_TEL_LAST_GOOD = telemetry.gauge(
    "hvd_guard_last_good_step", "newest verified (pinned) checkpoint step")


class RollbackManager:
    """Tracks verified checkpoints for a :class:`TpuState` and performs
    in-place rollback.

    ``dataset_state_fn(step)`` (optional) returns the dataset position
    state (:meth:`ShardedDataset.state_dict`) as of ``step``'s commit;
    it is captured at checkpoint time and surfaced again on rollback so
    the loop can ``load_position`` back to the exact sample.
    """

    def __init__(self, state: Any,
                 dataset_state_fn: Optional[Callable[[int], Any]] = None):
        self._state = state
        self._dataset_state_fn = dataset_state_fn
        self._positions: Dict[int, Any] = {}
        self._last_checkpoint: Optional[int] = None
        self._last_good: Optional[int] = None
        self.last_data_position: Optional[Any] = None
        self.rollbacks = 0

    @property
    def last_good_step(self) -> Optional[int]:
        return self._last_good

    @property
    def last_checkpoint_step(self) -> Optional[int]:
        return self._last_checkpoint

    def note_commit(self) -> None:
        """Call right after ``state.commit()``: records whether this
        commit cut a checkpoint, and at which dataset position."""
        state = self._state
        step = state._commit_count
        every = max(getattr(state, "_checkpoint_every", 1), 1)
        if getattr(state, "_checkpointer", None) is None:
            return
        if step % every != 0:
            return
        self._last_checkpoint = step
        if self._dataset_state_fn is not None:
            self._positions[step] = self._dataset_state_fn(step)

    def note_verified(self, step: int) -> None:
        """A replica-consistency check at ``step`` passed: every
        checkpoint at or before it is clean — promote the newest."""
        cand = self._last_checkpoint
        if cand is None or cand > step:
            return
        if self._last_good == cand:
            return
        prev = self._last_good
        ckpt = getattr(self._state, "_checkpointer", None)
        if ckpt is not None:
            ckpt.pin(cand)
            if prev is not None:
                ckpt.unpin(prev)
        self._last_good = cand
        _TEL_LAST_GOOD.set(cand)
        # positions older than the rollback target can never be needed
        for s in [s for s in self._positions if s < cand]:
            del self._positions[s]

    def rollback(self, reason: str = "anomaly") -> int:
        """Restore the last-good checkpoint in place; returns the number
        of steps the loop must replay.  ``last_data_position`` afterward
        holds the dataset state to ``load_position`` (None if no
        ``dataset_state_fn`` was wired)."""
        target = self._last_good
        if target is None:
            # no verified checkpoint yet (anomaly inside the first check
            # window): the newest checkpoint predates any detected
            # corruption and is the best available target
            target = self._last_checkpoint
        if target is None:
            raise RuntimeError(
                "guard rollback requested but no checkpoint has been "
                "written yet — is checkpointing enabled?")
        before = self._state._commit_count
        self._state.restore_from_checkpoint(step=target)
        self.last_data_position = self._positions.get(target)
        replayed = before - target
        self.rollbacks += 1
        _TEL_ROLLBACKS.inc(reason=reason)
        _TEL_REPLAYED.set(replayed)
        logger.warning(
            "guard rollback (%s): step %d -> %d, replaying %d steps",
            reason, before, target, replayed)
        return replayed
