"""hvdguard: the training-state integrity plane (docs/guardian.md).

The elastic runtime (docs/elastic.md) recovers from failures that
announce themselves — dead processes, missed heartbeats, raised
exceptions.  This package covers the failures that don't:

**numerics guardian** (:mod:`~horovod_tpu.guard.numerics`)
    per-step NaN/Inf + grad-norm-spike detection, enforced *inside*
    the compiled step (``DistributedTrainStep(guard=...)``) so a
    poisoned update is never applied even with donated buffers;
    policy: ``skip_step`` | ``rollback`` | ``abort``.

**replica-consistency checksums** (:mod:`~horovod_tpu.guard.checksum`)
    every ``HOROVOD_GUARD_CHECK_INTERVAL`` steps, a per-rank parameter
    fingerprint and a majority vote across the data-parallel axis —
    silent data corruption detected and *attributed* to a rank.

**rollback-and-replay** (:mod:`~horovod_tpu.guard.rollback`)
    in-place restore of the pinned last-*verified* checkpoint (no
    elastic generation bump), dataset rewound to the exact global
    sample, replay bit-identical to a fault-free run; a diverged-but-
    alive worker instead repairs from a healthy peer over RPC
    (:mod:`~horovod_tpu.guard.repair`).

**preemption grace** (:mod:`~horovod_tpu.guard.preempt`)
    SIGTERM → drain the in-flight step → priority checkpoint commit →
    planned-departure notice, so the driver skips quarantine and the
    HealthMonitor never counts the departure as a death.

Everything is opt-in behind ``HOROVOD_GUARD_*`` knobs (docs/running.md)
and free when off: the module-level :func:`check` hook is a single
``None`` test (same contract as ``faults.inject``), pinned < 5µs by
tier-1.  All signals flow through the hvdtel registry as
``hvd_guard_*`` series (docs/metrics.md).

Typical wiring::

    guard = hvd.guard.TrainingGuard.from_config(cfg, state=state)
    step = hvd.DistributedTrainStep(loss_fn, opt, mesh=mesh, guard=guard)
    ...
    try:
        params, opt_state, loss = step(params, opt_state, batch)
        state.commit(); guard.note_commit()
        params = guard.check_replicas(state._commit_count, params)
    except hvd.guard.GuardRollback:
        replayed = guard.rollback()
        # restore params/opt_state from state, rewind the dataset, replay
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

from horovod_tpu import faults
from horovod_tpu.guard.checksum import (
    DivergenceReport,
    ReplicaChecker,
    compare,
    fingerprint,
)
from horovod_tpu.guard.numerics import (
    _TEL_ANOMALIES,
    POLICIES,
    GuardAbort,
    GuardAnomaly,
    GuardRollback,
    NumericsGuardian,
)
from horovod_tpu.guard.preempt import PreemptionHandler
from horovod_tpu.guard.repair import repair_from_peer
from horovod_tpu.guard.rollback import RollbackManager

__all__ = [
    "DivergenceReport",
    "GuardAbort",
    "GuardAnomaly",
    "GuardRollback",
    "NumericsGuardian",
    "POLICIES",
    "PreemptionHandler",
    "ReplicaChecker",
    "RollbackManager",
    "TrainingGuard",
    "active_guard",
    "check",
    "clear_guard",
    "compare",
    "fingerprint",
    "repair_from_peer",
    "set_guard",
]


class TrainingGuard:
    """Composes the numerics guardian, the replica checker and (when a
    :class:`TpuState` is wired) the rollback manager into the one
    object the training loop talks to."""

    def __init__(self, policy: str = "rollback", check_interval: int = 10,
                 zscore: float = 6.0, warmup_steps: int = 10,
                 ema: float = 0.99,
                 gather_fn: Optional[Callable[[int], List[int]]] = None,
                 rollback: Optional[RollbackManager] = None):
        self.numerics = NumericsGuardian(policy=policy, zscore=zscore,
                                         warmup_steps=warmup_steps, ema=ema)
        self.checker = ReplicaChecker(check_interval, gather_fn)
        self.rollback_mgr = rollback

    @classmethod
    def from_config(cls, cfg: Any, gather_fn=None, state: Any = None,
                    dataset_state_fn=None) -> Optional["TrainingGuard"]:
        """Build from a :class:`runtime.Config`; returns None when
        ``HOROVOD_GUARD`` is off.  Passing ``state`` (a TpuState with a
        checkpointer) arms rollback-and-replay."""
        if not getattr(cfg, "guard_enabled", False):
            return None
        rb = None
        if state is not None:
            rb = RollbackManager(state, dataset_state_fn=dataset_state_fn)
        return cls(policy=cfg.guard_policy,
                   check_interval=cfg.guard_check_interval,
                   zscore=cfg.guard_zscore,
                   warmup_steps=cfg.guard_warmup_steps,
                   ema=cfg.guard_ema, gather_fn=gather_fn, rollback=rb)

    @property
    def policy(self) -> str:
        return self.numerics.policy

    # -- numerics guardian (DistributedTrainStep talks to these) -------

    def current_limit(self) -> float:
        return self.numerics.current_limit()

    def observe(self, gnorm: float, limit: Optional[float] = None) -> str:
        return self.numerics.observe(gnorm, limit=limit)

    # -- replica consistency -------------------------------------------

    def check_replicas(self, step: int, params: Any) -> Any:
        """Run the guard's chaos sites and, when the cadence is due, a
        replica-consistency vote.  Returns ``params`` (replaced by the
        ``corrupt`` action's perturbed copy when a chaos plan fires —
        the SDC injection point).  Raises :class:`GuardRollback` /
        :class:`GuardAbort` on a divergence verdict; divergence cannot
        be skipped — a diverged replica never rejoins by itself."""
        faults.inject("guard.check")
        corrupted = faults.inject("guard.params", value=params)
        if corrupted is not None:
            params = corrupted
        if self.checker.due(step):
            report = self.checker.check(step, params)
            if report is not None:
                _TEL_ANOMALIES.inc(kind="divergence")
                detail = f"rank {report.rank} diverged " \
                         f"(vote {report.fingerprints})"
                if self.policy == "abort":
                    raise GuardAbort("divergence", step=step, detail=detail)
                raise GuardRollback("divergence", step=step, detail=detail)
            if self.rollback_mgr is not None:
                self.rollback_mgr.note_verified(step)
        return params

    # -- rollback plumbing ---------------------------------------------

    def note_commit(self) -> None:
        if self.rollback_mgr is not None:
            self.rollback_mgr.note_commit()

    def rollback(self, reason: str = "anomaly") -> int:
        if self.rollback_mgr is None:
            raise RuntimeError("no RollbackManager wired — construct the "
                               "guard with rollback= or from_config(state=)")
        return self.rollback_mgr.rollback(reason=reason)


# -- module-level hook (mirrors faults.inject's zero-cost contract) ----

_active: Optional[TrainingGuard] = None


def set_guard(guard: Optional[TrainingGuard]) -> Optional[TrainingGuard]:
    """Install the process-wide guard (None to disarm); returns it."""
    global _active
    _active = guard
    return guard


def clear_guard() -> None:
    set_guard(None)


def active_guard() -> Optional[TrainingGuard]:
    return _active


def check(step: int, params: Any = None) -> Any:
    """Hot-loop hook: no-op (one global ``None`` test — pinned < 5µs)
    until :func:`set_guard` arms it, then
    :meth:`TrainingGuard.check_replicas`."""
    if _active is None:
        return None
    return _active.check_replicas(step, params)
