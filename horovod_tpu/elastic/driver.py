"""The elastic driver: orchestrates a dynamic worker set.

Reference: ``horovod/runner/elastic/driver.py`` — periodic host
discovery (1 s), rank-stable reassignment on host changes, host
blacklisting on worker failure, worker notification, and the rendezvous
workers query for their new identity after a reset.  The TPU twist: each
world generation gets a fresh ``jax.distributed`` coordinator address
(XLA's world is static per generation), handed out through the same
rendezvous RPC.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.elastic.discovery import HostManager, HostUpdateResult
from horovod_tpu.elastic.health import HealthMonitor
from horovod_tpu.elastic.registration import WorkerStateRegistry
from horovod_tpu.runner.hosts import HostInfo, SlotInfo, get_host_assignments
from horovod_tpu.runner.network import (
    AckResponse,
    BasicService,
    GetHealthyPeerRequest,
    HeartbeatRequest,
    PeerAddressResponse,
    PlannedDepartureRequest,
    RegisterWorkerRequest,
    WorkerReadyRequest,
    notify_hosts_updated,
)
from horovod_tpu.utils import logging as hvd_logging

DISCOVER_INTERVAL_S = 1.0    # reference driver.py:30
START_TIMEOUT_S = 120.0      # worker must report READY within this window


class GetRankAndSizeRequest:
    """Worker → driver: my (host, local_rank); give me my current
    assignment (reference ``ElasticRendezvousHandler`` GET rank_and_size)."""

    def __init__(self, host: str, local_rank: int, generation: int = -1):
        self.host = host
        self.local_rank = local_rank
        self.generation = generation


class RankAndSizeResponse:
    def __init__(self, slot: Optional[SlotInfo], coordinator_addr: str,
                 generation: int, plan: Optional[str] = None):
        self.slot = slot
        self.coordinator_addr = coordinator_addr
        self.generation = generation
        # the parallelism plan of this generation's world (canonical
        # HOROVOD_PLAN string) when a degrade controller is attached:
        # a worker rejoining after a degrade/promote transition must
        # rebuild its mesh for the CURRENT plan, not the one it was
        # launched with (elastic/degrade.py)
        self.plan = plan


class ElasticDriver:
    def __init__(self, discovery, min_np: int, max_np: Optional[int] = None,
                 timeout: float = 600.0, reset_limit: int = 0,
                 secret_key: Optional[str] = None,
                 start_timeout: float = START_TIMEOUT_S):
        self._host_manager = HostManager(discovery)
        self._registry = WorkerStateRegistry(self, self._host_manager,
                                             reset_limit=reset_limit)
        self._min_np = min_np
        self._max_np = max_np
        self._timeout = timeout
        self._start_timeout = start_timeout
        self._secret_key = secret_key

        self._lock = threading.RLock()
        self._assignments: Dict[Tuple[str, int], SlotInfo] = {}
        self._abort_events: Dict[Tuple[str, int], threading.Event] = {}
        # per-spawn token so a startup watchdog armed for an earlier
        # spawn of the same (host, local_rank) slot cannot fail a newer
        # worker that reuses the key (see _check_started)
        self._spawn_tokens: Dict[Tuple[str, int], int] = {}
        # workers that asked for a generation newer than the current one
        # (worker-initiated re-rendezvous, see _handle)
        self._regen_requests: set = set()
        self._generation = 0
        # recovery observability: wall-clock from each generation's
        # assignment to every assigned worker reporting READY — the
        # number the warm-start compile cache is meant to collapse from
        # ~full-compile (42-51 s per flagship model) to seconds
        self._generation_started: float = time.monotonic()
        self._generation_ready_logged = -1
        self.last_recovery_s: Optional[float] = None
        # heartbeat health plane: workers beat over the driver RPC
        # channel; the monitor declares a silent worker dead (and a
        # beating-but-stuck one hung) BEFORE its process exit is
        # observed, so regeneration starts detect_s after the failure
        # instead of whenever the launcher thread notices the exit
        self._health = HealthMonitor.from_env(self._on_worker_dead)
        self.last_detect_s: Optional[float] = None
        self.last_detect_reason: Optional[str] = None
        # structured per-generation recovery record (docs/metrics.md):
        # what the recovery_s/detect_s log lines said, as data — appended
        # when a generation reaches fully-READY, mirrored into the
        # registry as generation-labeled gauges
        self._generation_history: List[dict] = []
        self._step_at_detect: Optional[int] = None
        # per-worker counter snapshots off the heartbeat piggyback; the
        # driver's Prometheus endpoint serves them worker-labeled
        self._worker_metrics = telemetry.worker_store()
        self._worker_fn_takes_abort = True
        self._coordinator_addr = ""
        # Driver-hosted per-generation coordination services.  Old
        # generations are retired, NOT shut down, until job completion: a
        # coordination service dying while any worker's client is still
        # attached terminates that worker from a C++ poll thread
        # (jaxlib's missed-heartbeat path raises std::bad_cast before the
        # Python callback can run), so every service must outlive the
        # last client that may still detach from it.
        self._coord_services: List = []
        self._worker_notify_addrs: Dict[int, Tuple[str, int]] = {}
        # (host, local_rank) keys that announced a preemption-grace
        # departure (guard/preempt.py): their exit — any code — is
        # graceful, so no blacklist, no quarantine, no sibling abort
        self._planned_departures: set = set()
        # plan-aware graceful degradation (elastic/degrade.py): when a
        # DegradeController is attached, a world-size change re-resolves
        # the ShardingPlan to the survivors instead of blocking on full
        # capacity, and promotion grows it back when hosts return
        self._degrade = None
        self._create_worker_fn: Optional[Callable] = None
        self._shutdown = threading.Event()
        self._resume_lock = threading.Lock()   # serialize concurrent resumes
        self._hosts_avail = threading.Event()
        self._exit_code: Optional[int] = None
        self._finished = threading.Event()

        self._service = BasicService("elastic_driver", secret_key,
                                     self._handle, host="0.0.0.0")
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, daemon=True,
            name="hvd_tpu_elastic_discovery")

    # -- service plumbing ---------------------------------------------------

    @property
    def registry(self) -> WorkerStateRegistry:
        return self._registry

    @property
    def host_manager(self) -> HostManager:
        return self._host_manager

    @property
    def address(self) -> Tuple[str, int]:
        return self._service.address

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def degrade_controller(self):
        return self._degrade

    def set_degrade_controller(self, controller) -> None:
        """Attach a :class:`~horovod_tpu.elastic.degrade.
        DegradeController`: reassignment consults it for the plan the
        surviving world should run, ``resume`` waits only for its
        minimum world (the model extent) instead of ``min_np``, and
        workers receive the current plan with their assignment."""
        with self._lock:
            self._degrade = controller

    def _plan_string(self) -> Optional[str]:
        ctl = self._degrade
        return None if ctl is None else ctl.current_plan.to_string()

    @property
    def health_monitor(self) -> HealthMonitor:
        return self._health

    @property
    def generation_history(self) -> List[dict]:
        """Per-generation recovery records (newest last): generation,
        worker count, ``recovery_s`` (assignment → all-READY),
        ``detect_s``/``detect_reason`` when a health-plane verdict
        triggered the generation, ``step_at_detect`` (the pre-failure
        training peak the monitor saw) and best-effort ``steps_lost``
        (peak minus the highest step reported by the new generation at
        ready time; None until a worker reports)."""
        with self._lock:
            return [dict(e) for e in self._generation_history]

    def _handle(self, req):
        if isinstance(req, RegisterWorkerRequest):
            with self._lock:
                self._worker_notify_addrs[req.rank] = tuple(req.address)
            return AckResponse()
        if isinstance(req, HeartbeatRequest):
            self._health.record_heartbeat(req.host, req.local_rank,
                                          getattr(req, "step", -1))
            metrics = getattr(req, "metrics", None)
            if metrics:
                # rank-registry aggregation rides the beat the way the
                # step counter does — no extra RPC (docs/metrics.md)
                self._worker_metrics.update(
                    f"{req.host}:{req.local_rank}", metrics)
            return AckResponse()
        if isinstance(req, WorkerReadyRequest):
            self._registry.record_ready(req.host, req.local_rank)
            self._check_generation_ready()
            return AckResponse()
        if isinstance(req, PlannedDepartureRequest):
            self.announce_departure(req.host, req.local_rank,
                                    step=getattr(req, "step", -1))
            return AckResponse()
        if isinstance(req, GetHealthyPeerRequest):
            # peer repair (guard/repair.py): hand the diverged worker a
            # healthy peer's notification address.  Healthy = currently
            # assigned to a different rank, registered a notification
            # service, not departing; prefer rank 0 (the checkpoint
            # writer — its copy is the recovery reference).
            with self._lock:
                rank_of = {s.rank: k for k, s in self._assignments.items()}
                candidates = []
                for rank in sorted(self._worker_notify_addrs):
                    if rank == req.rank or rank not in rank_of:
                        continue
                    key = rank_of[rank]
                    if key in self._planned_departures:
                        continue
                    candidates.append(
                        (rank, self._worker_notify_addrs[rank]))
            for rank, addr in candidates:
                if not self._health.is_departing(*rank_of[rank]):
                    return PeerAddressResponse(rank=rank,
                                               address=tuple(addr))
            return PeerAddressResponse()
        if isinstance(req, GetRankAndSizeRequest):
            with self._lock:
                slot = self._assignments.get((req.host, req.local_rank))
                if slot is not None and req.generation >= self._generation:
                    # Worker-initiated re-rendezvous: the worker already
                    # has the current generation but needs a newer one —
                    # its collectives failed without anything the driver
                    # can observe (e.g. a cross-rank signature mismatch
                    # raised on every rank at once).  When every assigned
                    # worker asks, regenerate: new generation + fresh
                    # coordinator, same assignments.  This is the
                    # reference's rendezvous-round advance: workers
                    # re-registering IS the signal for a new round.
                    self._regen_requests.add((req.host, req.local_rank))
                    if self._regen_requests >= set(self._assignments):
                        hvd_logging.info(
                            "elastic: all %d workers requested a new "
                            "generation — re-rendezvousing",
                            len(self._assignments))
                        self._update_host_assignments()
                    slot = self._assignments.get((req.host, req.local_rank))
                resp = RankAndSizeResponse(slot, self._coordinator_addr,
                                           self._generation,
                                           plan=self._plan_string())
            if slot is not None:
                # a worker fetching its assignment has a live control loop
                # — the reference records READY at the rendezvous GET
                # (``elastic/rendezvous.py`` → driver.record_ready)
                self._registry.record_ready(req.host, req.local_rank)
                self._check_generation_ready()
            return resp
        raise ValueError(f"unexpected request {type(req).__name__}")

    def _check_generation_ready(self) -> None:
        """Log (once per generation) the assignment→all-READY latency:
        ``recovery_s`` is the operational cost of a world change, the
        quantity the persistent compile cache takes off restarts."""
        from horovod_tpu.elastic.registration import READY, SUCCESS

        # registry state is read OUTSIDE the driver lock: the registry's
        # failure path calls driver.stop() while holding its own lock,
        # so holding ours while taking its would invert the order
        with self._lock:
            if self._generation_ready_logged >= self._generation \
                    or not self._assignments:
                return
            gen = self._generation
            keys = list(self._assignments)
            started = self._generation_started
        if not all(self._registry.get_state(h, lr) in (READY, SUCCESS)
                   for (h, lr) in keys):
            return
        # read the post-recovery training peak BEFORE taking our lock
        # (the monitor has its own lock; keep the acquisition one-way)
        step_now = self._health.max_step()
        with self._lock:
            if gen != self._generation \
                    or self._generation_ready_logged >= gen:
                return      # a newer generation superseded this reading
            self._generation_ready_logged = gen
            # log the local, not the attribute: a ready-check for a newer
            # generation may overwrite last_recovery_s before the log runs
            recovery_s = time.monotonic() - started
            self.last_recovery_s = recovery_s
            detect_s = self.last_detect_s
            detect_reason = self.last_detect_reason
            step_at_detect = self._step_at_detect
            self.last_detect_s = None        # consumed by this generation
            self._step_at_detect = None
            entry = {
                "generation": gen,
                "workers": len(keys),
                "recovery_s": round(recovery_s, 4),
                "detect_s": None if detect_s is None
                else round(detect_s, 4),
                "detect_reason": detect_reason if detect_s is not None
                else None,
                "step_at_detect": step_at_detect,
                "steps_lost": (max(step_at_detect - step_now, 0)
                               if step_at_detect is not None
                               and step_now >= 0 else None),
                # the plan this generation's world runs (None without a
                # degrade controller): ties the recovery record to the
                # shrink/promote transitions in docs/elastic.md
                "plan": self._plan_string(),
            }
            self._generation_history.append(entry)
        # registry mirror of the history entry (generation-labeled so a
        # scraper keeps every generation, not just the last)
        g = str(gen)
        telemetry.counter("hvd_elastic_generations_ready_total",
                          "generations that reached fully-READY").inc()
        telemetry.gauge("hvd_elastic_recovery_seconds",
                        "assignment → all-READY latency").set(
                            recovery_s, generation=g)
        if detect_s is not None:
            telemetry.gauge("hvd_elastic_generation_detect_seconds",
                            "failure-detection latency that triggered "
                            "the generation").set(detect_s, generation=g)
        if entry["steps_lost"] is not None:
            telemetry.gauge("hvd_elastic_generation_steps_lost",
                            "training steps lost across the generation "
                            "change (best effort)").set(
                                entry["steps_lost"], generation=g)
        detect = "" if detect_s is None else f" detect_s={detect_s:.1f}"
        hvd_logging.info(
            "elastic: generation %d fully ready — %d worker(s) in "
            "recovery_s=%.1f%s", gen, len(keys), recovery_s, detect)

    def announce_departure(self, host: str, local_rank: int,
                           step: int = -1) -> None:
        """A planned (preemption-grace or serve-drain) departure: the
        worker has committed (or is committing) its state and will
        exit.  Exempt it from death verdicts now; its exit is handled
        as graceful in :meth:`record_worker_exit` — no blacklist, no
        quarantine, no sibling abort (guard/preempt.py, serve/pool.py).
        """
        self._health.mark_departing(host, local_rank)
        with self._lock:
            self._planned_departures.add((host, local_rank))
        telemetry.counter(
            "hvd_guard_preempt_departures_total",
            "planned (preemption-grace) departures announced").inc()
        hvd_logging.info(
            "elastic: worker %s:%d announced a planned departure at "
            "step %d — exempt from death verdicts and quarantine",
            host, local_rank, step)

    def _on_worker_dead(self, host: str, local_rank: int,
                        detect_s: float, reason: str) -> None:
        """Health-monitor verdict: treat as a failure exit NOW — the
        regeneration starts before the worker process is ever observed
        to exit (it may never exit: a hang holds its chips until the
        abort event kills the tree)."""
        if self._shutdown.is_set():
            return    # completed/stopped job: silence is expected
        if "departure" in reason:
            # the planned-departure grace expired: the worker announced
            # but wedged instead of exiting.  Revoke the graceful-exit
            # exemption so this takes the normal failure path
            with self._lock:
                self._planned_departures.discard((host, local_rank))
        # the pre-failure training peak, for the generation_history
        # steps_lost estimate (monitor lock first, ours second — the
        # same one-way order _check_generation_ready uses)
        step_at_detect = self._health.max_step()
        # the monitor thread calls this; _check_generation_ready reads
        # and consumes last_detect_s under the lock
        with self._lock:
            self.last_detect_s = detect_s
            self.last_detect_reason = reason
            self._step_at_detect = step_at_detect \
                if step_at_detect >= 0 else None
        hvd_logging.warning(
            "elastic: worker %s:%d declared dead (%s) — detect_s=%.2f; "
            "regenerating without waiting for process exit",
            host, local_rank, reason, detect_s)
        self.record_worker_exit(host, local_rank, 1)

    # -- lifecycle ----------------------------------------------------------

    def start(self, np: int, create_worker_fn: Callable) -> None:
        """Wait for ``min(np, …)`` slots, compute assignments, spawn all
        workers (reference ``driver.start``).  ``create_worker_fn`` takes
        ``(slot, coordinator_addr, generation[, abort_event])``; when the
        4th parameter is accepted, the driver sets the event to demand
        the worker process tree be killed (hung startup, de-assignment)."""
        self._create_worker_fn = create_worker_fn
        import inspect

        try:
            nparams = len(inspect.signature(create_worker_fn).parameters)
        except (TypeError, ValueError):
            nparams = 4
        self._worker_fn_takes_abort = nparams >= 4
        self._service.start()
        self._discovery_thread.start()
        self._health.start()
        # wait for the REQUESTED world, not the minimum (reference
        # ``driver.start`` → ``wait_for_available_slots(np)``): with racy
        # discovery (e.g. executor-pool registration) waiting only for
        # min_np starts a world of whichever slots registered first and a
        # fast job can finish before the rest ever join.  But np is a
        # request, not a contract: past the start timeout an elastic
        # cluster that can muster min_np starts small and grows when
        # hosts arrive — failing it outright would defeat elasticity.
        self.wait_for_available_slots(max(np, self._min_np),
                                      fallback_min=self._min_np,
                                      fallback_after=self._start_timeout)
        with self._lock:
            self._update_host_assignments()
        self._spawn_all()

    def stop(self, exit_code: int = 1) -> None:
        # under the lock: stop() runs from resume/discovery threads as
        # well as the main thread, and record_worker_exit's success path
        # writes _exit_code concurrently — first finisher wins, torn
        # writes lose (hvdlint HVD004)
        with self._lock:
            if not self._finished.is_set():
                self._exit_code = exit_code
                self._finished.set()
        self._shutdown.set()
        self._health.stop()
        with self._lock:
            keys = list(self._abort_events)
        self._abort_workers(keys)

    def finished(self) -> bool:
        return self._finished.is_set()

    def wait_for_completion(self) -> int:
        self._finished.wait()
        self._health.stop()
        self._service.shutdown()
        with self._lock:
            services, self._coord_services = self._coord_services, []
        for svc in services:
            svc.shutdown()
        return self._exit_code if self._exit_code is not None else 0

    def wait_for_available_slots(self, min_np: int,
                                 fallback_min: Optional[int] = None,
                                 fallback_after: Optional[float] = None,
                                 deadline_s: Optional[float] = None
                                 ) -> None:
        """Block until discovery supplies ≥ min_np slots (reference
        ``wait_for_available_slots:145``).  With a fallback, accept
        ``fallback_min`` slots once ``fallback_after`` seconds have
        passed — start-small-grow-later elasticity when the requested
        world doesn't fully materialize.  ``deadline_s`` overrides the
        driver timeout (the degrade path's ``HOROVOD_DEGRADE_WAIT_S``
        bound on waiting for a lost model extent to return)."""
        start = time.monotonic()
        deadline = start + (self._timeout if deadline_s is None
                            else deadline_s)
        while not self._shutdown.is_set():
            avail = self._host_manager.available_slots
            if avail >= min_np:
                return
            if fallback_min is not None and fallback_after is not None \
                    and avail >= fallback_min and \
                    time.monotonic() - start > fallback_after:
                hvd_logging.warning(
                    "elastic: only %d of the requested %d slots appeared "
                    "within %.0fs — starting with %d and growing as "
                    "hosts arrive", avail, min_np, fallback_after, avail)
                return
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"timed out waiting for {min_np} slots; discovered "
                    f"{self._host_manager.available_slots}")
            self._hosts_avail.wait(timeout=DISCOVER_INTERVAL_S)
            self._hosts_avail.clear()

    # -- discovery / reassignment ------------------------------------------

    def _discovery_loop(self) -> None:
        while not self._shutdown.is_set():
            try:
                faults.inject("driver.discovery")
                res = self._host_manager.update_available_hosts()
            except Exception as e:
                hvd_logging.warning("elastic: discovery failed: %s", e)
                res = HostUpdateResult.no_update
            if res != HostUpdateResult.no_update:
                hvd_logging.info("elastic: host set changed (res=%d)", res)
                self._hosts_avail.set()
                with self._lock:
                    started = bool(self._assignments)
                if started:
                    # recompute assignments + spawn added workers + notify
                    # survivors; async so discovery keeps feeding
                    # wait_for_available_slots during the resume
                    threading.Thread(target=self.resume, daemon=True).start()
            self._shutdown.wait(DISCOVER_INTERVAL_S)

    def _notify_workers_host_changes(self, res: int) -> None:
        """Ping every registered worker so rank 0's next commit raises
        HostsUpdatedInterrupt (reference ``driver.py:197-225``)."""
        timestamp = int(time.time() * 1e6)
        with self._lock:
            addrs = dict(self._worker_notify_addrs)
        for rank, addr in addrs.items():
            try:
                notify_hosts_updated(addr, self._secret_key, timestamp, res)
            except OSError as e:
                hvd_logging.debug(
                    "elastic: could not notify rank %d at %s: %s",
                    rank, addr, e)

    def _update_host_assignments(self) -> Dict[Tuple[str, int], SlotInfo]:
        """Recompute SlotInfos with ranks stable for surviving workers
        (reference ``_update_host_assignments:227``): hosts keep their
        discovery order, so a surviving (host, local_rank) keeps its rank
        unless an earlier host vanished; at least one previously-assigned
        host must survive to carry the state forward."""
        # every caller already holds self._lock, but the generation swap
        # must be atomic regardless of future call sites — the RLock
        # makes re-acquiring free (hvdlint HVD004)
        with self._lock:
            current = self._host_manager.current_hosts
            prev = self._assignments
            if prev:
                surviving = {h for h, _ in prev} & set(current)
                if not surviving:
                    raise RuntimeError(
                        "elastic: no previously-assigned host survived — "
                        "model state is lost (reference guarantee "
                        "driver.py:236-242)")
            hosts = [HostInfo(h, s) for h, s in current.items()]
            assignments = get_host_assignments(
                hosts, self._min_np,
                self._max_np or sum(h.slots for h in hosts))
            self._assignments = {(s.hostname, s.local_rank): s
                                 for s in assignments}
            if self._degrade is not None:
                # re-resolve the plan to the new world BEFORE workers
                # fetch their assignment: shrink when capacity was
                # lost, promote when it came back (a "wait" verdict
                # leaves the current plan standing — resume() already
                # blocked for at least the model extent)
                self._degrade.on_world_change(len(self._assignments))
            self._registry.purge_unassigned(set(self._assignments))
            self._health.purge(set(self._assignments))
            self._worker_metrics.purge(
                {f"{h}:{lr}" for (h, lr) in self._assignments})
            self._coordinator_addr = self._new_coordinator_addr(assignments)
            self._generation += 1
            self._generation_started = time.monotonic()
            self._regen_requests.clear()
            telemetry.gauge("hvd_elastic_generation",
                            "current elastic world generation").set(
                                self._generation)
            telemetry.gauge("hvd_elastic_world_size",
                            "assigned workers in the current "
                            "generation").set(len(self._assignments))
            telemetry.run_context().advance(generation=self._generation)
            return self._assignments

    def _new_coordinator_addr(self, assignments: List[SlotInfo]) -> str:
        """Fresh coordination service per generation, hosted HERE in the
        driver process (see ``runtime/distributed.py``): a worker death —
        including rank 0's — must not take the coordination plane down,
        the same reason the reference's rendezvous server lives in the
        launcher (``gloo_run.py:213``), never in a worker."""
        from horovod_tpu.runtime import distributed as hvd_dist

        with socket.socket() as sock:
            sock.bind(("", 0))
            port = sock.getsockname()[1]
        nproc = len(assignments)
        if nproc > 1:   # single-process generations never connect
            heartbeat = int(os.environ.get(
                "HOROVOD_ELASTIC_HEARTBEAT_TIMEOUT",
                hvd_dist.DEFAULT_HEARTBEAT_TIMEOUT_S))
            self._coord_services.append(hvd_dist.start_coordination_service(
                port, nproc, heartbeat_timeout=heartbeat))
        host = socket.gethostname()
        if all(s.hostname in ("localhost", "127.0.0.1", host)
               for s in assignments):
            host = "127.0.0.1"
        return f"{host}:{port}"

    # -- worker management --------------------------------------------------

    def _spawn_all(self) -> None:
        with self._lock:
            slots = list(self._assignments.values())
        for slot in slots:
            self._spawn(slot)

    def _spawn(self, slot: SlotInfo) -> None:
        # SPAWNED, not READY: readiness is worker-reported (it arrives via
        # WorkerReadyRequest / the rendezvous GET) so a worker hung in
        # startup is observable — the round-1 design marked workers ready
        # at spawn, making a wedged startup look healthy forever.
        abort = threading.Event()
        key = (slot.hostname, slot.local_rank)
        # token first, SPAWNED second: a stale watchdog firing in between
        # fails its token check; the reverse order would let it see the
        # new worker's SPAWNED state while the token still matches its own
        with self._lock:
            self._abort_events[key] = abort
            token = self._spawn_tokens.get(key, 0) + 1
            self._spawn_tokens[key] = token
        self._registry.record_spawned(slot.hostname, slot.local_rank)
        thread = threading.Thread(
            target=self._run_worker, args=(slot, abort, token), daemon=True,
            name=f"hvd_tpu_elastic_worker_{slot.rank}")
        thread.start()
        watchdog = threading.Timer(
            self._start_timeout, self._check_started, args=(slot, token))
        watchdog.daemon = True
        watchdog.start()

    def _check_started(self, slot: SlotInfo, token: int) -> None:
        """Startup watchdog: a worker that never reported READY within the
        start timeout is treated as a startup failure (blacklist + resume),
        the reference's start-timeout semantics
        (``runner/elastic/settings.py`` elastic start timeout).

        ``token`` pins the watchdog to the spawn that armed it: a slot
        removed by scale-down and re-spawned at the same (host,
        local_rank) within start_timeout is again SPAWNED when the stale
        timer fires — without the token it would fail the new worker."""
        from horovod_tpu.elastic.registration import SPAWNED

        if self._shutdown.is_set():
            return
        with self._lock:
            if self._spawn_tokens.get(
                    (slot.hostname, slot.local_rank)) != token:
                return
        if self._registry.get_state(slot.hostname, slot.local_rank) == SPAWNED:
            hvd_logging.warning(
                "elastic: worker %s:%d never reported ready within %.0fs — "
                "treating as startup failure",
                slot.hostname, slot.local_rank, self._start_timeout)
            self.record_worker_exit(slot.hostname, slot.local_rank, 1,
                                    token=token)

    def _run_worker(self, slot: SlotInfo,
                    abort: Optional[threading.Event] = None,
                    token: Optional[int] = None) -> None:
        with self._lock:
            coordinator = self._coordinator_addr
            generation = self._generation
        try:
            if self._worker_fn_takes_abort:
                exit_code = self._create_worker_fn(slot, coordinator,
                                                   generation, abort)
            else:
                exit_code = self._create_worker_fn(slot, coordinator,
                                                   generation)
        except Exception as e:
            hvd_logging.warning("elastic: worker rank %d crashed in "
                                "launcher: %s", slot.rank, e)
            exit_code = 1
        self.record_worker_exit(slot.hostname, slot.local_rank, exit_code,
                                token=token)

    def _abort_workers(self, keys) -> None:
        """Fire abort events so the launcher kills the worker process
        trees (reference: host events passed into create_worker_fn,
        ``driver.py:276-283``) — a hung or de-assigned worker must not
        keep holding its host's chips."""
        with self._lock:
            events = [self._abort_events[k] for k in keys
                      if k in self._abort_events]
        for ev in events:
            ev.set()

    def record_worker_exit(self, host: str, local_rank: int,
                           exit_code: int,
                           token: Optional[int] = None) -> None:
        """Reference ``_handle_worker_exit``: zero → success (job completes
        when every assigned worker succeeded); non-zero → blacklist +
        resume with survivors.  Exits from workers without a current rank
        assignment (scale-down removals, already-blacklisted hosts) are
        ignored (reference ``driver.py:292-296``) — otherwise a gracefully
        removed worker's exit would blacklist its still-healthy host.

        ``token``, when given, pins the exit to the spawn that produced
        it: a slot removed and re-spawned at the same (host, local_rank)
        key can otherwise have the *old* worker's late exit recorded
        against the *new* worker — exit 0 would mark it SUCCESS (and
        could complete the job mid-training), non-zero would blacklist
        its healthy host."""
        with self._lock:
            if token is not None and \
                    self._spawn_tokens.get((host, local_rank)) != token:
                hvd_logging.debug(
                    "elastic: ignoring exit code %d from superseded spawn "
                    "of %s:%d", exit_code, host, local_rank)
                return
            if (host, local_rank) not in self._assignments:
                hvd_logging.debug(
                    "elastic: ignoring exit code %d from unassigned worker "
                    "%s:%d", exit_code, host, local_rank)
                return
            if (host, local_rank) in self._planned_departures:
                # preemption grace: the departure was announced and the
                # state committed — this exit is not a failure (no
                # blacklist, no quarantine, no sibling abort) and not a
                # job-completing success either (the work is unfinished;
                # the host returns to the pool when discovery re-lists it)
                self._planned_departures.discard((host, local_rank))
                hvd_logging.info(
                    "elastic: worker %s:%d exited (code %d) after a "
                    "planned departure — treating as graceful",
                    host, local_rank, exit_code)
                return
        if self._host_manager.is_blacklisted(host):
            # one incident, one reset: the first failure on this host
            # blacklisted it and queued the resume; its sibling workers'
            # exits (aborted, or crashing on the dead host) must not each
            # burn a --reset-limit slot, and a straggler exit 0 from a
            # blacklisted host must not count toward job completion.
            hvd_logging.debug(
                "elastic: ignoring exit code %d from blacklisted host "
                "%s:%d", exit_code, host, local_rank)
            return
        if exit_code == 0:
            self._registry.record_success(host, local_rank)
            with self._lock:
                all_done = all(
                    self._registry.get_state(h, lr) == "SUCCESS"
                    for (h, lr) in self._assignments)
                if all_done and not self._finished.is_set():
                    self._exit_code = 0
                    self._finished.set()
            if all_done:
                self._shutdown.set()
        else:
            # record_failure's check-and-set is atomic: it returns False
            # when the worker is already FAILURE — e.g. the startup
            # watchdog recorded the failure and the aborted process's
            # real exit lands before resume() purges the assignment.  A
            # second count would halve the effective --reset-limit and
            # queue a redundant resume.
            if not self._registry.record_failure(host, local_rank):
                hvd_logging.debug(
                    "elastic: ignoring duplicate failure exit %d from "
                    "%s:%d", exit_code, host, local_rank)
                return
            hvd_logging.warning(
                "elastic: worker %s:%d exited with code %d",
                host, local_rank, exit_code)
            # the whole host is blacklisted: kill its other workers too
            with self._lock:
                siblings = [k for k in self._abort_events if k[0] == host]
            self._abort_workers(siblings)

    def resume(self) -> None:
        """Failure/host-change recovery: recompute assignments, spawn
        workers for newly-added slots, notify survivors (reference
        ``driver.resume``)."""
        if self._shutdown.is_set():
            return
        with self._resume_lock:
            # Unrecoverable-fast-path: the state carrier rule requires a
            # previously-assigned host to survive (reference
            # driver.py:236-242).  If every one of them is blacklisted,
            # no future discovery output can help — stop now instead of
            # waiting out the elastic timeout for slots that cannot
            # carry the state anyway.
            with self._lock:
                prev_hosts = {h for h, _ in self._assignments}
            if prev_hosts and all(self._host_manager.is_blacklisted(h)
                                  for h in prev_hosts):
                hvd_logging.warning(
                    "elastic: every previously-assigned host is "
                    "blacklisted — model state is lost; stopping job")
                self.stop(1)
                return
            try:
                if self._degrade is not None:
                    # degraded continuation: only the model extent is
                    # load-bearing — any world that hosts it can train
                    # (at a shrunk dp/fsdp).  Bound the wait with the
                    # degrade deadline, not the full elastic timeout.
                    self.wait_for_available_slots(
                        max(1, self._degrade.min_world()),
                        deadline_s=self._degrade.wait_s)
                else:
                    self.wait_for_available_slots(self._min_np)
            except TimeoutError as e:
                hvd_logging.warning("elastic: %s", e)
                self.stop(1)
                return
            with self._lock:
                before = set(self._assignments)
                try:
                    self._update_host_assignments()
                except RuntimeError as e:
                    hvd_logging.warning("elastic: %s", e)
                    self.stop(1)
                    return
                added = [s for k, s in self._assignments.items()
                         if k not in before]
                removed = before - set(self._assignments)
            for slot in added:
                self._spawn(slot)
            self._notify_workers_host_changes(HostUpdateResult.mixed)
            # give de-assigned workers a grace window to self-retire via
            # the rendezvous (clean exit 0), then force-kill stragglers.
            # Capture the Event objects NOW: resolving keys at fire time
            # would abort a worker re-spawned at the same (host,
            # local_rank) during the grace window, since _spawn
            # overwrites _abort_events entries.
            if removed:
                with self._lock:
                    stale_events = {k: self._abort_events[k] for k in removed
                                    if k in self._abort_events}

                def _reap():
                    self._shutdown.wait(30.0)
                    for ev in stale_events.values():
                        ev.set()
                    # drop bookkeeping for slots that stayed de-assigned
                    # so host churn doesn't grow these dicts without
                    # bound; a slot re-spawned at the same key in the
                    # grace window has fresh entries (identity differs)
                    # and keeps them
                    with self._lock:
                        for k, ev in stale_events.items():
                            if k not in self._assignments:
                                if self._abort_events.get(k) is ev:
                                    self._abort_events.pop(k, None)
                                    self._spawn_tokens.pop(k, None)

                threading.Thread(target=_reap, daemon=True,
                                 name="hvd_tpu_elastic_reaper").start()

    def get_slot_info(self, host: str, local_rank: int) -> Optional[SlotInfo]:
        with self._lock:
            return self._assignments.get((host, local_rank))

    @property
    def world_size(self) -> int:
        with self._lock:
            return len(self._assignments)
