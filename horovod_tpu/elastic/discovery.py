"""Host discovery for elastic training.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostDiscovery``
interface, ``HostDiscoveryScript`` (user script printing ``host:slots``
lines, re-run every second), ``FixedHosts`` (the built-in test fake), and
``HostManager`` which diffs discoveries, applies the exclusion rules and
keeps a stable host ordering for rank assignment.

Robustness changes over the reference (docs/faults.md):

* a failing discovery script **retains the last-good host set** instead
  of propagating into (and killing) the driver's discovery loop, with
  in-pass retries under the unified :class:`RetryPolicy`;
* worker-failure exclusion is a **quarantine with exponential-cooldown
  decay and probationary readmission** (:class:`HostQuarantine`) instead
  of a permanent blacklist: a flapping host stops churning generations
  (each relapse doubles its cooldown) but a genuinely recovered host
  rejoins without operator action.  The permanent :meth:`HostManager.
  blacklist` remains for explicit operator blacklisting;
* **starvation escape**: when a discovery pass finds hosts but every
  one of them is excluded, the earliest-eligible quarantined host is
  readmitted on probation instead of reporting an empty cluster — an
  all-flapping fleet must degrade to "keep trying the least-bad host",
  never to a discovery loop that stalls forever.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.utils import logging as hvd_logging

# quarantine/readmit transitions as events (docs/metrics.md) — today's
# log lines, scrapeable: a flapping host shows as a climbing
# `quarantined` count with matching `probation` readmissions
_TEL_QUARANTINE = telemetry.counter(
    "hvd_quarantine_events_total",
    "host quarantine state transitions (event=quarantined|probation|"
    "cleared)")


class HostUpdateResult:
    """Bitmask of what changed in a discovery pass (reference enum)."""

    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return ``{hostname: slots}`` for every currently-usable host."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Execute the user's discovery script; stdout lines are
    ``hostname:slots`` (or bare hostnames with ``default_slots``).

    A script failure (non-zero exit, timeout, unparsable output) is
    retried under ``retry`` (env-default :class:`RetryPolicy`, capped at
    2 in-pass attempts — the discovery loop itself re-runs every
    second) and then **logged and absorbed**: the last successfully
    discovered host set is returned, so one flaky ``kubectl``/ssh call
    cannot take down the discovery loop or make the driver believe the
    cluster vanished."""

    def __init__(self, discovery_script: str, default_slots: int = 1,
                 retry=None):
        from horovod_tpu.runtime.retry import RetryPolicy

        self._script = discovery_script
        self._default_slots = default_slots
        self._retry = retry if retry is not None else RetryPolicy(
            max_attempts=2, base_s=0.2, max_s=1.0, deadline_s=30.0,
            retry_on=(subprocess.CalledProcessError,
                      subprocess.TimeoutExpired, OSError),
            name="discovery-script")
        self._last_good: Optional[Dict[str, int]] = None
        self._consecutive_failures = 0

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    def _run_script(self) -> Dict[str, int]:
        faults.inject("discovery.script")
        out = subprocess.check_output(
            self._script, shell=True, timeout=60).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, _, slots = line.rpartition(":")
                hosts[name] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        try:
            hosts = self._retry.call(self._run_script)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                OSError, ValueError) as e:
            self._consecutive_failures += 1
            if self._last_good is not None:
                hvd_logging.warning(
                    "elastic: discovery script failed (%d consecutive: "
                    "%s: %s) — retaining last-good host set (%d host(s))",
                    self._consecutive_failures, type(e).__name__, e,
                    len(self._last_good))
                return dict(self._last_good)
            hvd_logging.warning(
                "elastic: discovery script failed (%d consecutive: %s: "
                "%s) and no prior result exists — reporting no hosts",
                self._consecutive_failures, type(e).__name__, e)
            return {}
        self._consecutive_failures = 0
        self._last_good = dict(hosts)
        return hosts


class FixedHosts(HostDiscovery):
    """Static (but settable) host set — the reference's test fake, also
    used for ``-H``-style elastic runs."""

    def __init__(self, available_hosts: Optional[Dict[str, int]] = None):
        self._hosts = dict(available_hosts or {})

    def set(self, available_hosts: Dict[str, int]) -> None:
        self._hosts = dict(available_hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


_QUARANTINED = "quarantined"
_PROBATION = "probation"


class HostQuarantine:
    """Per-host failure tracking with exponential-cooldown quarantine
    and probationary readmission.

    Failure ``n`` excludes the host for ``min(base_s * 2**(n-1),
    max_s)`` seconds.  After the cooldown the host is readmitted **on
    probation**: a relapse within ``probation_s`` re-quarantines it with
    the doubled cooldown (the failure count is retained), while
    surviving probation clears its record entirely — the decay that
    lets a repaired host return to full standing without operator
    action.

    Knobs: ``HOROVOD_QUARANTINE_BASE_S`` (30), ``HOROVOD_QUARANTINE_
    MAX_S`` (600), ``HOROVOD_QUARANTINE_PROBATION_S`` (120);
    ``HOROVOD_QUARANTINE_DISABLE=1`` restores the reference's permanent
    exclusion (every failure quarantines forever).  ``clock`` is
    injectable for deterministic tests."""

    def __init__(self, base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 probation_s: Optional[float] = None,
                 disabled: Optional[bool] = None,
                 clock: Callable[[], float] = time.monotonic):
        env = os.environ.get
        self.base_s = float(base_s if base_s is not None
                            else env("HOROVOD_QUARANTINE_BASE_S", 30.0))
        self.max_s = float(max_s if max_s is not None
                           else env("HOROVOD_QUARANTINE_MAX_S", 600.0))
        self.probation_s = float(
            probation_s if probation_s is not None
            else env("HOROVOD_QUARANTINE_PROBATION_S", 120.0))
        self.disabled = bool(disabled if disabled is not None
                             else env("HOROVOD_QUARANTINE_DISABLE", "")
                             in ("1", "true", "yes", "on"))
        self._clock = clock
        # host -> {"failures": n, "state": ..., "until": t}
        self._hosts: Dict[str, dict] = {}

    def record_failure(self, host: str) -> float:
        """One failure incident; returns the cooldown applied (``inf``
        when quarantine decay is disabled)."""
        now = self._clock()
        rec = self._hosts.setdefault(
            host, {"failures": 0, "state": _QUARANTINED, "until": now})
        rec["failures"] += 1
        if self.disabled:
            cooldown = float("inf")
        else:
            cooldown = min(self.base_s * (2.0 ** (rec["failures"] - 1)),
                           self.max_s)
        rec["state"] = _QUARANTINED
        rec["until"] = now + cooldown
        _TEL_QUARANTINE.inc(event="quarantined")
        return cooldown

    def is_excluded(self, host: str) -> bool:
        """Whether ``host`` is currently held out of assignment; lazily
        advances the quarantined → probation → cleared transitions."""
        rec = self._hosts.get(host)
        if rec is None:
            return False
        now = self._clock()
        if rec["state"] == _QUARANTINED:
            if now < rec["until"]:
                return True
            rec["state"] = _PROBATION
            rec["until"] = now + self.probation_s
            _TEL_QUARANTINE.inc(event="probation")
            hvd_logging.info(
                "elastic: quarantine cooldown for host %s expired — "
                "readmitting on probation (%.0fs, %d prior failure(s))",
                host, self.probation_s, rec["failures"])
            return False
        # probation: available; survival past the window clears the record
        if now >= rec["until"]:
            del self._hosts[host]
            _TEL_QUARANTINE.inc(event="cleared")
            hvd_logging.info(
                "elastic: host %s survived probation — record cleared",
                host)
        return False

    def status(self, host: str) -> Optional[str]:
        rec = self._hosts.get(host)
        return None if rec is None else rec["state"]

    def force_probation(self, host: str) -> bool:
        """Readmit a quarantined host before its cooldown expires —
        the anti-starvation escape hatch (:meth:`HostManager.
        update_available_hosts`): the failure count is retained, so a
        relapse still gets the doubled cooldown.  Returns False when
        the host has no quarantine record to lift."""
        rec = self._hosts.get(host)
        if rec is None or rec["state"] != _QUARANTINED:
            return False
        rec["state"] = _PROBATION
        rec["until"] = self._clock() + self.probation_s
        _TEL_QUARANTINE.inc(event="probation")
        return True

    def failures(self, host: str) -> int:
        rec = self._hosts.get(host)
        return 0 if rec is None else rec["failures"]

    def remaining_s(self, host: str) -> float:
        """Seconds of cooldown left (0 when not quarantined)."""
        rec = self._hosts.get(host)
        if rec is None or rec["state"] != _QUARANTINED:
            return 0.0
        return max(rec["until"] - self._clock(), 0.0)


class HostManager:
    """Tracks the discovered host set, the exclusion rules (permanent
    blacklist + decaying quarantine) and a stable assignment order
    (reference ``HostManager``): surviving hosts keep their position,
    new hosts append — the property that lets surviving workers keep
    their ranks across resets."""

    def __init__(self, discovery: HostDiscovery,
                 quarantine: Optional[HostQuarantine] = None):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._available: Dict[str, int] = {}
        self._order: List[str] = []
        self._blacklist: set = set()
        self._quarantine = quarantine if quarantine is not None \
            else HostQuarantine()

    def update_available_hosts(self) -> int:
        """Run one discovery pass; returns a :class:`HostUpdateResult`
        bitmask describing the delta.  Quarantine expiry is applied
        here, so a readmitted host surfaces as an ``added`` delta on
        the pass after its cooldown ends."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            usable = {h: s for h, s in found.items()
                      if h not in self._blacklist
                      and not self._quarantine.is_excluded(h)}
            if not usable and found and not self._quarantine.disabled:
                # starvation escape: every discovered host is excluded
                # (quarantine ∪ blacklist), so without intervention the
                # discovery loop would report an empty cluster until a
                # cooldown happens to expire — potentially forever with
                # flapping hosts re-doubling their cooldowns.  Readmit
                # the earliest-eligible quarantined host on probation
                # (failure count retained); permanently-blacklisted
                # hosts stay out, and HOROVOD_QUARANTINE_DISABLE=1
                # keeps the reference's exclude-forever behavior.
                cands = [h for h in found
                         if h not in self._blacklist
                         and self._quarantine.status(h) == _QUARANTINED]
                if cands:
                    pick = min(cands, key=lambda h: (
                        self._quarantine.remaining_s(h), h))
                    waived = self._quarantine.remaining_s(pick)
                    self._quarantine.force_probation(pick)
                    usable[pick] = found[pick]
                    hvd_logging.warning(
                        "elastic: every discovered host is excluded "
                        "(quarantine/blacklist) — readmitting host %s "
                        "early on probation (%.0fs of cooldown waived, "
                        "%d prior failure(s)) to avoid discovery "
                        "starvation", pick, waived,
                        self._quarantine.failures(pick))
            found = usable
            prev = self._available
            res = HostUpdateResult.no_update
            if any(h not in found or found[h] < prev[h] for h in prev):
                res |= HostUpdateResult.removed
            if any(h not in prev or found[h] > prev[h] for h in found):
                res |= HostUpdateResult.added
            self._available = found
            self._order = [h for h in self._order if h in found] + \
                          [h for h in found if h not in self._order]
            return res

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return {h: self._available[h] for h in self._order}

    @property
    def assignment_order(self) -> List[str]:
        with self._lock:
            return list(self._order)

    @property
    def host_quarantine(self) -> HostQuarantine:
        return self._quarantine

    def blacklist(self, host: str) -> bool:
        """PERMANENTLY exclude a host from all future assignments — the
        explicit operator action (and the reference's only behavior).
        Returns True if newly added."""
        with self._lock:
            if host in self._blacklist:
                return False
            hvd_logging.warning("elastic: blacklisting host %s "
                                "(permanent)", host)
            self._blacklist.add(host)
            self._drop_locked(host)
            return True

    def quarantine(self, host: str) -> float:
        """Exclude a failing host for an exponentially-growing cooldown
        (the failure-exit path).  Returns the cooldown seconds."""
        with self._lock:
            cooldown = self._quarantine.record_failure(host)
            self._drop_locked(host)
        hvd_logging.warning(
            "elastic: quarantining host %s for %.0fs (failure #%d; "
            "probationary readmission after cooldown)",
            host, cooldown, self._quarantine.failures(host))
        return cooldown

    def _drop_locked(self, host: str) -> None:
        self._available.pop(host, None)
        if host in self._order:
            self._order.remove(host)

    def is_blacklisted(self, host: str) -> bool:
        """Currently excluded from assignment — permanently blacklisted
        OR inside a quarantine cooldown.  (The driver's sibling-exit
        suppression and state-carrier checks need "excluded now", which
        both causes satisfy.)"""
        with self._lock:
            return host in self._blacklist \
                or self._quarantine.is_excluded(host)

    def is_quarantined(self, host: str) -> bool:
        with self._lock:
            return self._quarantine.is_excluded(host)

    @property
    def available_slots(self) -> int:
        with self._lock:
            return sum(self._available.values())
