"""Host discovery for elastic training.

Reference: ``horovod/runner/elastic/discovery.py`` — ``HostDiscovery``
interface, ``HostDiscoveryScript`` (user script printing ``host:slots``
lines, re-run every second), ``FixedHosts`` (the built-in test fake), and
``HostManager`` which diffs discoveries, applies the blacklist and keeps
a stable host ordering for rank assignment.
"""

from __future__ import annotations

import subprocess
import threading
from typing import Dict, List, Optional

from horovod_tpu.utils import logging as hvd_logging


class HostUpdateResult:
    """Bitmask of what changed in a discovery pass (reference enum)."""

    no_update = 0
    removed = 1
    added = 2
    mixed = removed | added


class HostDiscovery:
    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        """Return ``{hostname: slots}`` for every currently-usable host."""
        raise NotImplementedError


class HostDiscoveryScript(HostDiscovery):
    """Execute the user's discovery script; stdout lines are
    ``hostname:slots`` (or bare hostnames with ``default_slots``)."""

    def __init__(self, discovery_script: str, default_slots: int = 1):
        self._script = discovery_script
        self._default_slots = default_slots

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        out = subprocess.check_output(
            self._script, shell=True, timeout=60).decode()
        hosts: Dict[str, int] = {}
        for line in out.splitlines():
            line = line.strip()
            if not line:
                continue
            if ":" in line:
                name, _, slots = line.rpartition(":")
                hosts[name] = int(slots)
            else:
                hosts[line] = self._default_slots
        return hosts


class FixedHosts(HostDiscovery):
    """Static (but settable) host set — the reference's test fake, also
    used for ``-H``-style elastic runs."""

    def __init__(self, available_hosts: Optional[Dict[str, int]] = None):
        self._hosts = dict(available_hosts or {})

    def set(self, available_hosts: Dict[str, int]) -> None:
        self._hosts = dict(available_hosts)

    def find_available_hosts_and_slots(self) -> Dict[str, int]:
        return dict(self._hosts)


class HostManager:
    """Tracks the discovered host set, the blacklist, and a stable
    assignment order (reference ``HostManager``): surviving hosts keep
    their position, new hosts append — the property that lets surviving
    workers keep their ranks across resets."""

    def __init__(self, discovery: HostDiscovery):
        self._discovery = discovery
        self._lock = threading.Lock()
        self._available: Dict[str, int] = {}
        self._order: List[str] = []
        self._blacklist: set = set()

    def update_available_hosts(self) -> int:
        """Run one discovery pass; returns a :class:`HostUpdateResult`
        bitmask describing the delta."""
        found = self._discovery.find_available_hosts_and_slots()
        with self._lock:
            found = {h: s for h, s in found.items()
                     if h not in self._blacklist}
            prev = self._available
            res = HostUpdateResult.no_update
            if any(h not in found or found[h] < prev[h] for h in prev):
                res |= HostUpdateResult.removed
            if any(h not in prev or found[h] > prev[h] for h in found):
                res |= HostUpdateResult.added
            self._available = found
            self._order = [h for h in self._order if h in found] + \
                          [h for h in found if h not in self._order]
            return res

    @property
    def current_hosts(self) -> Dict[str, int]:
        with self._lock:
            return {h: self._available[h] for h in self._order}

    @property
    def assignment_order(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def blacklist(self, host: str) -> bool:
        """Exclude a host from all future assignments (reference
        blacklisting of failing hosts).  Returns True if newly added."""
        with self._lock:
            if host in self._blacklist:
                return False
            hvd_logging.warning("elastic: blacklisting host %s", host)
            self._blacklist.add(host)
            self._available.pop(host, None)
            if host in self._order:
                self._order.remove(host)
            return True

    def is_blacklisted(self, host: str) -> bool:
        with self._lock:
            return host in self._blacklist

    @property
    def available_slots(self) -> int:
        with self._lock:
            return sum(self._available.values())
