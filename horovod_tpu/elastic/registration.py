"""Worker lifecycle registry for the elastic driver.

Reference: ``horovod/runner/elastic/registration.py`` —
``WorkerStateRegistry`` collects per-worker READY/SUCCESS/FAILURE
records, acts as the barrier deciding when a generation is complete, and
triggers ``driver.resume()`` when a failure requires re-assignment.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from horovod_tpu.utils import logging as hvd_logging

SPAWNED = "SPAWNED"   # process launched, worker has not reported in yet
READY = "READY"       # worker-reported: startup done, training loop entered
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, driver, host_manager, reset_limit: int = 0):
        self._driver = driver
        self._host_manager = host_manager
        self._lock = threading.Lock()
        self._states: Dict[Tuple[str, int], str] = {}
        self._reset_limit = reset_limit      # 0 = unlimited resets
        self._reset_count = 0
        self._failure_count = 0

    @property
    def reset_count(self) -> int:
        return self._reset_count

    @property
    def failure_count(self) -> int:
        """Total FAILURE records this job — the degrade plane's cheap
        capacity-churn signal (a world that keeps failing should stay
        shrunk rather than promote into the same flaky hosts)."""
        with self._lock:
            return self._failure_count

    def get_state(self, host: str, local_rank: int) -> str:
        with self._lock:
            return self._states.get((host, local_rank), "")

    def record_spawned(self, host: str, local_rank: int) -> None:
        """Launcher-side: the process was exec'd; READY comes from the
        worker itself (reference registration.py: READY is reported via
        the rendezvous, not assumed at spawn)."""
        with self._lock:
            self._states.setdefault((host, local_rank), SPAWNED)

    def record_ready(self, host: str, local_rank: int) -> None:
        with self._lock:
            # never regress a terminal state (late READY after FAILURE)
            if self._states.get((host, local_rank)) not in (SUCCESS, FAILURE):
                self._states[(host, local_rank)] = READY

    def record_success(self, host: str, local_rank: int) -> None:
        with self._lock:
            self._states[(host, local_rank)] = SUCCESS

    def record_failure(self, host: str, local_rank: int) -> bool:
        """A worker exited non-zero: exclude its host immediately and
        resume with the survivors (the reference's immediate-blacklist
        rule, ``driver.py:291-307``) — but through the decaying
        quarantine (``discovery.HostQuarantine``), so a flapping host's
        cooldown grows exponentially while a recovered host is
        readmitted on probation without operator action.  Permanent
        exclusion remains available via ``HostManager.blacklist``.

        Returns False (and does nothing) when the worker is already in
        FAILURE — the check-and-set is atomic under the registry lock so
        two concurrent exit records for the same incident (startup
        watchdog + the aborted process's real exit) cannot both
        increment reset_count or queue two resumes."""
        with self._lock:
            if self._states.get((host, local_rank)) == FAILURE:
                return False
            self._states[(host, local_rank)] = FAILURE
            self._failure_count += 1
        self._host_manager.quarantine(host)
        self._maybe_resume()
        return True

    def _maybe_resume(self) -> None:
        # decide under the lock, call the driver OUTSIDE it: stop()
        # takes the driver lock, and the driver calls back into this
        # registry (purge_unassigned) while holding it — calling out
        # with our lock held is the registry->driver half of a
        # driver->registry lock-order inversion, i.e. a deadlock with
        # the resume path (hvdlint HVD004 lock-order graph)
        with self._lock:
            stop = bool(self._reset_limit
                        and self._reset_count >= self._reset_limit)
            if not stop:
                self._reset_count += 1
        if stop:
            hvd_logging.warning(
                "elastic: reset limit %d reached — stopping job",
                self._reset_limit)
            self._driver.stop()
            return
        self._driver.resume()

    def count(self, state: str) -> int:
        with self._lock:
            return sum(1 for s in self._states.values() if s == state)

    def purge_unassigned(self, assigned: set) -> None:
        """Drop states for workers no longer in the assignment set —
        otherwise a host removed and later re-added would inherit its old
        worker's READY/SUCCESS state, blinding the startup watchdog and
        the completion check for the re-spawned worker."""
        with self._lock:
            self._states = {k: v for k, v in self._states.items()
                            if k in assigned}

    def reset(self, expected: int) -> None:
        with self._lock:
            self._states = {}
