"""Heartbeat health monitoring for the elastic driver (docs/faults.md).

The driver previously learned of a dead worker only when its *process
exit* was observed by the launcher thread — a worker wedged in a
collective, or whose host dropped off the network, looked healthy
forever (until the coordination-service heartbeat killed the whole
generation from C++).  :class:`HealthMonitor` closes that gap at the
control plane: workers send periodic heartbeats over the existing
driver RPC channel (``HeartbeatRequest``, piggybacking the training
step counter), and the monitor applies two detectors:

* **liveness**: a worker is *suspect* after ``suspect_misses`` missed
  beats (logged once), and *dead* once no beat has arrived for
  ``dead_s`` — at which point ``on_dead(host, local_rank, detect_s,
  reason)`` fires and the driver starts regeneration *before* the
  process exit is ever observed;
* **progress**: a worker whose beats keep arriving but whose step
  counter has not advanced for ``progress_timeout_s`` is declared hung
  (:class:`~horovod_tpu.utils.stall.ProgressWatchdog` per worker) —
  the hung-but-alive case liveness alone cannot see;
* **stragglers** (observability-only): each worker's step-rate EWMA
  (off the same heartbeat step piggyback) is compared to the fleet
  median; one falling to ``1/straggler_ratio`` of the median gets a
  ``suspect_slow`` verdict — a worker-labeled
  ``hvd_elastic_straggler_ratio`` gauge and a one-shot warning, never
  a regeneration (a slow worker still makes progress; killing it
  trades throughput for a recovery stall).

Workers appear here only after their first heartbeat: never-started
workers are the startup watchdog's job (``driver._check_started``).
``clock`` and ``start_thread`` are injectable so chaos tests drive the
monitor deterministically on a fake clock.

Knobs: ``HOROVOD_ELASTIC_HEARTBEAT_INTERVAL`` (seconds between worker
beats, 0 disables the subsystem), ``HOROVOD_ELASTIC_HEARTBEAT_SUSPECT_
MISSES``, ``HOROVOD_ELASTIC_HEARTBEAT_DEAD_S``,
``HOROVOD_ELASTIC_PROGRESS_TIMEOUT_S`` (0 disables the progress
detector), ``HOROVOD_ELASTIC_DEPART_GRACE_S`` (how long an
announced planned departure may linger before the wedged worker falls
back to the normal dead-worker path), and
``HOROVOD_ELASTIC_STRAGGLER_RATIO`` (suspect_slow threshold, 0
disables the straggler detector).  See docs/running.md.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.utils import logging as hvd_logging
from horovod_tpu.utils.stall import ProgressWatchdog

DEFAULT_INTERVAL_S = 2.0
DEFAULT_SUSPECT_MISSES = 3
DEFAULT_DEAD_MULTIPLE = 10     # dead_s default = interval * this
DEFAULT_DEPART_GRACE_MULTIPLE = 3   # depart_grace_s default = dead_s * this
DEFAULT_STRAGGLER_RATIO = 3.0  # suspect_slow at median/rate >= this
STRAGGLER_EWMA_ALPHA = 0.3     # smoothing of the per-worker step rate

# health-plane telemetry (docs/metrics.md): what used to exist only as
# log lines.  Heartbeat age + progress stall are the precursors
# (scrapeable while a worker degrades); detect_s and the death counter
# record the verdicts the driver acts on.
_TEL_BEAT_AGE = telemetry.gauge(
    "hvd_worker_heartbeat_age_seconds",
    "max seconds since any monitored worker's last heartbeat")
_TEL_WORKERS = telemetry.gauge(
    "hvd_workers_monitored", "workers currently heartbeating")
_TEL_SUSPECT = telemetry.counter(
    "hvd_elastic_worker_suspect_total", "suspect declarations")
_TEL_DEATHS = telemetry.counter(
    "hvd_elastic_worker_deaths_total",
    "health-plane death/hang declarations")
_TEL_DETECT = telemetry.gauge(
    "hvd_elastic_detect_seconds",
    "silence/stagnation span of the most recent death declaration")
_TEL_STRAGGLER = telemetry.gauge(
    "hvd_elastic_straggler_ratio",
    "fleet-median step rate over this worker's EWMA step rate "
    "(1.0 = keeping pace; >= the straggler threshold = suspect_slow)")


def heartbeat_interval_s() -> float:
    return float(os.environ.get("HOROVOD_ELASTIC_HEARTBEAT_INTERVAL",
                                DEFAULT_INTERVAL_S))


class _WorkerHealth:
    __slots__ = ("last_beat", "suspect", "progress",
                 "rate", "last_step", "last_step_t", "slow")

    def __init__(self, now: float, clock, name: str = ""):
        self.last_beat = now
        self.suspect = False
        # named: the per-worker progress watchdog publishes its
        # stagnation gauge, the scrapeable hung-worker precursor
        self.progress = ProgressWatchdog(clock=clock, name=name or None)
        # straggler detector state: EWMA steps/s off the heartbeat's
        # step piggyback, compared to the fleet median in check()
        self.rate: Optional[float] = None
        self.last_step: Optional[int] = None
        self.last_step_t: Optional[float] = None
        self.slow = False

    def observe_step(self, step: int, now: float) -> None:
        """Fold a step report into the EWMA rate (advances only — a
        repeated step is the progress watchdog's business)."""
        if self.last_step is None:
            self.last_step, self.last_step_t = step, now
            return
        if step <= self.last_step or now <= self.last_step_t:
            return
        inst = (step - self.last_step) / (now - self.last_step_t)
        self.rate = inst if self.rate is None else (
            STRAGGLER_EWMA_ALPHA * inst
            + (1.0 - STRAGGLER_EWMA_ALPHA) * self.rate)
        self.last_step, self.last_step_t = step, now


class HealthMonitor:
    def __init__(self, on_dead: Callable[[str, int, float, str], None],
                 interval_s: float = DEFAULT_INTERVAL_S,
                 suspect_misses: int = DEFAULT_SUSPECT_MISSES,
                 dead_s: Optional[float] = None,
                 progress_timeout_s: float = 0.0,
                 depart_grace_s: Optional[float] = None,
                 straggler_ratio: float = DEFAULT_STRAGGLER_RATIO,
                 clock: Callable[[], float] = time.monotonic,
                 start_thread: bool = True):
        self._on_dead = on_dead
        self.interval_s = float(interval_s)
        self.suspect_misses = max(int(suspect_misses), 1)
        self.dead_s = float(dead_s) if dead_s is not None \
            else self.interval_s * DEFAULT_DEAD_MULTIPLE
        self.progress_timeout_s = float(progress_timeout_s)
        self.depart_grace_s = float(depart_grace_s) \
            if depart_grace_s is not None \
            else self.dead_s * DEFAULT_DEPART_GRACE_MULTIPLE
        self.straggler_ratio = float(straggler_ratio)  # 0 disables
        self._clock = clock
        self._start_thread = start_thread
        self._lock = threading.Lock()
        self._workers: Dict[Tuple[str, int], _WorkerHealth] = {}
        # workers that announced a planned (preemption) departure,
        # keyed to the announce time: exempt from death/hang verdicts —
        # their silence is expected and must not trigger regeneration
        # ahead of the clean exit.  The exemption is bounded: a worker
        # that announces but never exits within depart_grace_s is
        # wedged, and falls back to the normal dead-worker path
        self._departing: Dict[Tuple[str, int], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_env(cls, on_dead) -> "HealthMonitor":
        interval = heartbeat_interval_s()
        dead_env = os.environ.get("HOROVOD_ELASTIC_HEARTBEAT_DEAD_S")
        grace_env = os.environ.get("HOROVOD_ELASTIC_DEPART_GRACE_S")
        return cls(
            on_dead,
            interval_s=interval,
            suspect_misses=int(os.environ.get(
                "HOROVOD_ELASTIC_HEARTBEAT_SUSPECT_MISSES",
                DEFAULT_SUSPECT_MISSES)),
            dead_s=float(dead_env) if dead_env else None,
            progress_timeout_s=float(os.environ.get(
                "HOROVOD_ELASTIC_PROGRESS_TIMEOUT_S", 0.0)),
            depart_grace_s=float(grace_env) if grace_env else None,
            straggler_ratio=float(os.environ.get(
                "HOROVOD_ELASTIC_STRAGGLER_RATIO",
                DEFAULT_STRAGGLER_RATIO)))

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if not self.enabled or not self._start_thread \
                or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._watch, daemon=True,
            name="hvd_tpu_elastic_health")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _watch(self) -> None:
        poll = max(self.interval_s / 2.0, 0.05)
        while not self._stop.wait(poll):
            # chaos hook: a hang/delay here models a stalled monitor —
            # death detection latency degrades to the process-exit path
            faults.inject("driver.health")
            self.check()

    # -- recording ----------------------------------------------------------

    def record_heartbeat(self, host: str, local_rank: int,
                         step: int = -1) -> None:
        now = self._clock()
        with self._lock:
            if (host, local_rank) in self._departing:
                # a straggler beat sent during the drain window must not
                # re-enroll the worker: its exit is imminent and planned
                return
            w = self._workers.get((host, local_rank))
            if w is None:
                w = _WorkerHealth(now, self._clock,
                                  name=f"{host}:{local_rank}")
                self._workers[(host, local_rank)] = w
            else:
                if w.suspect:
                    hvd_logging.info(
                        "elastic: worker %s:%d resumed heartbeating",
                        host, local_rank)
                w.last_beat = now
                w.suspect = False
            if step >= 0:
                w.progress.update(step, now=now)
                w.observe_step(step, now)

    def mark_departing(self, host: str, local_rank: int) -> None:
        """A planned (preemption-grace) departure was announced: stop
        counting this worker toward death/hang verdicts.  Its eventual
        exit is handled by the driver as graceful (guard/preempt.py)."""
        with self._lock:
            self._departing[(host, local_rank)] = self._clock()
            self._workers.pop((host, local_rank), None)

    def is_departing(self, host: str, local_rank: int) -> bool:
        with self._lock:
            return (host, local_rank) in self._departing

    def forget(self, host: str, local_rank: int) -> None:
        with self._lock:
            self._workers.pop((host, local_rank), None)
            self._departing.pop((host, local_rank), None)

    def purge(self, assigned: set) -> None:
        """Drop entries for workers no longer assigned (driver calls this
        on every reassignment — a removed worker must not be declared
        dead later, and a re-added one must start with a fresh clock)."""
        with self._lock:
            self._workers = {k: w for k, w in self._workers.items()
                             if k in assigned}
            self._departing = {k: t for k, t in self._departing.items()
                               if k in assigned}

    def max_step(self) -> int:
        """Highest training step any monitored worker ever reported —
        the pre-failure peak the chaos probe diffs against the restored
        step to compute ``steps_lost``."""
        with self._lock:
            vals = [w.progress.value for w in self._workers.values()
                    if w.progress.value is not None]
        return max(vals) if vals else -1

    def stragglers(self) -> list:
        """``(host, local_rank)`` keys currently under a
        ``suspect_slow`` verdict (observability-only: no regeneration,
        no quarantine — docs/elastic.md)."""
        with self._lock:
            return [k for k, w in self._workers.items() if w.slow]

    def _check_stragglers(self) -> None:
        """Per-worker EWMA step rate vs the fleet median (caller holds
        the lock).  A worker whose rate falls to ``1/straggler_ratio``
        of the median gets a one-shot ``suspect_slow`` warning and a
        worker-labeled gauge; the verdict clears when it catches back
        up.  Needs >= 2 rated workers — a fleet of one has no median
        worth trusting."""
        if self.straggler_ratio <= 0:
            return
        rated = [(k, w) for k, w in self._workers.items()
                 if w.rate is not None and w.rate > 0]
        if len(rated) < 2:
            return
        med = statistics.median(w.rate for _, w in rated)
        if med <= 0:
            return
        for (host, lr), w in rated:
            ratio = med / w.rate
            _TEL_STRAGGLER.set(ratio, worker=f"{host}:{lr}")
            if ratio >= self.straggler_ratio:
                if not w.slow:
                    w.slow = True
                    hvd_logging.warning(
                        "elastic: worker %s:%d is suspect_slow — "
                        "stepping at %.3g/s vs fleet median %.3g/s "
                        "(%.1fx slower; threshold %.1fx). "
                        "Observability-only: not a death verdict",
                        host, lr, w.rate, med, ratio,
                        self.straggler_ratio)
            elif w.slow:
                w.slow = False
                hvd_logging.info(
                    "elastic: worker %s:%d caught back up "
                    "(%.1fx the fleet median)", host, lr, ratio)

    # -- detection ----------------------------------------------------------

    def check(self, now: Optional[float] = None) -> list:
        """One detection pass; returns the ``(host, local_rank)`` keys
        declared dead/hung (their ``on_dead`` callbacks have run)."""
        if not self.enabled:
            return []
        if now is None:
            now = self._clock()
        dead = []
        max_age = 0.0
        with self._lock:
            _TEL_WORKERS.set(len(self._workers))
            for key, w in list(self._workers.items()):
                age = now - w.last_beat
                max_age = max(max_age, age)
                if age >= self.dead_s:
                    # detect_s: silence span from the last sign of life
                    # to this declaration
                    dead.append((key, age, "missed heartbeats"))
                    del self._workers[key]
                    continue
                if self.progress_timeout_s > 0:
                    stalled = w.progress.stalled_for(now=now)
                    if stalled >= self.progress_timeout_s:
                        dead.append((key, stalled,
                                     "no step progress (hung)"))
                        del self._workers[key]
                        continue
                if not w.suspect and \
                        age >= self.interval_s * self.suspect_misses:
                    w.suspect = True
                    _TEL_SUSPECT.inc()
                    hvd_logging.warning(
                        "elastic: worker %s:%d is suspect — %.0f missed "
                        "heartbeat(s) (%.1fs silent; declared dead at "
                        "%.1fs)", key[0], key[1],
                        age / self.interval_s, age, self.dead_s)
            self._check_stragglers()
            if self.depart_grace_s > 0:
                # bounded exemption: an announced departure that never
                # became a process exit is a wedged worker, not a
                # graceful one — fall back to the dead-worker path
                for key, announced in list(self._departing.items()):
                    waited = now - announced
                    if waited >= self.depart_grace_s:
                        dead.append((key, waited,
                                     "departure grace expired (wedged)"))
                        del self._departing[key]
        _TEL_BEAT_AGE.set(max_age)
        for (host, local_rank), detect_s, reason in dead:
            # verdict telemetry BEFORE the callback: bench.py --chaos
            # and the driver both read detect_s from the registry
            _TEL_DETECT.set(detect_s)
            if "hung" in reason:
                label = "hung"
            elif "departure" in reason:
                label = "depart_grace_expired"
            else:
                label = "missed_heartbeats"
            _TEL_DEATHS.inc(reason=label)
            self._on_dead(host, local_rank, detect_s, reason)
        return [k for k, _, _ in dead]
