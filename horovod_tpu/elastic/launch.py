"""Elastic launch path for the ``hvdrun`` CLI.

Reference: ``horovod/runner/gloo_run.py:274 launch_gloo_elastic`` —
rendezvous server + ``ElasticDriver`` + per-slot worker exec with the
elastic env contract.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from horovod_tpu.elastic.discovery import FixedHosts, HostDiscoveryScript
from horovod_tpu.elastic.driver import ElasticDriver
from horovod_tpu.runner import config_parser, safe_shell_exec
from horovod_tpu.runner.hosts import SlotInfo, parse_hosts
from horovod_tpu.runner.launch import build_worker_command
from horovod_tpu.runner.network import make_secret_key


def run_elastic(args) -> int:
    min_np = args.min_np or args.np
    if not min_np:
        raise SystemExit("elastic mode needs --min-np or -np")
    if args.host_discovery_script:
        discovery = HostDiscoveryScript(args.host_discovery_script,
                                        default_slots=args.slots or 1)
    elif args.hosts:
        discovery = FixedHosts(
            {h.hostname: h.slots for h in parse_hosts(args.hosts)})
    else:
        raise SystemExit(
            "elastic mode needs --host-discovery-script or -H hosts")

    key = make_secret_key()
    from horovod_tpu.elastic.driver import START_TIMEOUT_S

    start_timeout = float(os.environ.get("HOROVOD_ELASTIC_START_TIMEOUT",
                                         START_TIMEOUT_S))
    driver = ElasticDriver(discovery, min_np, args.max_np,
                           timeout=args.elastic_timeout,
                           reset_limit=args.reset_limit or 0,
                           secret_key=key,
                           start_timeout=start_timeout)
    base_env = config_parser.set_env_from_args(dict(os.environ), args)
    driver_host, driver_port = driver.address
    out_dir: Optional[str] = args.output_filename
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    def create_worker_fn(slot: SlotInfo, coordinator: str,
                         generation: int, abort_event=None) -> int:
        env = dict(base_env)
        env.update(slot.to_env())
        env.update({
            "HOROVOD_COORDINATOR_ADDR": coordinator,
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_SECRET_KEY": key,
            "HOROVOD_ELASTIC_DRIVER_ADDR": f"{driver_host}:{driver_port}",
            "HOROVOD_ELASTIC_NOTIFY_ADDR": "1",
            "HOROVOD_ELASTIC_GENERATION": str(generation),
        })
        # pin the warm-start compile cache root for every generation's
        # workers: a respawned worker then restores serialized
        # executables from earlier generations instead of recompiling
        # (runtime/compile_cache.py; HOROVOD_COMPILE_CACHE=0 opts out)
        from horovod_tpu.runtime import compile_cache

        env.setdefault("HOROVOD_COMPILE_CACHE_DIR",
                       compile_cache.default_dir())
        cmd = build_worker_command(slot, args.command, args.ssh_port,
                                   getattr(args, "ssh_identity_file", None))
        stdout = stderr = None
        if out_dir:
            stdout = open(os.path.join(out_dir, f"rank.{slot.rank}.out"), "ab")
            stderr = open(os.path.join(out_dir, f"rank.{slot.rank}.err"), "ab")
        events = [abort_event] if abort_event is not None else None
        try:
            return safe_shell_exec.execute(cmd, env=env, stdout=stdout,
                                           stderr=stderr, events=events)
        finally:
            for f in (stdout, stderr):
                if f:
                    f.close()

    if args.verbose:
        print(f"[launcher] elastic driver at {driver_host}:{driver_port}, "
              f"min_np={min_np} max_np={args.max_np}", file=sys.stderr)
    driver.start(args.np or min_np, create_worker_fn)
    return driver.wait_for_completion()
