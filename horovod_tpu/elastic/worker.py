"""In-worker notification service for elastic host updates.

Reference: ``horovod/runner/elastic/worker.py`` — each worker runs a tiny
service the driver pings with ``HostsUpdatedRequest``; the notification
manager fans the timestamp out to registered ``State`` listeners, which
turn it into ``HostsUpdatedInterrupt`` at the next ``commit()``/
``check_host_updates()``.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from horovod_tpu.utils import logging as hvd_logging


class WorkerNotificationManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners: List = []
        self._service: Optional["WorkerNotificationService"] = None

    def init(self) -> None:
        if self._service is not None:
            return
        secret_key = os.environ.get("HOROVOD_SECRET_KEY")
        addr = os.environ.get("HOROVOD_ELASTIC_NOTIFY_ADDR")
        if addr:
            self._service = WorkerNotificationService(self, secret_key)
            self._service.start()
            # register our address with the driver so it can notify us,
            # and report READY: startup finished, training loop entered
            # (worker-reported readiness — reference registration.py)
            driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
            if driver_addr:
                import socket

                from horovod_tpu.runner.network import (
                    notify_worker_ready,
                    notify_worker_registered,
                )

                notify_worker_registered(driver_addr, self._service.address,
                                         secret_key)
                notify_worker_ready(
                    driver_addr, secret_key,
                    os.environ.get("HOROVOD_HOSTNAME", socket.gethostname()),
                    int(os.environ.get("HOROVOD_LOCAL_RANK", "0")))

    def register_listener(self, listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def handle_hosts_updated(self, timestamp: int, update_res=None) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_hosts_updated(timestamp, update_res)
        hvd_logging.debug("elastic: hosts-updated notification ts=%s",
                          timestamp)


class WorkerNotificationService:
    """TCP listener receiving HostsUpdated pings (lazy import of runner
    network layer; constructed only under an elastic launcher)."""

    def __init__(self, manager: WorkerNotificationManager, secret_key):
        from horovod_tpu.runner.network import NotificationServer

        self._server = NotificationServer(manager, secret_key)

    def start(self) -> None:
        self._server.start()

    @property
    def address(self):
        return self._server.address


def refresh_assignment_from_driver(timeout_s: float = 60.0) -> bool:
    """After a reset, fetch this worker's new identity from the elastic
    driver's rendezvous RPC and export it into the env the runtime reads
    (reference: workers re-read rank/size from the rendezvous on reset,
    ``elastic/rendezvous.py``).  No-op (False) outside elastic runs.

    Waits for a generation STRICTLY newer than the one this worker was
    running: a reset is only ever triggered after something the driver
    will also observe (a worker death → resume, a host change → resume
    after reassignment), so re-initializing against the old generation's
    coordinator would race the driver's reassignment and hang in
    ``jax.distributed.initialize`` waiting for a world that will never
    form again.  A worker whose (host, local_rank) has no slot in the new
    generation was scaled away — it exits 0 (the reference driver stops
    removed workers via the host event; here the worker retires itself).
    """
    import socket
    import sys
    import time

    driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
    if not driver_addr:
        return False
    from horovod_tpu.elastic.driver import GetRankAndSizeRequest
    from horovod_tpu.runner.network import BasicClient

    key = os.environ.get("HOROVOD_SECRET_KEY")
    hostname = os.environ.get("HOROVOD_HOSTNAME", socket.gethostname())
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", "0"))
    known_gen = int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "-1"))
    host, port = driver_addr.rsplit(":", 1)
    client = BasicClient((host, int(port)), key)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        resp = client.request(
            GetRankAndSizeRequest(hostname, local_rank, known_gen))
        if resp.generation > known_gen:
            if resp.slot is None:
                hvd_logging.info(
                    "elastic: (%s, %d) has no slot in generation %d — "
                    "worker removed by scale-down, exiting cleanly",
                    hostname, local_rank, resp.generation)
                sys.exit(0)
            os.environ.update(resp.slot.to_env())
            os.environ["HOROVOD_COORDINATOR_ADDR"] = resp.coordinator_addr
            os.environ["HOROVOD_ELASTIC_GENERATION"] = str(resp.generation)
            hvd_logging.info(
                "elastic: new assignment rank=%d/%d (generation %d)",
                resp.slot.rank, resp.slot.size, resp.generation)
            return True
        time.sleep(0.5)
    raise TimeoutError(
        f"elastic: no new-generation assignment for ({hostname}, "
        f"{local_rank}) from driver within {timeout_s}s")


_manager: Optional[WorkerNotificationManager] = None
_manager_lock = threading.Lock()


def init_notification_manager() -> Optional[WorkerNotificationManager]:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
            try:
                _manager.init()
            except Exception as e:  # non-elastic runs have no driver
                hvd_logging.debug("notification manager init skipped: %s", e)
        return _manager
