"""In-worker notification service for elastic host updates.

Reference: ``horovod/runner/elastic/worker.py`` — each worker runs a tiny
service the driver pings with ``HostsUpdatedRequest``; the notification
manager fans the timestamp out to registered ``State`` listeners, which
turn it into ``HostsUpdatedInterrupt`` at the next ``commit()``/
``check_host_updates()``.

Health plane (docs/faults.md): alongside the notification service each
elastic worker runs a :class:`HeartbeatSender` — a daemon thread beating
to the driver every ``HOROVOD_ELASTIC_HEARTBEAT_INTERVAL`` seconds and
piggybacking the training step counter (:func:`report_step`, bumped by
``TpuState.save()`` on every commit).  The driver's ``HealthMonitor``
turns missing beats into death detection and a stagnant step counter
into hang detection — both *before* the worker process exit is ever
observed.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from horovod_tpu import faults
from horovod_tpu.utils import logging as hvd_logging

# training progress, exported to the driver through heartbeats — written
# by TpuState.save() (one bump per commit), read by the sender thread
_step_lock = threading.Lock()
_current_step = -1


def report_step(step: int) -> None:
    """Record this worker's training progress counter (monotonic; the
    elastic commit count).  Cheap enough to call every step."""
    global _current_step
    with _step_lock:
        if step > _current_step:
            _current_step = step


def current_step() -> int:
    with _step_lock:
        return _current_step


class HeartbeatSender:
    """Daemon thread beating ``(host, local_rank, step)`` to the elastic
    driver.  Send failures are logged at debug and dropped — the next
    beat IS the retry, and a worker must never die because the control
    plane hiccupped."""

    def __init__(self, driver_addr: str, secret_key: Optional[str],
                 host: str, local_rank: int, interval_s: float):
        self._driver_addr = driver_addr
        self._key = secret_key
        self._host = host
        self._local_rank = local_rank
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hvd_tpu_heartbeat_{host}_{local_rank}")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        from horovod_tpu import telemetry
        from horovod_tpu.runner.network import notify_heartbeat

        while not self._stop.wait(self.interval_s):
            try:
                # chaos hook: a hang fault here silences the beats while
                # the process stays alive — exactly the failure mode the
                # driver-side HealthMonitor exists to catch
                faults.inject("worker.heartbeat")
                # metrics piggyback: this rank's counter snapshot rides
                # the beat the way the step counter does, so the driver
                # aggregates rank registries with no extra RPC or thread
                metrics = telemetry.counters_snapshot() \
                    if telemetry.enabled() else None
                notify_heartbeat(self._driver_addr, self._key,
                                 self._host, self._local_rank,
                                 current_step(), metrics=metrics)
            except OSError as e:
                hvd_logging.debug("elastic: heartbeat send failed: %s", e)


class WorkerNotificationManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners: List = []
        self._service: Optional["WorkerNotificationService"] = None
        self._heartbeat: Optional[HeartbeatSender] = None
        # peer-repair provider (guard/repair.py): a callable returning
        # this worker's committed (step, state) snapshot, served to a
        # diverged peer over the notification channel
        self._state_provider = None

    def init(self) -> None:
        if self._service is not None:
            return
        secret_key = os.environ.get("HOROVOD_SECRET_KEY")
        addr = os.environ.get("HOROVOD_ELASTIC_NOTIFY_ADDR")
        if addr:
            self._service = WorkerNotificationService(self, secret_key)
            self._service.start()
            # register our address with the driver so it can notify us,
            # and report READY: startup finished, training loop entered
            # (worker-reported readiness — reference registration.py)
            driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
            if driver_addr:
                import socket

                from horovod_tpu.runner.network import (
                    notify_worker_ready,
                    notify_worker_registered,
                )
                from horovod_tpu.runtime.retry import RetryPolicy

                host = os.environ.get("HOROVOD_HOSTNAME",
                                      socket.gethostname())
                local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", "0"))
                # the driver may still be binding its service when a
                # fast worker comes up — transient connect failures are
                # retried under the unified policy instead of failing
                # the worker's whole startup
                policy = RetryPolicy(name="worker-register",
                                     retry_on=(OSError,))
                faults.inject("worker.register")
                policy.call(notify_worker_registered, driver_addr,
                            self._service.address, secret_key)
                policy.call(notify_worker_ready, driver_addr, secret_key,
                            host, local_rank)
                from horovod_tpu.elastic.health import heartbeat_interval_s

                interval = heartbeat_interval_s()
                if interval > 0:
                    self._heartbeat = HeartbeatSender(
                        driver_addr, secret_key, host, local_rank,
                        interval)
                    self._heartbeat.start()

    def set_state_provider(self, provider) -> None:
        """Install the callable a diverged peer's ``FetchStateRequest``
        is served from: ``provider() -> (step, state)`` or None when
        nothing is committed yet (guard/repair.py).  Typically
        ``lambda: (state._commit_count, state._saved_state)`` guarded by
        the training loop's commit."""
        with self._lock:
            self._state_provider = provider

    def handle_fetch_state(self):
        """NotificationServer dispatch target for FetchStateRequest."""
        with self._lock:
            provider = self._state_provider
        if provider is None:
            return None
        return provider()

    def register_listener(self, listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def handle_hosts_updated(self, timestamp: int, update_res=None) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_hosts_updated(timestamp, update_res)
        hvd_logging.debug("elastic: hosts-updated notification ts=%s",
                          timestamp)


class WorkerNotificationService:
    """TCP listener receiving HostsUpdated pings (lazy import of runner
    network layer; constructed only under an elastic launcher)."""

    def __init__(self, manager: WorkerNotificationManager, secret_key):
        from horovod_tpu.runner.network import NotificationServer

        self._server = NotificationServer(manager, secret_key)

    def start(self) -> None:
        self._server.start()

    @property
    def address(self):
        return self._server.address


def refresh_assignment_from_driver(timeout_s: float = 60.0) -> bool:
    """After a reset, fetch this worker's new identity from the elastic
    driver's rendezvous RPC and export it into the env the runtime reads
    (reference: workers re-read rank/size from the rendezvous on reset,
    ``elastic/rendezvous.py``).  No-op (False) outside elastic runs.

    Waits for a generation STRICTLY newer than the one this worker was
    running: a reset is only ever triggered after something the driver
    will also observe (a worker death → resume, a host change → resume
    after reassignment), so re-initializing against the old generation's
    coordinator would race the driver's reassignment and hang in
    ``jax.distributed.initialize`` waiting for a world that will never
    form again.  A worker whose (host, local_rank) has no slot in the new
    generation was scaled away — it exits 0 (the reference driver stops
    removed workers via the host event; here the worker retires itself).

    Transport failures (a driver mid-restart, a dropped connection) are
    retried with backoff+jitter under the unified policy instead of
    killing the worker — giving up only at ``timeout_s``.
    """
    import socket
    import sys
    import time

    driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
    if not driver_addr:
        return False
    from horovod_tpu.elastic.driver import GetRankAndSizeRequest
    from horovod_tpu.runner.network import BasicClient
    from horovod_tpu.runtime.retry import RetryPolicy

    key = os.environ.get("HOROVOD_SECRET_KEY")
    hostname = os.environ.get("HOROVOD_HOSTNAME", socket.gethostname())
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", "0"))
    known_gen = int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "-1"))
    host, port = driver_addr.rsplit(":", 1)
    client = BasicClient((host, int(port)), key)
    policy = RetryPolicy(name="rendezvous", retry_on=(OSError,),
                         deadline_s=timeout_s)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        faults.inject("worker.rendezvous")
        resp = policy.call(
            client.request,
            GetRankAndSizeRequest(hostname, local_rank, known_gen))
        if resp.generation > known_gen:
            if resp.slot is None:
                hvd_logging.info(
                    "elastic: (%s, %d) has no slot in generation %d — "
                    "worker removed by scale-down, exiting cleanly",
                    hostname, local_rank, resp.generation)
                sys.exit(0)
            os.environ.update(resp.slot.to_env())
            os.environ["HOROVOD_COORDINATOR_ADDR"] = resp.coordinator_addr
            os.environ["HOROVOD_ELASTIC_GENERATION"] = str(resp.generation)
            # a degrade/promote transition re-resolved the plan to this
            # generation's world: export it so the runtime rebuilds the
            # mesh at the CURRENT factorization (elastic/degrade.py);
            # getattr: the driver may predate the plan field
            plan = getattr(resp, "plan", None)
            if plan:
                os.environ["HOROVOD_PLAN"] = plan
            hvd_logging.info(
                "elastic: new assignment rank=%d/%d (generation %d%s)",
                resp.slot.rank, resp.slot.size, resp.generation,
                f", plan {plan}" if plan else "")
            return True
        time.sleep(0.5)
    raise TimeoutError(
        f"elastic: no new-generation assignment for ({hostname}, "
        f"{local_rank}) from driver within {timeout_s}s")


_manager: Optional[WorkerNotificationManager] = None
_manager_lock = threading.Lock()


def init_notification_manager() -> Optional[WorkerNotificationManager]:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
            try:
                _manager.init()
            except Exception as e:  # non-elastic runs have no driver
                hvd_logging.debug("notification manager init skipped: %s", e)
        return _manager


def announce_departure(step: int = -1) -> bool:
    """Worker-side planned-departure announcement: tell the elastic
    driver this process will exit on purpose (preemption grace, serve
    replica drain) so the exit is graceful — no blacklist, no
    quarantine, no sibling abort.  Reads the worker identity from the
    env the driver exported; no-op (False) outside elastic runs.  The
    exemption is bounded by ``HOROVOD_ELASTIC_DEPART_GRACE_S``: a
    worker that announces but wedges instead of exiting falls back to
    the normal dead-worker path."""
    import socket

    driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
    if not driver_addr:
        return False
    from horovod_tpu.runner.network import notify_planned_departure

    if step < 0:
        step = current_step()
    try:
        notify_planned_departure(
            driver_addr, os.environ.get("HOROVOD_SECRET_KEY"),
            os.environ.get("HOROVOD_HOSTNAME", socket.gethostname()),
            int(os.environ.get("HOROVOD_LOCAL_RANK", "0")), step)
        return True
    except OSError as e:
        # best-effort: a dead driver cannot grant grace anyway
        hvd_logging.warning("departure announcement failed: %s", e)
        return False
