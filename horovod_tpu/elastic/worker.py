"""In-worker notification service for elastic host updates.

Reference: ``horovod/runner/elastic/worker.py`` — each worker runs a tiny
service the driver pings with ``HostsUpdatedRequest``; the notification
manager fans the timestamp out to registered ``State`` listeners, which
turn it into ``HostsUpdatedInterrupt`` at the next ``commit()``/
``check_host_updates()``.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from horovod_tpu.utils import logging as hvd_logging


class WorkerNotificationManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._listeners: List = []
        self._service: Optional["WorkerNotificationService"] = None

    def init(self) -> None:
        if self._service is not None:
            return
        secret_key = os.environ.get("HOROVOD_SECRET_KEY")
        addr = os.environ.get("HOROVOD_ELASTIC_NOTIFY_ADDR")
        if addr:
            self._service = WorkerNotificationService(self, secret_key)
            self._service.start()
            # register our address with the driver so it can notify us
            driver_addr = os.environ.get("HOROVOD_ELASTIC_DRIVER_ADDR")
            if driver_addr:
                from horovod_tpu.runner.network import notify_worker_registered

                notify_worker_registered(driver_addr, self._service.address,
                                         secret_key)

    def register_listener(self, listener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def remove_listener(self, listener) -> None:
        with self._lock:
            if listener in self._listeners:
                self._listeners.remove(listener)

    def handle_hosts_updated(self, timestamp: int, update_res=None) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_hosts_updated(timestamp, update_res)
        hvd_logging.debug("elastic: hosts-updated notification ts=%s",
                          timestamp)


class WorkerNotificationService:
    """TCP listener receiving HostsUpdated pings (lazy import of runner
    network layer; constructed only under an elastic launcher)."""

    def __init__(self, manager: WorkerNotificationManager, secret_key):
        from horovod_tpu.runner.network import NotificationServer

        self._server = NotificationServer(manager, secret_key)

    def start(self) -> None:
        self._server.start()

    @property
    def address(self):
        return self._server.address


_manager: Optional[WorkerNotificationManager] = None
_manager_lock = threading.Lock()


def init_notification_manager() -> Optional[WorkerNotificationManager]:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = WorkerNotificationManager()
            try:
                _manager.init()
            except Exception as e:  # non-elastic runs have no driver
                hvd_logging.debug("notification manager init skipped: %s", e)
        return _manager
