"""Framework-independent elastic state + retry loop.

Reference: ``horovod/common/elastic.py`` — ``State`` (commit/
check_host_updates:60-93), ``ObjectState:112``, ``run_fn`` retry loop
(:147-168); TF/torch specializations in ``tensorflow/elastic.py`` /
``torch/elastic.py``.  ``TpuState`` is the JAX specialization: model
params + optimizer state are pytrees, so save/restore is a host-side
pytree copy and ``sync()`` is a ``broadcast_variables`` from rank 0.
"""

from __future__ import annotations

import copy
import queue
import time
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from horovod_tpu.exceptions import HorovodInternalError, HostsUpdatedInterrupt
from horovod_tpu.utils import logging as hvd_logging


class State:
    """Base elastic state (reference ``common/elastic.py:State``).

    Subclasses implement ``save``/``restore``/``sync``.  ``commit()``
    persists a known-good snapshot and then checks for host changes;
    ``check_host_updates()`` alone is the cheap between-batch probe.
    """

    def __init__(self, **kwargs):
        self._host_messages: "queue.Queue" = queue.Queue()
        self._last_updated_timestamp = 0
        self._reset_callbacks = []

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        self._host_messages = queue.Queue()
        self.reset()
        for callback in self._reset_callbacks:
            callback()

    def on_hosts_updated(self, timestamp, update_res=None) -> None:
        """Called by the worker notification service when the driver reports
        a host-set change (reference ``elastic.py:54``)."""
        self._host_messages.put((timestamp, update_res))

    def commit(self) -> None:
        from horovod_tpu import faults

        faults.inject("worker.commit")   # chaos hook: crash/hang at step k
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        """Raise ``HostsUpdatedInterrupt`` if new hosts arrived/left; all
        workers agree on the decision via a max-allreduce of the newest
        timestamp they saw (reference ``elastic.py:70-93``)."""
        last_updated_timestamp = prev_timestamp = self._last_updated_timestamp
        all_update_res = 0
        while not self._host_messages.empty():
            timestamp, update_res = self._host_messages.get()
            if timestamp > last_updated_timestamp:
                last_updated_timestamp = timestamp
                if update_res:
                    all_update_res |= int(update_res)

        # coordinate the view across workers so everyone interrupts together
        prev_timestamp, last_updated_timestamp, all_update_res = \
            self._sync_host_updates(prev_timestamp, last_updated_timestamp,
                                    all_update_res)

        if last_updated_timestamp > prev_timestamp:
            self._last_updated_timestamp = last_updated_timestamp
            raise HostsUpdatedInterrupt(all_update_res == 0)

    def _sync_host_updates(self, prev_ts, last_ts, update_res):
        from horovod_tpu.ops import eager

        if eager.process_mesh().devices.size == 1:
            return prev_ts, last_ts, update_res
        # Rank 0's (prev, last, res) triple is the global truth — the
        # reference broadcasts all three (``elastic.py:84-88``) so the
        # raise decision is all-or-none.  A max-allreduce of each rank's
        # own view deadlocks a freshly-joined worker: its prev is 0 while
        # a survivor's prev already covers the update, so only the new
        # worker would interrupt and wait for a generation that never
        # comes.  int64 goes through the int32-pair-safe metadata
        # exchange (microsecond timestamps overflow int32).  The
        # ``hostsync`` negotiation keeps the wire aligned when some
        # process sits in a join() service loop — it emulates the
        # follow-up 3-word exchange with zeros (and zeros from a joined
        # rank 0 simply mean "no interrupt", which is right: a joined
        # rank has left the training loop).
        eager._negotiate({"kind": "hostsync", "sig": "hostsync"})
        allv = eager._allgather_host_metadata(
            np.asarray([prev_ts, last_ts, update_res], np.int64))
        return int(allv[0, 0]), int(allv[0, 1]), int(allv[0, 2])

    # -- to implement -------------------------------------------------------

    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError

    def reset(self) -> None:
        pass


class ObjectState(State):
    """Elastic state for arbitrary picklable attributes (reference
    ``elastic.py:112``): everything passed as kwargs becomes a synced,
    commit/restorable attribute."""

    def __init__(self, bcast_object: Optional[Callable] = None, **kwargs):
        if bcast_object is None:
            from horovod_tpu.functions import broadcast_object

            bcast_object = broadcast_object
        self._bcast_object = bcast_object
        self._saved_state: Dict[str, Any] = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)
        super().__init__()

    def save(self) -> None:
        new_state = {}
        for attr in self._saved_state.keys():
            new_state[attr] = copy.deepcopy(getattr(self, attr))
        self._saved_state = new_state

    def restore(self) -> None:
        for attr, value in self._saved_state.items():
            setattr(self, attr, copy.deepcopy(value))

    def sync(self) -> None:
        if self._saved_state:
            synced = self._bcast_object(self._saved_state, root_rank=0)
            for attr, value in synced.items():
                setattr(self, attr, value)
                self._saved_state[attr] = copy.deepcopy(value)


class TpuState(ObjectState):
    """JAX/TPU elastic state: pytree model+optimizer state with host-side
    snapshots (the analogue of ``TensorFlowKerasState`` /
    ``TorchState``).

    ``params``/``opt_state`` (and any extra kwargs) are committed as numpy
    host copies — cheap, device-memory-free snapshots — and restored /
    rank-0-broadcast as pytrees.

    ``checkpointer`` (a :class:`horovod_tpu.checkpoint.Checkpointer`)
    additionally persists every Nth commit (``checkpoint_every``,
    default 1) to durable storage through the async writer: the train
    loop still stalls only for the host copy ``commit()`` makes anyway
    — the numpy snapshot is handed to the background thread as-is — so
    a process-loss restart (every previously-assigned host gone, the
    case in-memory commits cannot survive) resumes from disk via
    :meth:`restore_from_checkpoint` instead of losing the run.
    """

    def __init__(self, params=None, opt_state=None, checkpointer=None,
                 checkpoint_every: int = 1, **kwargs):
        self._checkpointer = checkpointer
        self._checkpoint_every = max(int(checkpoint_every), 1)
        self._commit_count = 0
        super().__init__(params=params, opt_state=opt_state, **kwargs)

    def save(self) -> None:
        new_state = {}
        for attr in self._saved_state.keys():
            val = getattr(self, attr)
            new_state[attr] = jax.tree_util.tree_map(
                lambda x: np.asarray(x) if hasattr(x, "shape") else
                copy.deepcopy(x), val)
        self._saved_state = new_state
        self._commit_count += 1
        # progress export: the commit count rides the worker's heartbeats
        # so the driver's hung-rank watchdog sees training advance
        from horovod_tpu.elastic import worker as elastic_worker

        elastic_worker.report_step(self._commit_count)
        from horovod_tpu import telemetry

        telemetry.counter("hvd_elastic_commits_total",
                          "elastic state commits").inc()
        # gauge (not counter): overwritten per commit, so a crash leaves
        # the last durable-loop value for restore's steps_lost diff
        telemetry.gauge("hvd_elastic_steps_committed",
                        "highest committed elastic step").set(
                            self._commit_count)
        telemetry.run_context().advance(step=self._commit_count)
        if self._checkpointer is not None and \
                self._commit_count % self._checkpoint_every == 0:
            # the leaves are already host numpy arrays, so the
            # checkpointer's cut costs only a host memcpy (it copies
            # numpy leaves to own its snapshot) plus thread dispatch —
            # serialization and fsync run behind the loop (checkpoint.py)
            self._checkpointer.save(self._commit_count, self._saved_state)

    def wait(self) -> None:
        """Barrier on the async checkpoint writer (no-op without one)."""
        if self._checkpointer is not None:
            self._checkpointer.wait()

    def priority_commit(self) -> int:
        """A commit that bypasses ``checkpoint_every`` — the degrade
        transition's drain leg (and the preemption-grace ``commit_fn``;
        guard/preempt.py): whatever the interval, THIS commit reaches
        durable storage, so the post-reshard restore replays zero
        steps from the drain point.  Uses :meth:`save`, not
        :meth:`commit`: the world is already changing, so the
        host-update check would raise mid-drain.  Returns the
        committed step; blocks until the writer has it durable."""
        every, self._checkpoint_every = self._checkpoint_every, 1
        try:
            self.save()
        finally:
            self._checkpoint_every = every
        self.wait()
        return self._commit_count

    def restore_from_checkpoint(self, step=None) -> bool:
        """Load the latest (or ``step``-th) durable commit into this
        state's attributes — the cold-restart path when no surviving
        worker holds an in-memory commit.  Returns False when the
        checkpointer has nothing."""
        if self._checkpointer is None:
            return False
        t0 = time.perf_counter()
        if step is None:
            # resolve once (collective when multi-process) so the step is
            # known here, not just inside restore(): the commit counter
            # must continue from it
            step = self._checkpointer._resolve_step()
            if step is None:
                return False
        saved = self._checkpointer.restore(self._saved_state, step=step)
        self._saved_state = saved
        # Continue the step sequence from the restored commit: leaving
        # _commit_count at 0 would make post-restore saves re-use step
        # numbers 1, 2, ... — the checkpointer's keep-highest retention
        # would then GC the fresh low-numbered steps while latest_step()
        # kept answering the stale pre-crash one, so a second crash would
        # lose everything since the first restart.
        self._commit_count = int(step)
        from horovod_tpu.elastic import worker as elastic_worker

        elastic_worker.report_step(self._commit_count)
        self.restore()
        # recovery telemetry (docs/metrics.md): restore latency, the
        # restored step, and steps_lost diffed against the last
        # committed-step gauge — the structured record bench.py --chaos
        # reads instead of re-deriving these from timing locals
        from horovod_tpu import telemetry

        if telemetry.enabled():
            committed = telemetry.value("hvd_elastic_steps_committed")
            telemetry.gauge("hvd_elastic_restore_seconds",
                            "durable-checkpoint restore latency").set(
                                time.perf_counter() - t0)
            telemetry.gauge("hvd_elastic_restored_step",
                            "step the state restored to").set(
                                self._commit_count)
            telemetry.gauge(
                "hvd_elastic_steps_lost",
                "committed-but-not-durable steps lost by the restore"
            ).set(max(int(committed) - self._commit_count, 0))
        return True

    def restore(self) -> None:
        for attr, value in self._saved_state.items():
            setattr(self, attr, value)

    def sync(self) -> None:
        from horovod_tpu.functions import broadcast_variables

        for attr in list(self._saved_state.keys()):
            val = getattr(self, attr)
            if val is None:
                continue
            is_tree = any(hasattr(l, "shape")
                          for l in jax.tree_util.tree_leaves(val))
            if is_tree:
                synced = broadcast_variables(val, root_rank=0,
                                             name=f"elastic.sync.{attr}")
            else:
                synced = self._bcast_object(val, root_rank=0,
                                            name=f"elastic.sync.{attr}")
            setattr(self, attr, synced)
        self.save()


def run(func: Callable) -> Callable:
    """Elastic run decorator (reference ``run_fn``, ``elastic.py:147-168``)::

        @hvd.elastic.run
        def train(state, ...):
            ...

        train(state)

    Loop: notification init → ``state.sync()`` → ``func(state)``; on
    ``HorovodInternalError`` restore committed state, on
    ``HostsUpdatedInterrupt`` continue with live state; then ``reset()``
    (runtime re-init over the new world) and retry.
    """

    def wrapper(state: State, *args, **kwargs):
        from horovod_tpu.elastic.worker import init_notification_manager

        notification_manager = init_notification_manager()
        if notification_manager is not None:
            notification_manager.register_listener(state)

        skip_sync = False
        try:
            while True:
                if not skip_sync:
                    state.sync()
                try:
                    return func(state, *args, **kwargs)
                except HorovodInternalError:
                    hvd_logging.warning(
                        "elastic: collective failure — restoring last "
                        "committed state and re-initializing")
                    state.restore()
                    skip_sync = False
                except HostsUpdatedInterrupt as e:
                    hvd_logging.info(
                        "elastic: host set changed — re-initializing")
                    skip_sync = e.skip_sync
                _reset()
                state.on_reset()
        finally:
            if notification_manager is not None:
                notification_manager.remove_listener(state)

    return wrapper


def _reset() -> None:
    """Tear down and re-initialize the runtime for a changed world.

    The TPU-specific fidelity point (SURVEY §7 hard part #1): XLA programs
    are compiled for a fixed mesh, so a world change means shutdown,
    re-rendezvous via jax.distributed, mesh rebuild, and recompilation of
    every jitted collective — accomplished by clearing the compiled-fn
    caches so first use recompiles against the new mesh.
    """
    from horovod_tpu.elastic.worker import refresh_assignment_from_driver
    from horovod_tpu.ops import eager
    from horovod_tpu.runtime import state as rt_state

    # input pipelines first: their queues hold device batches pinning
    # buffers (and threads issuing device_puts) against the OLD world's
    # backend — they must drain before the client is torn down.  The
    # training fn rebuilds its feed after reset, re-seeded at the
    # restored (epoch, sample position): ShardedDataset positions are
    # world-size independent, so the resharded dataset replays nothing
    # (docs/data.md "Elastic resume").
    from horovod_tpu import data as hvd_data

    n_closed = hvd_data.close_all_pipelines()
    if n_closed:
        hvd_logging.info(
            "elastic: closed %d input pipeline(s) for reset", n_closed)
    rt_state.shutdown()
    # under an elastic launcher: pull the new rank/size/coordinator from
    # the driver's rendezvous before re-initializing
    refresh_assignment_from_driver()
    # leave the old coordination-service world: without this,
    # jax.distributed stays initialized, GlobalState.initialize skips the
    # re-rendezvous, and the rebuilt mesh would still contain dead peers
    from horovod_tpu.runtime import distributed as hvd_dist

    if hvd_dist.elastic_client_active():
        # driver-hosted service: detach without the shutdown barrier
        # (dead peers would block it)
        hvd_dist.disconnect_elastic_client()
    else:
        try:
            if getattr(jax.distributed, "is_initialized", lambda: False)():
                jax.distributed.shutdown()
        except Exception as e:  # pragma: no cover - backend teardown
            hvd_logging.warning(
                "elastic: jax.distributed.shutdown failed: %s", e)
    # The live PJRT client was built against the OLD distributed world (its
    # cross-process collectives hold dead peer connections); re-initializing
    # jax.distributed alone is not enough — the backend must be rebuilt so
    # the new world's client is constructed on first use.
    try:
        import jax.extend.backend as _jeb

        _jeb.clear_backends()
    except Exception as e:  # pragma: no cover - version-dependent API
        hvd_logging.warning("elastic: clear_backends failed: %s", e)
    eager._reset_mesh_cache()   # drops all mesh-capturing eager caches
    jax.clear_caches()   # compiled programs hold the old mesh's devices
    st = rt_state.init()
    # Warm start: clear_backends/clear_caches dropped every in-memory
    # executable, but the persistent compile cache (runtime/compile_cache)
    # survives on disk — init() re-asserted the XLA cache dir, and the
    # rebuilt DistributedTrainStep's first compile consults the AOT
    # store, so a generation whose (mesh, model, knobs) was ever
    # compiled before restarts in seconds instead of re-paying the full
    # XLA pipeline (docs/warmstart.md).
    if st.compile_cache_dir:
        from horovod_tpu.runtime import compile_cache

        hvd_logging.info(
            "elastic: warm-start cache ready at %s (%d AOT entries) — "
            "recompiles for a previously-seen world are disk loads",
            st.compile_cache_dir,
            compile_cache.entry_count(st.compile_cache_dir))
