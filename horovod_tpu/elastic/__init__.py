"""Elastic (fault-tolerant, dynamic-world-size) training.

Reference: ``horovod/common/elastic.py`` (framework-independent State and
retry loop), ``horovod/runner/elastic/`` (driver, discovery, registration,
rendezvous).  The semantics preserved exactly: ``state.sync()`` →
``train(state)`` → on ``HorovodInternalError`` restore to last commit / on
``HostsUpdatedInterrupt`` keep going → ``reset()`` → ``on_reset()`` →
retry.  The TPU-specific hard part — XLA compiles for a static world — is
handled in ``reset()``: the runtime is shut down, jax.distributed
re-initialized against the new rendezvous, meshes rebuilt, and all jitted
collectives recompile on first use (caches are invalidated here).
"""

from horovod_tpu.elastic.degrade import (
    DegradeController,
    DegradeDecision,
    DegradedPlanResolver,
    preserve_global_batch,
)
from horovod_tpu.elastic.state import ObjectState, State, TpuState, run
from horovod_tpu.exceptions import HorovodInternalError, HostsUpdatedInterrupt

__all__ = [
    "State", "ObjectState", "TpuState", "run",
    "HorovodInternalError", "HostsUpdatedInterrupt",
    "DegradeController", "DegradeDecision", "DegradedPlanResolver",
    "preserve_global_batch",
]
