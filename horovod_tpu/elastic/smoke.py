"""Seeded degrade-chaos smoke for ``hvdci`` (analysis/ci.py gate 7).

A sub-second, CPU-only, pure-sim walk of the plan-aware degradation
story (docs/elastic.md "Degraded mode"): a ``dp=4`` world trains with
ZeRO-style sharded optimizer state (momentum + error-feedback
residual, flat fusion-buffer slices), loses half its devices at a
non-boundary step, and the :class:`~horovod_tpu.elastic.degrade.
DegradedPlanResolver` shrinks the plan to ``dp=2``:
``checkpoint.restore_sharded`` re-slices the 4-way shards to 2-way
(residuals included), gradient accumulation doubles to preserve the
global batch, and the lost steps replay.  At the next checkpoint
boundary capacity returns and the controller promotes back to
``dp=4`` — the 2-way shards re-slice to 4-way.  The update math is
elementwise over the flat buffers, so every decomposition is
bit-exact against a never-degraded run: the final state must match
fault-free exactly, and the whole scenario runs twice and must be
bit-identical, so degrade determinism itself is gated.

The three degradation chaos sites fire on their normal no-plan no-op
path here (``degrade.resolve``, ``degrade.reshard``,
``elastic.promote`` — docs/faults.md); fault-plan-driven kills of the
transition are exercised in ``tests/test_degrade.py``.

Returns error strings (empty = pass) in the same idiom as
``guard.smoke`` / ``serve.smoke`` / ``parallel.smoke`` so ci.py folds
it straight into its exit code.  Budget: well under a second — pure
numpy, a tempdir checkpointer, 12 simulated steps.
"""

from __future__ import annotations

import tempfile
from typing import Any, Dict, List

import numpy as np

from horovod_tpu.elastic.degrade import (
    DegradeController, DegradedPlanResolver, preserve_global_batch,
    reshard_restore,
)
from horovod_tpu.parallel.plan import ShardingPlan

PLAN = "dp=4"
WORLD = 4
SHRUNK = 2         # surviving devices after the kill
STEPS = 12
EVERY = 3          # checkpoint_every
KILL_AT = 8        # capacity loss strikes after this step's update
WIDTH = 16         # flat fusion-buffer length (divisible by 4 and 2)
GLOBAL_BATCH = 8
PER_REPLICA_BATCH = 2
SEED = 777


def _grad(step: int) -> np.ndarray:
    # derived from the global step alone so replay sees identical data
    return np.sin(np.arange(WIDTH, dtype=np.float32)
                  * (1.0 + 0.1 * step)).astype(np.float32)


def _train_step(w: np.ndarray, m: np.ndarray, r: np.ndarray,
                step: int):
    """One elementwise optimizer step over the flat buffers: quantize
    grad + residual (error feedback), momentum, apply.  Elementwise,
    so any equal slicing of the buffers reproduces it bit-exactly."""
    g = _grad(step)
    q = (np.round(8.0 * (g + r)) / 8.0).astype(np.float32)
    r = (g + r - q).astype(np.float32)
    m = (0.9 * m + q).astype(np.float32)
    w = (w - 0.1 * m).astype(np.float32)
    return w, m, r


def _fault_free() -> Dict[str, np.ndarray]:
    w = np.full((WIDTH,), 1.5, np.float32)
    m = np.zeros((WIDTH,), np.float32)
    r = np.zeros((WIDTH,), np.float32)
    for s in range(1, STEPS + 1):
        w, m, r = _train_step(w, m, r, s)
    return {"w": w, "m": m, "r": r}


def _save(ckpt, step: int, w, m, r, ranks: int) -> None:
    """Replicated params on rank 0 + one sharded-state file per rank,
    plan-stamped — both layouts in the same step dir, the production
    ZeRO checkpoint shape."""
    ckpt.save(step, {"w": w, "step": step})
    size = WIDTH // ranks
    for rank in range(ranks):
        sl = slice(rank * size, (rank + 1) * size)
        ckpt.save_sharded(step, {"m": m[sl].copy(), "r": r[sl].copy()},
                          rank, ranks, plan=f"dp={ranks}")
    ckpt.wait()


def _restore(ckpt, step: int, ranks: int):
    """Reassemble full buffers from a reshard to ``ranks`` shards —
    the per-rank restore every survivor runs, concatenated so the sim
    keeps training on full vectors."""
    plan = ShardingPlan.from_string(f"dp={ranks}")
    size = WIDTH // ranks
    template = {"m": np.zeros((size,), np.float32),
                "r": np.zeros((size,), np.float32)}
    parts = [reshard_restore(ckpt, template, rank, plan, step=step)
             for rank in range(ranks)]
    rep = ckpt.restore(None, step=step)
    m = np.concatenate([p["m"] for p in parts])
    r = np.concatenate([p["r"] for p in parts])
    return np.asarray(rep["w"]), m, r


def _scenario(root: str) -> Dict[str, Any]:
    resolver = DegradedPlanResolver(PLAN, WORLD, payload_bytes=4 * WIDTH,
                                    compute_s=1e-3)
    ctl = DegradeController(resolver, global_batch=GLOBAL_BATCH,
                            per_replica_batch=PER_REPLICA_BATCH,
                            promote=True, clock=lambda: 0.0)
    from horovod_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(root, use_orbax=False)
    w = np.full((WIDTH,), 1.5, np.float32)
    m = np.zeros((WIDTH,), np.float32)
    r = np.zeros((WIDTH,), np.float32)
    ranks = WORLD
    accums: List[int] = []
    events: List[str] = []
    last_commit = 0
    steps_lost = None
    restored_step = None

    step = 1
    while step <= STEPS:
        w, m, r = _train_step(w, m, r, step)
        if step % EVERY == 0:
            _save(ckpt, step, w, m, r, ranks)
            last_commit = step
            if ctl.degraded and step > KILL_AT:
                # checkpoint boundary with capacity back: promote
                decision = ctl.on_world_change(WORLD, step=step)
                if decision.action == "promote":
                    ranks = decision.plan.total
                    w, m, r = _restore(ckpt, step, ranks)
                    events.append(f"promote@{step}->{ranks}")
        if step == KILL_AT and not ctl.degraded:
            # half the world dies mid-interval: resolve, shrink,
            # reshard-restore the last commit, replay from there
            decision = ctl.on_world_change(SHRUNK, step=step)
            events.append(f"{decision.action}@{step}->"
                          f"{decision.plan_string}")
            ranks = decision.plan.total
            restored_step = last_commit
            steps_lost = step - last_commit
            w, m, r = _restore(ckpt, restored_step, ranks)
            step = restored_step
        accums.append(ctl.grad_accum())
        step += 1

    ref = _fault_free()
    ga = preserve_global_batch(GLOBAL_BATCH,
                               ctl.current_plan, PER_REPLICA_BATCH)
    return {
        "from_plan": ctl.base_plan.to_string(),
        "history": [
            {k: (round(v, 9) if isinstance(v, float) else v)
             for k, v in e.items()} for e in ctl.history],
        "events": events,
        "steps_lost": steps_lost,
        "restored_step": restored_step,
        "promoted_step": ctl.promoted_step,
        "final_plan": ctl.current_plan.to_string(),
        "degraded_at_end": ctl.degraded,
        "grad_accums": accums,
        "grad_accum_final": ga[0],
        "achieved_global_batch": ga[1],
        "final_matches_fault_free": bool(
            np.array_equal(w, ref["w"]) and np.array_equal(m, ref["m"])
            and np.array_equal(r, ref["r"])),
        "final": [round(float(x), 6) for x in w],
    }


def run_smoke() -> List[str]:
    """Run the seeded degrade scenario twice; returns a list of error
    strings (empty = pass)."""
    errors: List[str] = []
    try:
        with tempfile.TemporaryDirectory(
                prefix="hvd_degrade_smoke_") as d1:
            r1 = _scenario(d1)
        with tempfile.TemporaryDirectory(
                prefix="hvd_degrade_smoke_") as d2:
            r2 = _scenario(d2)
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        return [f"degrade-smoke: scenario crashed: "
                f"{type(e).__name__}: {e}"]
    if r1["events"] != [f"shrink@{KILL_AT}->dp={SHRUNK}",
                        f"promote@9->{WORLD}"]:
        errors.append(f"degrade-smoke: transition sequence was "
                      f"{r1['events']}, expected a shrink at the kill "
                      f"and a promote at the next boundary")
    if not r1["final_matches_fault_free"]:
        errors.append("degrade-smoke: shrink->replay->promote state "
                      "diverged from the never-degraded run")
    if r1["steps_lost"] is None or r1["steps_lost"] > EVERY:
        errors.append(f"degrade-smoke: lost {r1['steps_lost']} steps, "
                      f"bound is checkpoint_every={EVERY}")
    if r1["final_plan"] != r1["from_plan"] or r1["degraded_at_end"]:
        errors.append(f"degrade-smoke: ended at {r1['final_plan']} "
                      f"(degraded={r1['degraded_at_end']}), expected "
                      f"promotion back to {r1['from_plan']}")
    if r1["promoted_step"] != 9:
        errors.append(f"degrade-smoke: promoted_step="
                      f"{r1['promoted_step']}, expected 9 (the first "
                      f"checkpoint boundary after the kill)")
    if max(r1["grad_accums"]) != 2 or r1["grad_accum_final"] != 1 \
            or r1["achieved_global_batch"] != GLOBAL_BATCH:
        errors.append(f"degrade-smoke: grad-accum trajectory "
                      f"{r1['grad_accums']} -> {r1['grad_accum_final']} "
                      f"does not preserve the global batch "
                      f"{GLOBAL_BATCH}")
    if r1 != r2:
        errors.append("degrade-smoke: two seeded runs were not "
                      "identical")
    return errors
