"""Plan-aware graceful degradation: keep training through capacity
loss by re-resolving the :class:`~horovod_tpu.parallel.plan.
ShardingPlan` to the surviving topology (docs/elastic.md "Degraded
mode").

Horovod's elastic mode only ever re-runs the *same* layout on whatever
hosts remain; the plan compiler makes a stronger contract possible.
When a slice or host dies, the driver asks a
:class:`DegradedPlanResolver` for the best plan the survivors can
host.  Only the data extents move — ``dp`` shrinks first (replicas are
interchangeable), then ``fsdp`` (re-slices every parameter shard via
``checkpoint.restore_sharded``); the model-parallel axes
(``pp``/``ep``/``sp``/``tp``) are load-bearing, so a loss that eats
into the model extent yields a **wait** decision with a
``HOROVOD_DEGRADE_WAIT_S`` deadline instead of a broken factorization.
Candidates are scored with :func:`~horovod_tpu.analysis.cost_model.
plan_cost_s`, with per-replica compute scaled by the shrink factor
(the global batch is preserved via gradient accumulation, so fewer
replicas each do proportionally more work).

The :class:`DegradeController` holds the current plan across
transitions and drives the state machine::

    FULL --capacity loss--> (resolve) --feasible--> DEGRADED
      ^                         |
      |                         +--model extent lost--> WAITING
      +--capacity regained (next checkpoint boundary)--+

Each transition is: drain -> priority commit
(``TpuState.priority_commit``, the preemption-grace machinery) ->
reshard restore (``checkpoint.restore_sharded``'s dp-extent
resharding, error-feedback residuals included) -> new generation at
the new plan.  Promotion is symmetric and fires only at a checkpoint
boundary, where the shards are already durable at the old extent.

Chaos sites: ``degrade.resolve`` (the verdict), ``degrade.reshard``
(the restore), ``elastic.promote`` (the grow-back) — docs/faults.md.

Knobs (docs/running.md): ``HOROVOD_DEGRADE`` (enable the controller in
``elastic.run``/bench wiring), ``HOROVOD_DEGRADE_WAIT_S``,
``HOROVOD_DEGRADE_MIN_DATA_EXTENT``, ``HOROVOD_DEGRADE_PROMOTE``.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Callable, List, Optional, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.parallel.plan import PlanLike, ShardingPlan, as_plan
from horovod_tpu.utils import logging as hvd_logging

DEFAULT_WAIT_S = 300.0
DEFAULT_MIN_DATA_EXTENT = 1

ENV_DEGRADE = "HOROVOD_DEGRADE"
ENV_WAIT_S = "HOROVOD_DEGRADE_WAIT_S"
ENV_MIN_DATA_EXTENT = "HOROVOD_DEGRADE_MIN_DATA_EXTENT"
ENV_PROMOTE = "HOROVOD_DEGRADE_PROMOTE"

# degradation telemetry (docs/metrics.md, analysis/metrics_schema.py
# DEGRADE_SERIES): the BENCH fields' scrapeable mirror.
_TEL_TRANSITIONS = telemetry.counter(
    "hvd_degrade_transitions_total",
    "plan transitions applied, labeled kind=shrink|promote")
_TEL_WAITS = telemetry.counter(
    "hvd_degrade_waits_total",
    "wait-for-capacity verdicts (model extent did not fit)")
_TEL_ACTIVE = telemetry.gauge(
    "hvd_degrade_active",
    "1 while training below the base plan's device count")
_TEL_DATA_EXTENT = telemetry.gauge(
    "hvd_degrade_data_extent",
    "current dp*fsdp extent (the axis degradation moves)")
_TEL_GRAD_ACCUM = telemetry.gauge(
    "hvd_degrade_grad_accum",
    "gradient-accumulation factor preserving the global batch")
_TEL_TRANSITION_S = telemetry.gauge(
    "hvd_degrade_transition_seconds",
    "wall-clock of the most recent degrade/promote transition")
_TEL_PROMOTED_STEP = telemetry.gauge(
    "hvd_degrade_promoted_step",
    "step at which the plan last grew back toward the base plan")


@dataclasses.dataclass(frozen=True)
class DegradeDecision:
    """One resolver verdict: what the surviving world should run.

    ``action`` is ``keep`` (current plan still fits), ``shrink`` /
    ``promote`` (move to ``plan``), or ``wait`` (the model extent
    itself lost capacity — ``plan`` is None and the caller should
    block up to ``wait_s`` for hosts to return).
    """

    action: str
    plan: Optional[ShardingPlan]
    cost_s: float
    reason: str
    wait_s: float = 0.0

    @property
    def plan_string(self) -> Optional[str]:
        return None if self.plan is None else self.plan.to_string()


def preserve_global_batch(global_batch: int, plan: ShardingPlan,
                          per_replica_batch: int) -> Tuple[int, int]:
    """Gradient-accumulation factor that keeps the optimizer's global
    batch constant across a plan change: ``(grad_accum, achieved)``
    with ``achieved = replicas * per_replica_batch * grad_accum >=
    global_batch`` (rounded up — a degraded world trains on at least
    the configured batch, never a silently smaller one, so the loss
    trajectory stays comparable; docs/elastic.md)."""
    if global_batch < 1 or per_replica_batch < 1:
        raise ValueError(
            f"global_batch and per_replica_batch must be >= 1, got "
            f"{global_batch} and {per_replica_batch}")
    replicas = (plan.dp or 1) * plan.fsdp
    grad_accum = max(1, math.ceil(
        global_batch / (replicas * per_replica_batch)))
    return grad_accum, replicas * per_replica_batch * grad_accum


class DegradedPlanResolver:
    """Enumerate + score the plans a shrunken world can host.

    Pure policy (stdlib + cost model only, no driver state): feasible
    candidates come from :meth:`ShardingPlan.degrade_candidates` (model
    extent fixed, dp shrinks before fsdp); the largest feasible world
    wins, with the cost model (compute stretched by the shrink factor)
    ranking the factorizations of that world.  Deterministic: equal
    costs fall back to the enumeration's preference order.
    """

    def __init__(self, base_plan: PlanLike, n_devices: int,
                 payload_bytes: float = 0.0,
                 n_dcn: int = 1, n_ici: int = 1,
                 compute_s: float = 0.0,
                 min_data_extent: int = DEFAULT_MIN_DATA_EXTENT,
                 wait_s: float = DEFAULT_WAIT_S):
        self.base = as_plan(base_plan).resolve(n_devices)
        self.payload_bytes = float(payload_bytes)
        self.n_dcn = int(n_dcn)
        self.n_ici = int(n_ici)
        self.compute_s = float(compute_s)
        self.min_data_extent = max(1, int(min_data_extent))
        self.wait_s = float(wait_s)

    @classmethod
    def from_env(cls, base_plan: PlanLike, n_devices: int,
                 **kwargs) -> "DegradedPlanResolver":
        kwargs.setdefault("wait_s", float(os.environ.get(
            ENV_WAIT_S, DEFAULT_WAIT_S)))
        kwargs.setdefault("min_data_extent", int(os.environ.get(
            ENV_MIN_DATA_EXTENT, DEFAULT_MIN_DATA_EXTENT)))
        return cls(base_plan, n_devices, **kwargs)

    def min_world(self) -> int:
        """Smallest device count a shrink can land on — below this the
        resolver can only wait."""
        return self.base.model_extent * self.min_data_extent

    def _cost(self, plan: ShardingPlan) -> float:
        from horovod_tpu.analysis import cost_model

        # fewer data replicas each chew through more of the preserved
        # global batch: scale per-replica compute by the shrink factor
        # so the model prefers the largest feasible world
        base_data = (self.base.dp or 1) * self.base.fsdp
        data = (plan.dp or 1) * plan.fsdp
        return cost_model.plan_cost_s(
            plan.to_string(), self.payload_bytes,
            n_dcn=self.n_dcn, n_ici=self.n_ici,
            compute_s=self.compute_s * (base_data / data))

    def candidates(self, n_devices: int) -> List[ShardingPlan]:
        """Feasible plans for ``n_devices``, preference-ordered."""
        return [p for p in self.base.degrade_candidates(n_devices)
                if (p.dp or 1) * p.fsdp >= self.min_data_extent]

    def resolve(self, n_devices: int,
                current: Optional[ShardingPlan] = None
                ) -> DegradeDecision:
        """The best plan for ``n_devices`` surviving devices, relative
        to ``current`` (default: the base plan)."""
        faults.inject("degrade.resolve")
        current = self.base if current is None else current
        cands = self.candidates(n_devices)
        if not cands:
            axes = ", ".join(f"{ax}={getattr(self.base, ax)}"
                             for ax in self.base.model_axes) or "dp=1"
            _TEL_WAITS.inc()
            # ep is stateful in a way tp/sp are not: each ep rank holds
            # DISTINCT expert parameters, so a world below the expert
            # extent has no rank set that can host every expert — name
            # the axis so the operator knows which capacity to restore
            hint = ""
            if self.base.ep > 1:
                hint = (f" (ep={self.base.ep}: the survivors cannot "
                        f"host every expert shard — expert state is "
                        f"only reshardable across the data axes)")
            return DegradeDecision(
                action="wait", plan=None, cost_s=float("inf"),
                reason=(
                    f"{n_devices} surviving device(s) cannot host the "
                    f"load-bearing model extent "
                    f"{self.base.model_extent} ({axes}) at data extent "
                    f">= {self.min_data_extent} — waiting up to "
                    f"{self.wait_s:.0f}s for capacity to return"
                    f"{hint}"),
                wait_s=self.wait_s)
        # largest feasible world first (keeping capacity is never worse
        # — with compute_s=0 the cost model alone would price a
        # 1-replica world as "cheapest" because it has no exchange);
        # plan_cost_s then ranks the factorizations of that world
        # (dp-heavy vs fsdp-heavy splits), and the enumeration's
        # preference order (dp shrinks first) breaks exact cost ties
        scored = sorted(((-p.total, self._cost(p), i, p)
                         for i, p in enumerate(cands)),
                        key=lambda t: t[:3])
        _, cost, _, best = scored[0]
        if best.extents == current.extents:
            return DegradeDecision(
                action="keep", plan=best, cost_s=cost,
                reason=f"plan {best.to_string()} still fits "
                       f"{n_devices} device(s)")
        kind = "shrink" if best.total < current.total else "promote"
        return DegradeDecision(
            action=kind, plan=best, cost_s=cost,
            reason=(
                f"{kind} {current.to_string()} -> {best.to_string()} "
                f"for {n_devices} surviving device(s) "
                f"(cost {cost:.3g}s/step)"))


class DegradeController:
    """The stateful half: current plan, transition history, and the
    batch-preservation arithmetic, driven by the elastic driver (or a
    pure-sim harness — ``clock`` is injectable)."""

    def __init__(self, resolver: DegradedPlanResolver,
                 global_batch: int = 0,
                 per_replica_batch: int = 1,
                 promote: Optional[bool] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._resolver = resolver
        self._current = resolver.base
        self._clock = clock
        self._global_batch = int(global_batch)
        self._per_replica_batch = max(1, int(per_replica_batch))
        if promote is None:
            promote = os.environ.get(ENV_PROMOTE, "1") != "0"
        self._promote = bool(promote)
        self.history: List[dict] = []
        self.promoted_step: Optional[int] = None
        self._publish()

    # -- views --------------------------------------------------------------

    @property
    def base_plan(self) -> ShardingPlan:
        return self._resolver.base

    @property
    def current_plan(self) -> ShardingPlan:
        return self._current

    @property
    def degraded(self) -> bool:
        return self._current.total < self._resolver.base.total

    @property
    def wait_s(self) -> float:
        return self._resolver.wait_s

    def min_world(self) -> int:
        return self._resolver.min_world()

    def grad_accum(self) -> int:
        """Accumulation factor the *current* plan needs to hold the
        configured global batch (1 when no global batch was given)."""
        if self._global_batch < 1:
            return 1
        return preserve_global_batch(
            self._global_batch, self._current, self._per_replica_batch)[0]

    # -- transitions --------------------------------------------------------

    def on_world_change(self, n_devices: int,
                        step: int = -1) -> DegradeDecision:
        """Resolve the new world size and apply the verdict.  Called by
        the driver under reassignment; ``keep``/``wait`` are no-ops on
        controller state (a wait leaves the current plan in place for
        the capacity that may return)."""
        decision = self._resolver.resolve(n_devices,
                                          current=self._current)
        if decision.action == "promote":
            faults.inject("elastic.promote")
            if not self._promote:
                return DegradeDecision(
                    action="keep", plan=self._current,
                    cost_s=decision.cost_s,
                    reason=f"{ENV_PROMOTE}=0 pins the degraded plan "
                           f"{self._current.to_string()}")
        if decision.action in ("shrink", "promote"):
            self._apply(decision, step)
        elif decision.action == "wait":
            hvd_logging.warning("degrade: %s", decision.reason)
        return decision

    def _apply(self, decision: DegradeDecision, step: int) -> None:
        t0 = self._clock()
        prev = self._current
        self._current = decision.plan
        transition_s = max(0.0, self._clock() - t0)
        entry = {
            "kind": decision.action,
            "from_plan": prev.to_string(),
            "to_plan": decision.plan.to_string(),
            "step": step,
            "cost_s": decision.cost_s,
            "grad_accum": self.grad_accum(),
            "transition_s": transition_s,
        }
        self.history.append(entry)
        if decision.action == "promote":
            self.promoted_step = step
            _TEL_PROMOTED_STEP.set(max(step, 0))
        _TEL_TRANSITIONS.inc(kind=decision.action)
        _TEL_TRANSITION_S.set(transition_s)
        self._publish()
        hvd_logging.warning(
            "degrade: %s %s -> %s at step %d (grad_accum=%d): %s",
            decision.action, entry["from_plan"], entry["to_plan"],
            step, entry["grad_accum"], decision.reason)

    def record_transition_s(self, seconds: float) -> None:
        """Stamp the measured wall-clock of the full drain->commit->
        reshard->ready transition over the bookkeeping-only default."""
        if self.history:
            self.history[-1]["transition_s"] = float(seconds)
        _TEL_TRANSITION_S.set(float(seconds))

    def _publish(self) -> None:
        _TEL_ACTIVE.set(1.0 if self.degraded else 0.0)
        _TEL_DATA_EXTENT.set((self._current.dp or 1) * self._current.fsdp)
        _TEL_GRAD_ACCUM.set(self.grad_accum())


def reshard_restore(checkpointer, target, shard_rank: int,
                    plan: ShardingPlan, step: Optional[int] = None):
    """The degrade transition's restore leg: re-slice the sharded
    checkpoint (error-feedback residuals included — they live in the
    sharded optimizer state as flat fusion-buffer slices) to
    ``plan``'s data extent.  Chaos site ``degrade.reshard`` fires
    before any shard is read, so a fault plan can kill the transition
    at its most fragile point (docs/faults.md)."""
    faults.inject("degrade.reshard")
    shard_count = (plan.dp or 1) * plan.fsdp
    return checkpointer.restore_sharded(
        target, shard_rank, shard_count, step=step,
        plan=plan.to_string())


def enabled() -> bool:
    """True when ``HOROVOD_DEGRADE=1`` opts the job into plan-aware
    degradation (off by default: shrinking the world is a policy
    decision, not a safe universal default)."""
    return os.environ.get(ENV_DEGRADE, "0") == "1"
