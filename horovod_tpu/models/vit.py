"""Vision Transformer — the third benchmark-family model.

The reference ships no model zoo (its examples train Keras/torchvision
models); this repo's models play that role for TPU users.  ViT rounds
out the family: vision like ResNet, but matmul-dense like the
transformer — patches feed the MXU directly with none of ResNet's
low-arithmetic-intensity convolutions, so it scales with the same
:class:`~horovod_tpu.models.transformer.Block` stack (tensor-parallel
annotations, flash attention, remat) the LM uses.

TPU-first choices: patchify as one strided conv (a dense matmul on the
MXU), bidirectional attention through the shared blocks
(``TransformerConfig(causal=False)``), RoPE over the flattened patch
sequence instead of a learned position table (nothing extra to shard),
and mean pooling instead of a class token (keeps the sequence length a
power-of-two-friendly ``(image/patch)²`` for flash-attention tiling).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.models.transformer import Block, RMSNorm, TransformerConfig


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"       # dense | flash
    remat: bool = False
    remat_policy: Optional[str] = None  # none|dots|full|offload

    @property
    def num_patches(self) -> int:
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} is not a multiple of "
                f"patch_size {self.patch_size}")
        return (self.image_size // self.patch_size) ** 2

    def transformer(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=1,               # unused: inputs are patches
            num_layers=self.num_layers, num_heads=self.num_heads,
            d_model=self.d_model, d_ff=self.d_ff,
            max_seq_len=self.num_patches, dtype=self.dtype,
            attention_impl=self.attention_impl, causal=False,
            remat=self.remat, remat_policy=self.remat_policy)


class VisionTransformer(nn.Module):
    """``apply(variables, images) -> logits`` over (B, H, W, C) inputs."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        tcfg = cfg.transformer()
        p = cfg.patch_size
        x = x.astype(cfg.dtype)
        # patchify: one strided conv == a dense (p·p·C → d) matmul per
        # patch, the shape the MXU wants
        x = nn.Conv(cfg.d_model, (p, p), strides=(p, p), padding="VALID",
                    dtype=cfg.dtype, name="patch_embed")(x)
        b, gh, gw, d = x.shape
        x = x.reshape(b, gh * gw, d)
        positions = jnp.arange(x.shape[1])
        from horovod_tpu.memory.remat import remat_block, \
            resolve_remat_policy

        block = remat_block(
            Block, resolve_remat_policy(cfg.remat_policy, cfg.remat))
        for i in range(cfg.num_layers):
            x = block(tcfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        x = jnp.mean(x, axis=1)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        name="head")(x.astype(jnp.float32))


ViT_S16 = lambda **kw: VisionTransformer(ViTConfig(  # noqa: E731
    num_layers=12, num_heads=6, d_model=384, d_ff=1536, **kw))
ViT_B16 = lambda **kw: VisionTransformer(ViTConfig(**kw))  # noqa: E731
