"""Switch-style Mixture-of-Experts transformer LM.

Completes the model family around the expert-parallel machinery
(:mod:`horovod_tpu.parallel.expert` — the TPU formulation of the
reference's variable-split alltoall, ``operations.cc:979``, as an MoE
dispatch plane): :class:`SwitchFFN` replaces every second block's MLP
with top-1-routed experts, and :class:`MoETransformerLM` stacks them on
the same attention/RMSNorm/RoPE machinery as
:class:`~horovod_tpu.models.transformer.TransformerLM`.

TPU-first choices, same stance as the rest of the zoo:

* static capacity buckets (no dynamic shapes under jit; over-capacity
  tokens drop, the Switch-Transformer policy);
* expert FFNs run as ONE batched einsum over ``(E, C, d)`` buffers —
  the MXU sees a single large contraction, not per-expert dispatches;
* two execution modes sharing the router and parameters: *local*
  (every device holds all experts — single chip, or experts replicated
  under pure DP) and *expert-parallel* (``ep_axis`` set, call under
  ``shard_map``: experts sharded, tokens moved by ``all_to_all`` via
  :func:`~horovod_tpu.parallel.expert.expert_parallel_ffn` — or by the
  tile-fused ``a2a ⊗ expert-matmul`` ppermute ring when
  ``fused_dispatch`` / ``HOROVOD_MOE_FUSED_DISPATCH`` resolves on,
  overlapping each hop's wire with the previous tile's expert matmul);
* the Switch load-balancing auxiliary loss is sowed under
  ``intermediates/moe_aux_loss`` so training loops can add
  ``aux_weight * mean(aux)`` without threading extra outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.models.transformer import (
    Attention,
    RMSNorm,
    TransformerConfig,
)
from horovod_tpu.parallel.expert import expert_parallel_ffn, top1_routing


@dataclasses.dataclass
class MoEConfig:
    vocab_size: int = 32_000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    flash_block: int = 512
    causal: bool = True
    num_experts: int = 8
    capacity_factor: float = 1.25
    moe_every: int = 2              # every Nth block is MoE (Switch: 2)
    ep_axis: Optional[str] = None   # None: local experts; "ep": sharded
    fused_dispatch: Optional[str] = None  # auto|on|off; None -> env knob
    remat: bool = False
    remat_policy: Optional[str] = None  # none|dots|full|offload

    def resolved_fused_dispatch(self) -> str:
        """The ``fused_dispatch`` mode with the
        ``HOROVOD_MOE_FUSED_DISPATCH`` env-knob fallback applied
        (default ``"auto"`` = TPU-only, docs/fused_kernels.md)."""
        import os
        return (self.fused_dispatch
                or os.environ.get("HOROVOD_MOE_FUSED_DISPATCH")
                or "auto").lower()

    def transformer(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=self.vocab_size, num_layers=self.num_layers,
            num_heads=self.num_heads, d_model=self.d_model,
            d_ff=self.d_ff, max_seq_len=self.max_seq_len,
            dtype=self.dtype, attention_impl=self.attention_impl,
            flash_block=self.flash_block, causal=self.causal,
            remat=self.remat, remat_policy=self.remat_policy)


class SwitchFFN(nn.Module):
    """Top-1-routed expert FFN (gelu MLP experts).

    ``(B, T, D) -> (B, T, D)``; sows ``moe_aux_loss`` (Switch aux:
    ``E * sum_e fraction_e * prob_e``, minimized at uniform routing),
    ``moe_expert_fraction`` (per-expert routed-token share, the
    utilization vector) and ``moe_drop_fraction`` under
    ``intermediates``.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, d = x.shape
        e = cfg.num_experts
        gate_kernel = self.param(
            "gate", nn.initializers.normal(0.02), (d, e), jnp.float32)
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (e, d, cfg.d_ff), jnp.float32)
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (e, cfg.d_ff, d), jnp.float32)
        tokens = x.reshape(b * t, d)

        # Switch aux loss from the router view (identical in both
        # modes; fp32 for a stable softmax)
        scores = tokens.astype(jnp.float32) @ gate_kernel
        probs = jax.nn.softmax(scores, axis=-1)
        chosen = jax.nn.one_hot(jnp.argmax(probs, axis=-1), e,
                                dtype=jnp.float32)
        aux = e * jnp.sum(chosen.mean(0) * probs.mean(0))
        self.sow("intermediates", "moe_aux_loss", aux)
        # per-expert routing share (router view, both modes): the
        # fraction of tokens argmax-assigned to each expert BEFORE
        # capacity drops — uniform is 1/E; the bench emits this so an
        # imbalanced router (and the drops it causes) is visible in
        # the artifact instead of silently inflating active-FLOP MFU
        self.sow("intermediates", "moe_expert_fraction", chosen.mean(0))

        w1c = w1.astype(cfg.dtype)
        w2c = w2.astype(cfg.dtype)

        def expert_mlp(buffers, w1_, w2_):
            """(E?, S, d) -> (E?, S, d): one batched MXU contraction
            per layer across however many experts are present — the
            ONE expert body both modes share (local and EP must never
            diverge in what an expert computes)."""
            h = jnp.einsum("esd,edf->esf", buffers, w1_)
            return jnp.einsum("esf,efd->esd", nn.gelu(h), w2_)

        if cfg.ep_axis is not None:
            # expert-parallel: must be traced inside shard_map with the
            # axis bound.  Each shard applies ITS slice of the experts.
            from jax import lax

            def expert_fn(buffers):
                world = lax.axis_size(cfg.ep_axis)
                e_local = e // world
                idx = lax.axis_index(cfg.ep_axis)
                w1l = lax.dynamic_slice_in_dim(w1c, idx * e_local,
                                               e_local, 0)
                w2l = lax.dynamic_slice_in_dim(w2c, idx * e_local,
                                               e_local, 0)
                return expert_mlp(buffers, w1l, w2l)

            # scores= hands the fp32 routing used for the aux loss to
            # the dispatch plane: the accounted routing IS the
            # dispatched routing, in any compute dtype
            from horovod_tpu.ops.pallas_kernels import \
                resolve_fused_collectives

            fused = resolve_fused_collectives(
                cfg.resolved_fused_dispatch())
            y, dropped = expert_parallel_ffn(
                tokens.astype(cfg.dtype), gate_kernel,
                expert_fn, e, capacity_factor=cfg.capacity_factor,
                axis=cfg.ep_axis, scores=scores, fused=fused)
        else:
            # local mode: same dispatch/combine as the parallel path
            # minus the all_to_alls — numerics are mode-invariant
            capacity = int(max(1, -(-cfg.capacity_factor *
                                    tokens.shape[0] // e)))
            expert_idx, slot, keep, gate = top1_routing(scores, capacity)
            xt = tokens.astype(cfg.dtype)
            dispatch = jnp.zeros((e, capacity, d), cfg.dtype)
            safe_slot = jnp.where(keep, slot, 0)
            dispatch = dispatch.at[expert_idx, safe_slot].add(
                jnp.where(keep[:, None], xt, 0))
            out = expert_mlp(dispatch, w1c, w2c)
            y = out[expert_idx, safe_slot]
            y = jnp.where(keep[:, None],
                          y * gate[:, None].astype(y.dtype), 0)
            dropped = 1.0 - jnp.mean(keep.astype(jnp.float32))
        self.sow("intermediates", "moe_drop_fraction", dropped)
        return y.reshape(b, t, d).astype(cfg.dtype)


class MoEBlock(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions):
        tcfg = self.cfg.transformer()
        x = x + Attention(tcfg, name="attn")(
            RMSNorm(name="ln1")(x), positions)
        return x + SwitchFFN(self.cfg, name="moe")(
            RMSNorm(name="ln2")(x))


class MoETransformerLM(nn.Module):
    """``apply(variables, tokens) -> logits``; every
    ``cfg.moe_every``-th block routes through experts, the rest are the
    dense :class:`~horovod_tpu.models.transformer.Block` MLPs.  Collect
    the aux losses with ``mutable=["intermediates"]`` and add
    ``aux_weight * mean(moe_aux_loss values)`` to the task loss.

    With ``ep_axis`` set, call under ``shard_map`` with *unboxed*
    params (``flax.core.meta.unbox``) — same contract as
    TransformerLM's ring/ulysses modes (manual meshes reject the
    Partitioned metadata's sharding constraints); init with an
    ``ep_axis=None`` twin (identical param tree, no bound axis
    needed).  See ``examples/moe_lm_example.py``."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, positions: Optional[jax.Array] = None):
        from horovod_tpu.models.transformer import Block

        cfg = self.cfg
        tcfg = cfg.transformer()
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        emb = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                       embedding_init=nn.initializers.normal(0.02),
                       name="embed")
        x = emb(tokens)
        from horovod_tpu.memory.remat import remat_block, \
            resolve_remat_policy

        policy = resolve_remat_policy(cfg.remat_policy, cfg.remat)
        for i in range(cfg.num_layers):
            moe = cfg.moe_every and (i + 1) % cfg.moe_every == 0
            cls = remat_block(MoEBlock if moe else Block, policy)
            x = cls(cfg if moe else tcfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        return emb.attend(x.astype(jnp.float32))


def moe_aux_loss(intermediates) -> jax.Array:
    """Mean of the sowed Switch aux losses (0 when none present)."""
    leaves = [v for path, v in
              jax.tree_util.tree_flatten_with_path(intermediates)[0]
              if any(getattr(p, "key", "") == "moe_aux_loss"
                     for p in path)]
    if not leaves:
        return jnp.zeros(())
    return jnp.mean(jnp.stack([jnp.asarray(l, jnp.float32).mean()
                               for l in leaves]))
