"""ResNet v1.5 in flax — the benchmark flagship.

The reference's headline numbers are ResNet-50/101 synthetic benchmarks
(``examples/tensorflow2_synthetic_benchmark.py`` uses
``tf.keras.applications.ResNet50``; scaling table in
``docs/benchmarks.rst:13-43``).  This is the TPU-idiomatic counterpart:

* NHWC layout (TPU-native; channels-last feeds the MXU directly);
* bottleneck v1.5 (stride in the 3x3, matching torchvision/Keras);
* optional bf16 compute with fp32 params/batch-stats — the standard TPU
  mixed-precision recipe;
* BatchNorm runs in inference or train mode via ``train``; cross-replica
  stat sync is available through
  :mod:`horovod_tpu.optim.sync_batch_norm` utilities.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class FusedConvBnRelu3x3(nn.Module):
    """The block's 3x3 segment with the one-pass Pallas backward.

    Forward is plain XLA (conv + affine + relu fuse optimally there);
    the backward is :func:`~horovod_tpu.ops.pallas_kernels.
    fused_conv_bn_relu_bwd` — relu mask, BN dgamma/dbeta reductions, BN
    input scaling, and both conv gradients in ONE pass over the
    tensors, instead of XLA's extra VPU-bound convert+reduce streams
    (the measured ResNet bottleneck, PERF_NOTES.md).  Inference-mode BN
    only (frozen running stats — the synthetic bench's training
    configuration); param/stat names match nn.Conv/nn.BatchNorm but
    nest under this module, so the pytree differs from the unfused
    block — a bench-mode option, not a checkpoint-compatible toggle."""

    features: int
    dtype: Any = jnp.float32
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from horovod_tpu.ops.pallas_kernels import fused_conv_bn_relu

        cin = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (3, 3, cin, self.features), jnp.float32)
        gamma = self.param("scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), jnp.float32)
        mean = self.variable("batch_stats", "mean",
                             lambda: jnp.zeros((self.features,),
                                               jnp.float32))
        var = self.variable("batch_stats", "var",
                            lambda: jnp.ones((self.features,),
                                             jnp.float32))
        return fused_conv_bn_relu(x.astype(self.dtype), kernel, gamma,
                                  beta, mean.value, var.value,
                                  eps=self.eps)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    fused_bwd: bool = False   # inference-BN segments only (see ResNet)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        if self.fused_bwd and self.strides == (1, 1):
            y = FusedConvBnRelu3x3(self.filters, dtype=y.dtype)(y)
        else:
            y = self.conv(self.filters, (3, 3), self.strides)(y)
            y = self.norm()(y)
            y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


def space_to_depth_2x2(x):
    """(N, H, W, C) → (N, H/2, W/2, 4C) pixel shuffle for the TPU stem;
    pure rearrangement — every input value appears exactly once."""
    n, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            f"space_to_depth stem requires even spatial dims, got "
            f"({h}, {w})")
    return x.reshape(n, h // 2, 2, w // 2, 2, c) \
            .transpose(0, 1, 3, 2, 4, 5) \
            .reshape(n, h // 2, w // 2, 4 * c)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32
    # TPU stem variant: 2x2 space-to-depth + 4x4 stride-1 conv instead of
    # the 7x7 stride-2 conv.  The 7x7 stem's 3-channel input wastes MXU
    # lanes and pads badly in HBM; rearranging pixels into channels feeds
    # a dense (112,112,12)->64 conv instead (the standard MLPerf TPU
    # ResNet trick; measured ~+2% end-to-end on v5e, PERF_NOTES.md).
    space_to_depth: bool = False
    # fused one-pass Pallas backward for stride-1 3x3 block segments
    # (FusedConvBnRelu3x3).  Only meaningful with inference-mode BN
    # (train=False — the bench configuration); applied automatically
    # only then.  Changes the param-tree shape of those segments.
    fused_bwd: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.space_to_depth:
            x = space_to_depth_2x2(x)
            x = conv(self.num_filters, (4, 4), (1, 1),
                     padding="SAME", name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        fused = self.fused_bwd and not train
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i, strides,
                                    conv, norm, act,
                                    fused_bwd=fused)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
