"""Decoder-only transformer LM — the long-context flagship.

TPU-first design choices:

* matmul-heavy blocks sized for the MXU, bf16 compute / fp32 params;
* RMSNorm + rotary embeddings (no learned position table to shard);
* tensor parallelism by annotation:
  :class:`~horovod_tpu.parallel.tensor_parallel.ColumnParallelDense` /
  ``RowParallelDense`` carry kernel partition specs, so under ``jit``
  over a mesh with a ``tp`` axis XLA places one reduction per block;
* sequence parallelism by construction: ``attention_impl="ring"`` or
  ``"ulysses"`` wraps the attention core in ``shard_map`` over the
  ``sp`` axis (ring ppermute / all_to_all head exchange), enabling
  contexts that exceed one chip's HBM;
* ``remat`` applies ``jax.checkpoint`` per block — recompute activations
  in backward instead of holding them in HBM.

The reference has no model zoo beyond examples; this plays the role of
its ResNet-50 benchmark flagship (``examples/tensorflow2_synthetic_benchmark.py``)
for the long-context/LLM regime.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.parallel.mesh import AXIS_SP, AXIS_TP
from horovod_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)
from horovod_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)
from horovod_tpu.parallel.ulysses import ulysses_attention


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32_000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"       # dense | flash | ring | ulysses
    flash_block: int = 512              # flash q/k block (512 = round-4
                                        # measured winner; autotunable)
    causal: bool = True                 # False: bidirectional (ViT/BERT)
    sp_axis: str = AXIS_SP
    tp_axis: str = AXIS_TP
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    def tpu_efficiency_hints(self) -> list:
        """Measured-on-v5e shape advice (PERF_NOTES.md round 4): the MXU
        is a 128x128 systolic array, and head_dim 64 configs measured
        12-13 MFU points below head_dim 128 at every model size.
        Returns human-readable hints (empty = no issues)."""
        hints = []
        if self.d_model % 128:
            hints.append(
                f"d_model {self.d_model} is not a multiple of 128; "
                f"matmul tiles will be padded")
        elif self.head_dim < 128:
            # suggest only divisors of d_model so the advised config is
            # always constructible; d_model % 128 == 0 guarantees one
            suggestion = next(h for h in range(self.d_model // 128, 0, -1)
                              if self.d_model % h == 0)
            hints.append(
                f"head_dim {self.head_dim} < 128 underfills the MXU "
                f"(128-lane systolic array): fewer, wider heads measured "
                f"+12-13 MFU points on v5e (PERF_NOTES.md); consider "
                f"num_heads={suggestion}")
        return hints


def rotary_embedding(x: jax.Array, positions: jax.Array,
                     base: float = 10_000.0) -> jax.Array:
    """Rotate pairs of head dims by position-dependent angles (RoPE).
    ``x``: (b, t, h, d); ``positions``: (t,) global positions — under
    sequence parallelism each shard passes its global offsets."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


class RMSNorm(nn.Module):
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(),
                           (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon)
        return (y * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h, d = cfg.num_heads, cfg.head_dim
        # fused QKV projection, column-parallel over tp (heads shard)
        qkv = ColumnParallelDense(3 * cfg.d_model, axis=cfg.tp_axis,
                                  use_bias=False, dtype=cfg.dtype,
                                  name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = x.shape[:2] + (h, d)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)

        if cfg.attention_impl == "dense":
            o = reference_attention(q, k, v, causal=cfg.causal)
        elif cfg.attention_impl == "flash":
            from horovod_tpu.ops.pallas_kernels import flash_attention

            o = flash_attention(q, k, v, causal=cfg.causal,
                                block_q=cfg.flash_block,
                                block_k=cfg.flash_block)
        elif cfg.attention_impl == "ring":
            o = ring_attention(q, k, v, cfg.sp_axis, causal=cfg.causal)
        elif cfg.attention_impl == "ulysses":
            o = ulysses_attention(q, k, v, cfg.sp_axis, causal=cfg.causal)
        else:
            raise ValueError(
                f"unknown attention_impl {cfg.attention_impl!r}")
        o = o.reshape(x.shape[:2] + (cfg.d_model,))
        # output projection, row-parallel: closes the block's tp reduction
        return RowParallelDense(cfg.d_model, axis=cfg.tp_axis,
                                use_bias=False, dtype=cfg.dtype,
                                name="proj")(o)


class MlpBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = ColumnParallelDense(cfg.d_ff, axis=cfg.tp_axis, use_bias=False,
                                dtype=cfg.dtype, name="wi")(x)
        h = nn.gelu(h)
        return RowParallelDense(cfg.d_model, axis=cfg.tp_axis,
                                use_bias=False, dtype=cfg.dtype,
                                name="wo")(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(name="ln1")(x), positions)
        x = x + MlpBlock(self.cfg, name="mlp")(RMSNorm(name="ln2")(x))
        return x


_hinted_shapes: set = set()   # perf hints emitted once per shape


class TransformerLM(nn.Module):
    """``apply(variables, tokens, positions=None) -> logits``.

    ``tokens``: (batch, seq_local) int32.  ``positions``: (seq_local,)
    global positions; defaults to ``arange`` (correct without sequence
    parallelism — under SP pass each shard's global offsets).

    Execution modes: under plain ``jit`` over a mesh the tp-annotated
    kernels shard automatically (GSPMD).  Under ``shard_map`` (required
    for ``attention_impl="ring"``/``"ulysses"``) pass *unboxed* params —
    ``flax.core.meta.unbox(variables)`` — since manual-mesh code can't
    apply GSPMD sharding constraints.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions: Optional[jax.Array] = None):
        cfg = self.cfg
        shape_key = (cfg.d_model, cfg.num_heads)
        if shape_key not in _hinted_shapes:     # once per process, cheap
            import horovod_tpu

            # only mark hinted once a TPU was actually present — a CPU
            # trace before hvd.init() must not suppress the hint forever
            if horovod_tpu.tpu_available():
                _hinted_shapes.add(shape_key)
                from horovod_tpu.utils import logging as hvd_logging

                for hint in cfg.tpu_efficiency_hints():
                    hvd_logging.info("TransformerLM perf hint: %s", hint)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       dtype=cfg.dtype,
                       embedding_init=nn.initializers.normal(0.02),
                       name="embed")
        x = emb(tokens)
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        # tied output head: logits in fp32 for a stable softmax
        return emb.attend(x.astype(jnp.float32))


def lm_loss(variables, model: TransformerLM, tokens: jax.Array,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross-entropy (mean over the local shard)."""
    logits = model.apply(variables, tokens[:, :-1],
                         positions[:-1] if positions is not None else None)
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:]).mean()
