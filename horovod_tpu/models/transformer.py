"""Decoder-only transformer LM — the long-context flagship.

TPU-first design choices:

* matmul-heavy blocks sized for the MXU, bf16 compute / fp32 params;
* RMSNorm + rotary embeddings (no learned position table to shard);
* tensor parallelism by annotation:
  :class:`~horovod_tpu.parallel.tensor_parallel.ColumnParallelDense` /
  ``RowParallelDense`` carry kernel partition specs, so under ``jit``
  over a mesh with a ``tp`` axis XLA places one reduction per block;
* sequence parallelism by construction: ``attention_impl="ring"`` or
  ``"ulysses"`` wraps the attention core in ``shard_map`` over the
  ``sp`` axis (ring ppermute / all_to_all head exchange), enabling
  contexts that exceed one chip's HBM;
* ``remat`` applies ``jax.checkpoint`` per block — recompute activations
  in backward instead of holding them in HBM.

The reference has no model zoo beyond examples; this plays the role of
its ResNet-50 benchmark flagship (``examples/tensorflow2_synthetic_benchmark.py``)
for the long-context/LLM regime.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from horovod_tpu.parallel.mesh import AXIS_SP, AXIS_TP
from horovod_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)
from horovod_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)
from horovod_tpu.parallel.ulysses import ulysses_attention


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32_000
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"       # dense | flash | ring | ulysses
    flash_block: int = 512              # flash q/k block (512 = round-4
                                        # measured winner; autotunable)
    causal: bool = True                 # False: bidirectional (ViT/BERT)
    sp_axis: str = AXIS_SP
    tp_axis: str = AXIS_TP
    remat: bool = False
    # per-block remat tier (none|dots|full|offload) — overrides the
    # boolean when set; resolution order and the memory/recompute
    # trade of each tier: memory/remat.py, docs/memory.md
    remat_policy: Optional[str] = None
    # tile-fused matmul⊗collective kernels at the tp boundaries
    # (HOROVOD_FUSED_COLLECTIVES, docs/fused_kernels.md) — consumed by
    # :func:`fused_tp_apply`, the explicit shard_map execution mode,
    # and by the ring attention dispatch (``attention_impl="ring"``:
    # "auto" defers to HOROVOD_SP_FUSED_RING / HOROVOD_FUSED_COLLECTIVES
    # so env knobs stay live; "on"/"off" here wins).  The GSPMD modules
    # below ignore it (XLA owns their collectives)
    fused_collectives: str = "auto"     # auto | on | off
    # sp sequence layout for the ring path — None defers to
    # HOROVOD_SP_LAYOUT (default "contiguous"); "zigzag" load-balances
    # the causal mask across ranks (docs/fused_kernels.md)
    sp_layout: Optional[str] = None     # None | contiguous | zigzag
    # run the flash/ring-flash Pallas kernels in interpreter mode so
    # the CPU twin exercises the REAL blocked memory behavior instead
    # of the dense jnp fallback (which materializes the (T, T) scores
    # the kernels exist to avoid) — bench/test plumbing, never on-TPU
    flash_interpret: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    def tpu_efficiency_hints(self) -> list:
        """Measured-on-v5e shape advice (PERF_NOTES.md round 4): the MXU
        is a 128x128 systolic array, and head_dim 64 configs measured
        12-13 MFU points below head_dim 128 at every model size.
        Returns human-readable hints (empty = no issues)."""
        hints = []
        if self.d_model % 128:
            hints.append(
                f"d_model {self.d_model} is not a multiple of 128; "
                f"matmul tiles will be padded")
        elif self.head_dim < 128:
            # suggest only divisors of d_model so the advised config is
            # always constructible; d_model % 128 == 0 guarantees one
            suggestion = next(h for h in range(self.d_model // 128, 0, -1)
                              if self.d_model % h == 0)
            hints.append(
                f"head_dim {self.head_dim} < 128 underfills the MXU "
                f"(128-lane systolic array): fewer, wider heads measured "
                f"+12-13 MFU points on v5e (PERF_NOTES.md); consider "
                f"num_heads={suggestion}")
        return hints


def rotary_embedding(x: jax.Array, positions: jax.Array,
                     base: float = 10_000.0) -> jax.Array:
    """Rotate pairs of head dims by position-dependent angles (RoPE).
    ``x``: (b, t, h, d); ``positions``: (t,) global positions — under
    sequence parallelism each shard passes its global offsets."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


class RMSNorm(nn.Module):
    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(),
                           (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.epsilon)
        return (y * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        h, d = cfg.num_heads, cfg.head_dim
        # fused QKV projection, column-parallel over tp (heads shard)
        qkv = ColumnParallelDense(3 * cfg.d_model, axis=cfg.tp_axis,
                                  use_bias=False, dtype=cfg.dtype,
                                  name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = x.shape[:2] + (h, d)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)

        if cfg.attention_impl == "dense":
            o = reference_attention(q, k, v, causal=cfg.causal)
        elif cfg.attention_impl == "flash":
            from horovod_tpu.ops.pallas_kernels import flash_attention

            o = flash_attention(q, k, v, causal=cfg.causal,
                                block_q=cfg.flash_block,
                                block_k=cfg.flash_block,
                                interpret=cfg.flash_interpret)
        elif cfg.attention_impl == "ring":
            # "auto" stays None so the HOROVOD_SP_* env knobs resolve
            # inside the dispatch; an explicit config "on"/"off" wins
            o = ring_attention(
                q, k, v, cfg.sp_axis, causal=cfg.causal,
                fused=(None if cfg.fused_collectives == "auto"
                       else cfg.fused_collectives),
                layout=cfg.sp_layout,
                block_q=cfg.flash_block, block_k=cfg.flash_block,
                interpret=cfg.flash_interpret)
        elif cfg.attention_impl == "ulysses":
            o = ulysses_attention(q, k, v, cfg.sp_axis, causal=cfg.causal)
        else:
            raise ValueError(
                f"unknown attention_impl {cfg.attention_impl!r}")
        o = o.reshape(x.shape[:2] + (cfg.d_model,))
        # output projection, row-parallel: closes the block's tp reduction
        return RowParallelDense(cfg.d_model, axis=cfg.tp_axis,
                                use_bias=False, dtype=cfg.dtype,
                                name="proj")(o)


class MlpBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = ColumnParallelDense(cfg.d_ff, axis=cfg.tp_axis, use_bias=False,
                                dtype=cfg.dtype, name="wi")(x)
        h = nn.gelu(h)
        return RowParallelDense(cfg.d_model, axis=cfg.tp_axis,
                                use_bias=False, dtype=cfg.dtype,
                                name="wo")(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(name="ln1")(x), positions)
        x = x + MlpBlock(self.cfg, name="mlp")(RMSNorm(name="ln2")(x))
        return x


_hinted_shapes: set = set()   # perf hints emitted once per shape


class TransformerLM(nn.Module):
    """``apply(variables, tokens, positions=None) -> logits``.

    ``tokens``: (batch, seq_local) int32.  ``positions``: (seq_local,)
    global positions; defaults to ``arange`` (correct without sequence
    parallelism — under SP pass each shard's global offsets).

    Execution modes: under plain ``jit`` over a mesh the tp-annotated
    kernels shard automatically (GSPMD).  Under ``shard_map`` (required
    for ``attention_impl="ring"``/``"ulysses"``) pass *unboxed* params —
    ``flax.core.meta.unbox(variables)`` — since manual-mesh code can't
    apply GSPMD sharding constraints.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions: Optional[jax.Array] = None):
        cfg = self.cfg
        shape_key = (cfg.d_model, cfg.num_heads)
        if shape_key not in _hinted_shapes:     # once per process, cheap
            import horovod_tpu

            # only mark hinted once a TPU was actually present — a CPU
            # trace before hvd.init() must not suppress the hint forever
            if horovod_tpu.tpu_available():
                _hinted_shapes.add(shape_key)
                from horovod_tpu.utils import logging as hvd_logging

                for hint in cfg.tpu_efficiency_hints():
                    hvd_logging.info("TransformerLM perf hint: %s", hint)
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        emb = nn.Embed(cfg.vocab_size, cfg.d_model,
                       dtype=cfg.dtype,
                       embedding_init=nn.initializers.normal(0.02),
                       name="embed")
        x = emb(tokens)
        from horovod_tpu.memory.remat import remat_block, \
            resolve_remat_policy

        block = remat_block(
            Block, resolve_remat_policy(cfg.remat_policy, cfg.remat))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, positions)
        x = RMSNorm(name="ln_f")(x)
        # tied output head: logits in fp32 for a stable softmax
        return emb.attend(x.astype(jnp.float32))


def lm_loss(variables, model: TransformerLM, tokens: jax.Array,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """Next-token cross-entropy (mean over the local shard)."""
    logits = model.apply(variables, tokens[:, :-1],
                         positions[:-1] if positions is not None else None)
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        logits, tokens[:, 1:]).mean()


# ---------------------------------------------------------------------------
# tile-fused sequence-parallel execution mode (docs/fused_kernels.md)
# ---------------------------------------------------------------------------

def _rms(x, scale, epsilon=1e-6):
    """RMSNorm as a function of the unboxed ``scale`` param — the exact
    math of :class:`RMSNorm` (per-token, so it runs on token shards)."""
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + epsilon)
    return (y * scale).astype(x.dtype)


def fused_tp_apply(variables, cfg: TransformerConfig, tokens: jax.Array,
                   positions: Optional[jax.Array] = None,
                   fused: Optional[bool] = None,
                   interpret: bool = False) -> jax.Array:
    """TransformerLM forward with tile-fused collectives at every
    tensor-parallel boundary — the explicit shard_map twin of
    ``TransformerLM.apply``.

    Run inside ``shard_map`` over ``cfg.tp_axis`` with *unboxed*
    replicated variables (``flax.core.meta.unbox``); returns the same
    logits as the GSPMD ``apply``.  Where the annotated modules close
    each block with one boundary-wide psum, this path restructures to
    Megatron-SP: activations stay **token-sharded** between blocks
    (RMSNorm and residuals are per-token), each column boundary gathers
    tokens *inside* the matmul
    (:func:`~horovod_tpu.parallel.tensor_parallel.column_parallel_dense_ag`)
    and each row boundary reduce-scatters them back
    (:func:`~horovod_tpu.parallel.tensor_parallel.row_parallel_dense_rs`)
    — tile k's wire hides under tile k+1's MXU compute, so no serial
    full-width collective survives at any parallelism boundary (the
    HLO guard pins ring permutes, zero all-reduces).  The one
    remaining gather is the final-logits all-gather after ``ln_f``.

    Shape contract: ``seq % tp``, ``num_heads % tp`` and
    ``d_ff % tp`` must be 0.  ``fused=None`` resolves
    ``cfg.fused_collectives`` (``"auto"`` = TPU only); ``fused=False``
    keeps the same SP structure with unfused boundary collectives —
    the numerics-pinning baseline.
    """
    from jax import lax

    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives
    from horovod_tpu.parallel.tensor_parallel import (
        column_parallel_dense_ag,
        row_parallel_dense_rs,
    )

    if cfg.attention_impl not in ("dense", "flash"):
        raise ValueError(
            f"fused_tp_apply supports attention_impl dense|flash, got "
            f"{cfg.attention_impl!r} (ring/ulysses already own their "
            f"sequence axis)")
    if fused is None:
        fused = resolve_fused_collectives(cfg.fused_collectives)
    params = variables.get("params", variables)
    axis = cfg.tp_axis
    w = int(jax.lax.axis_size(axis))
    me = lax.axis_index(axis)
    b, t = tokens.shape
    d, heads = cfg.d_model, cfg.num_heads
    if t % w or heads % w or cfg.d_ff % w:
        raise ValueError(
            f"fused_tp_apply needs seq ({t}), num_heads ({heads}) and "
            f"d_ff ({cfg.d_ff}) divisible by the {axis!r} extent {w}")
    t_loc, d_loc, f_loc = t // w, d // w, cfg.d_ff // w
    h_loc, hd = heads // w, cfg.head_dim
    if positions is None:
        positions = jnp.arange(t)

    def col_shard(kernel, width):
        return lax.dynamic_slice_in_dim(kernel, me * width, width, axis=1)

    def row_shard(kernel, width):
        return lax.dynamic_slice_in_dim(kernel, me * width, width, axis=0)

    def to_rank_major(full):
        """(b, t, f) natural tokens → (w·b·t_loc, f) rank-major rows —
        the layout matmul_reducescatter scatters over."""
        f = full.shape[-1]
        return full.reshape(b, w, t_loc, f).transpose(1, 0, 2, 3) \
            .reshape(w * b * t_loc, f)

    def from_gathered(rows, f):
        """(w·b·t_loc, f) rank-major gather output → (b, t, f) natural."""
        return rows.reshape(w, b, t_loc, f).transpose(1, 0, 2, 3) \
            .reshape(b, t, f)

    def shard2d(x_shard):
        return x_shard.reshape(b * t_loc, x_shard.shape[-1])

    emb = params["embed"]["embedding"]
    x = jnp.take(emb.astype(cfg.dtype), tokens, axis=0)   # (b, t, d)
    # token-shard the residual stream: rank r owns tokens
    # [r·t_loc, (r+1)·t_loc) of every batch row
    x_shard = lax.dynamic_slice_in_dim(x, me * t_loc, t_loc, axis=1)

    for i in range(cfg.num_layers):
        layer = params[f"layer_{i}"]
        # -- attention: AG⊗qkv-matmul → core → proj-matmul⊗RS
        h = _rms(x_shard, layer["ln1"]["scale"])
        qkv_k = layer["attn"]["qkv"]["kernel"].astype(cfg.dtype)
        # per-matrix column shards: a contiguous slice of the fused
        # (d, 3d) kernel would span only one of q/k/v at tp > 3
        wq, wk, wv = (qkv_k[:, j * d:(j + 1) * d] for j in range(3))
        wqkv = jnp.concatenate(
            [col_shard(m, d_loc) for m in (wq, wk, wv)], axis=1)
        qkv = column_parallel_dense_ag(
            shard2d(h).astype(cfg.dtype), wqkv, axis=axis, fused=fused,
            interpret=interpret)
        q, k, v = jnp.split(from_gathered(qkv, 3 * d_loc), 3, axis=-1)
        shape = (b, t, h_loc, hd)
        q, k, v = (a.reshape(shape) for a in (q, k, v))
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)
        if cfg.attention_impl == "flash":
            from horovod_tpu.ops.pallas_kernels import flash_attention

            o = flash_attention(q, k, v, causal=cfg.causal,
                                block_q=cfg.flash_block,
                                block_k=cfg.flash_block)
        else:
            o = reference_attention(q, k, v, causal=cfg.causal)
        o = o.reshape(b, t, h_loc * hd)
        proj_k = layer["attn"]["proj"]["kernel"].astype(cfg.dtype)
        y = row_parallel_dense_rs(
            to_rank_major(o).astype(cfg.dtype),
            row_shard(proj_k, d_loc), axis=axis, fused=fused,
            interpret=interpret)
        x_shard = x_shard + y.reshape(b, t_loc, d)

        # -- MLP: AG⊗wi-matmul → gelu → wo-matmul⊗RS.  The activation
        # stays rank-major between the two boundaries — gelu is
        # elementwise, so no natural-order round trip is needed
        h = _rms(x_shard, layer["ln2"]["scale"])
        wi = col_shard(layer["mlp"]["wi"]["kernel"].astype(cfg.dtype),
                       f_loc)
        wo = row_shard(layer["mlp"]["wo"]["kernel"].astype(cfg.dtype),
                       f_loc)
        hh = column_parallel_dense_ag(
            shard2d(h).astype(cfg.dtype), wi, axis=axis, fused=fused,
            interpret=interpret)
        hh = nn.gelu(hh)
        y = row_parallel_dense_rs(hh.astype(cfg.dtype), wo, axis=axis,
                                  fused=fused, interpret=interpret)
        x_shard = x_shard + y.reshape(b, t_loc, d)

    x_shard = _rms(x_shard, params["ln_f"]["scale"])
    # the one boundary-wide gather left: reassemble tokens for the tied
    # logits head (rank-major chunks → natural order)
    full = lax.all_gather(x_shard, axis, tiled=False)    # (w, b, t_loc, d)
    x = full.transpose(1, 0, 2, 3).reshape(b, t, d)
    # tied head, exactly flax Embed.attend's promotion: both operands
    # to cfg.dtype (promote_dtype(dtype=self.dtype)) before the dot
    query = x.astype(jnp.float32)
    if cfg.dtype is not None:
        query = query.astype(cfg.dtype)
        emb = emb.astype(cfg.dtype)
    return jnp.dot(query, emb.T)
