"""Model zoo for benchmarks and examples.

The reference ships its benchmark models via ``tf.keras.applications`` /
``torchvision.models`` in ``examples/*_synthetic_benchmark.py``; this
package provides the TPU-native (flax, NHWC, bf16-friendly) equivalents
used by ``examples/`` and ``bench.py``.
"""

from horovod_tpu.models.moe import (
    MoEConfig,
    MoETransformerLM,
    moe_aux_loss,
)
from horovod_tpu.models.resnet import ResNet50, ResNet101, ResNet152
from horovod_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    lm_loss,
)
from horovod_tpu.models.vit import (
    ViT_B16,
    ViT_S16,
    ViTConfig,
    VisionTransformer,
)

__all__ = ["ResNet50", "ResNet101", "ResNet152",
           "TransformerLM", "TransformerConfig", "lm_loss",
           "MoETransformerLM", "MoEConfig", "moe_aux_loss",
           "VisionTransformer", "ViTConfig", "ViT_S16", "ViT_B16"]
