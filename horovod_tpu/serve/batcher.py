"""Continuous batcher: packs compatible requests into executables.

The batcher is the serving plane's engine loop.  Each :meth:`step`
drains up to ``HOROVOD_SERVE_MAX_BATCH`` batch-compatible requests
(same :func:`~horovod_tpu.serve.request.payload_signature`) from the
admission queue, leases them to a SERVING replica picked round-robin
from the pool, and feeds the observed service time back to the queue's
admission controller.  Run it inline (tests, bench — deterministic on
a logical clock) or as a background feeder thread (:meth:`start` /
:meth:`stop`, the production shape).

:class:`ExecutableCache` is the hot-swap layer to the AOT store
(runtime/compile_cache.py): batch sizes are bucketed so a handful of
padded executables cover every occupancy, each bucket compiled once
and — with the persistent cache enabled — deserialized from disk on
the next replica start instead of recompiled.

Fault site ``serve.feed`` fires at the top of every step; a ``hang``
there models a wedged queue feeder (docs/faults.md).
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_int
from horovod_tpu.serve.pool import ReplicaPool
from horovod_tpu.serve.queue import AdmissionQueue
from horovod_tpu.serve.request import InferenceResponse, payload_signature

DEFAULT_MAX_BATCH = 8
DEFAULT_BUCKET_SIZES = (1, 2, 4, 8, 16, 32)

_TEL_OCCUPANCY = telemetry.histogram(
    "hvd_serve_batch_occupancy", "requests packed per executed batch",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0))


class ExecutableCache:
    """Executable hot-swap keyed by ``(model_id, signature, padded
    batch size)``.

    ``build(signature, padded_size) -> executor`` is invoked once per
    key (a builder taking a third ``model_id`` argument receives it —
    the fleet shape, one AOT executable set per tenant model); use
    :meth:`from_jitted` to route it through
    ``compile_cache.aot_compile`` so warm starts deserialize instead of
    recompiling.  Short batches are padded up to the next bucket (by
    repeating the tail payload) and the results truncated, so the
    executable set stays small and every size hits a cached entry.
    ``model_id=None`` keys the single-model plane of PR 12 — its
    entries never collide with a named tenant's.
    """

    def __init__(self, build: Callable[..., Callable],
                 bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES):
        self._build = build
        try:
            params = inspect.signature(build).parameters
            self._build_takes_model = len(params) >= 3 or any(
                p.kind == inspect.Parameter.VAR_POSITIONAL
                for p in params.values())
        except (TypeError, ValueError):    # builtins, C callables
            self._build_takes_model = False
        self.bucket_sizes = tuple(sorted(bucket_sizes))
        self._lock = threading.Lock()
        self._cache: Dict[Tuple[Optional[str], Tuple, int],
                          Callable] = {}

    @classmethod
    def from_jitted(cls, jitted, example_batch: Callable[[Tuple, int], Any],
                    bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
                    **aot_kwargs) -> "ExecutableCache":
        """Build executors through the AOT store: ``example_batch``
        maps ``(signature, padded_size)`` to a tracer-shaped input for
        lowering; each bucket compiles (or loads) once."""
        def build(signature: Tuple, padded: int) -> Callable:
            from horovod_tpu.runtime import compile_cache

            compiled, _ = compile_cache.aot_compile(
                jitted, (example_batch(signature, padded),),
                extras={"serve_signature": repr(signature),
                        "serve_batch": padded},
                **aot_kwargs)
            return compiled
        return cls(build, bucket_sizes=bucket_sizes)

    def padded_size(self, n: int) -> int:
        for b in self.bucket_sizes:
            if n <= b:
                return b
        return n

    def get(self, signature: Tuple, n: int,
            model_id: Optional[str] = None) -> Callable:
        key = (model_id, signature, self.padded_size(n))
        with self._lock:
            ex = self._cache.get(key)
        if ex is None:
            if self._build_takes_model and model_id is not None:
                built = self._build(signature, key[2], model_id)
            else:
                built = self._build(signature, key[2])
            with self._lock:
                ex = self._cache.setdefault(key, built)
        return ex

    def run(self, payloads: Sequence[Any],
            model_id: Optional[str] = None, **kwargs) -> List[Any]:
        """Replica-executor entry point: pad to the bucket, execute,
        truncate — shaped to plug straight into ``Replica(executor=)``
        (extra replica keywords like ``weights`` pass through to the
        built executor when it accepts them, and are dropped when it
        does not — a weight-less executable set stays valid)."""
        payloads = list(payloads)
        signature = payload_signature(payloads[0])
        padded = self.padded_size(len(payloads))
        ex = self.get(signature, len(payloads), model_id=model_id)
        full = payloads + [payloads[-1]] * (padded - len(payloads))
        if kwargs:
            try:
                accepts = any(
                    p.kind == inspect.Parameter.VAR_KEYWORD
                    or p.name in kwargs
                    for p in inspect.signature(ex).parameters.values())
            except (TypeError, ValueError):
                accepts = False
            if accepts:
                return list(ex(full, **kwargs))[:len(payloads)]
        return list(ex(full))[:len(payloads)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._cache)


class ContinuousBatcher:
    """Queue → replica engine loop (module docstring)."""

    def __init__(self, queue: AdmissionQueue, pool: ReplicaPool,
                 max_batch: Optional[int] = None,
                 on_response: Optional[Callable[[InferenceResponse],
                                                None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 poll_interval_s: float = 0.001):
        self._queue = queue
        self._pool = pool
        self.max_batch = max_batch if max_batch is not None \
            else _env_int("HOROVOD_SERVE_MAX_BATCH", DEFAULT_MAX_BATCH)
        self._on_response = on_response
        self._clock = clock
        self._poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def step(self) -> List[InferenceResponse]:
        """One engine iteration: pick a replica, lease a compatible
        batch, execute, feed service time back to admission.  Returns
        the responses (empty when idle, when no replica is SERVING, or
        when the replica died mid-batch — its lease re-enqueues)."""
        faults.inject("serve.feed")
        replica = self._pool.pick()
        if replica is None:
            return []
        batch = self._queue.take(self.max_batch)
        if not batch:
            return []
        _TEL_OCCUPANCY.observe(float(len(batch)))
        t0 = self._clock()
        responses = self._pool.execute(replica, batch)
        if responses:
            self._queue.note_service_time(max(self._clock() - t0, 0.0))
            if self._on_response is not None:
                for resp in responses:
                    self._on_response(resp)
        return responses

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self.step():
                # idle or drained — back off so the feeder doesn't spin
                self._stop.wait(self._poll_interval_s)

    def start(self) -> None:
        """Start the background feeder thread (production shape; tests
        and the seeded scenarios call :meth:`step` inline instead)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="hvd-serve-batcher", daemon=True)
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
