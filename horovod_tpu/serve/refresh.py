"""Live weight refresh without drain (docs/serving.md).

Serving weights go stale the moment training produces a better
checkpoint; draining the fleet to swap them costs exactly the
availability the fleet exists to provide.  :class:`WeightRefresher`
double-buffers each tenant model's parameter tree and swaps it under
traffic:

1. **stage** — the new checkpoint streams toward the replicas on the
   :class:`~horovod_tpu.memory.offload.HostOffloadEngine`'s
   double-buffered H2D path (when an engine is wired; trees already on
   the right device skip the hop).  A transfer fault degrades to the
   engine's retained reference — the PR 15 offload contract: the
   caller gets its tree back bit-identical, no step (and no refresh)
   lost.  Staging while a previous stage is still pending is
   **latest-wins**: the superseded buffer is dropped whole, never
   half-applied (no torn state).
2. **flip** — :meth:`maybe_flip` applies the pending stage *between*
   batches only (the FleetBatcher calls it before snapshotting the
   batch's weights), so in-flight requests complete on the old weights
   and no batch ever runs half-old half-new.
3. **verify** — before the flip commits, the staged tree's
   position-weighted fingerprint (guard/checksum.py) is recomputed and
   checked against the producer's expected fingerprint.  A mismatch
   **rolls the flip back**: the old weights keep serving, the staged
   buffer is discarded, and the checkpoint tag is quarantined (the
   PR 11 rollback discipline — ``on_quarantine`` is the hook to pin
   the last-good checkpoint, ``Checkpointer.pin`` style).  Zero
   requests are shed on this path; the swap simply never happens.

Every response minted after a flip carries the new fingerprint
(``InferenceResponse.weights_fp``), so weight freshness is verifiable
end to end — ``bench --serve`` asserts it on every post-flip response.

Fault site ``serve.refresh`` fires at the top of every flip attempt; a
``corrupt`` action there tampers the staged tree in transit and must
be caught by the fingerprint verify (the rollback path's chaos proof),
a ``raise`` models a flip-time failure and takes the same rollback
edge (docs/faults.md).

``HOROVOD_SERVE_REFRESH_VERIFY=0`` disables the fingerprint check
(trusted same-process producers); the default is on.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_bool
from horovod_tpu.utils import logging as hvd_logging

_TEL_STAGED = telemetry.counter(
    "hvd_serve_refresh_staged_total",
    "checkpoints staged for a live weight swap (model=)")
_TEL_FLIPS = telemetry.counter(
    "hvd_serve_refresh_flips_total",
    "atomic weight flips committed (model=)")
_TEL_ROLLBACKS = telemetry.counter(
    "hvd_serve_refresh_rollbacks_total",
    "flips rolled back on fingerprint mismatch, checkpoint "
    "quarantined (model=)")
_TEL_SUPERSEDED = telemetry.counter(
    "hvd_serve_refresh_superseded_total",
    "pending stages replaced by a newer one before flipping "
    "(latest-wins; model=)")


class _Staged:
    """One pending double-buffer: the streamed tree, the producer's
    expected fingerprint, and the checkpoint tag for quarantine."""

    __slots__ = ("params", "expected_fp", "tag")

    def __init__(self, params: Any, expected_fp: Optional[int],
                 tag: str):
        self.params = params
        self.expected_fp = expected_fp
        self.tag = tag


class WeightRefresher:
    """Double-buffered, fingerprint-verified live weight swap for the
    serving fleet (module docstring).

    ``engine`` is an optional
    :class:`~horovod_tpu.memory.offload.HostOffloadEngine`; with one
    wired, :meth:`stage` round-trips the checkpoint through its
    offload/fetch path (async D2H behind a bounded ring, blocking H2D
    restore) so the transfer rides — and inherits the degrade contract
    of — the same machinery the training loop's optimizer offload
    already proved.  ``on_quarantine(model_id, tag)`` is the PR 11
    rollback hook (pin the last-good checkpoint, alert, …).
    """

    def __init__(self, verify: Optional[bool] = None,
                 engine=None,
                 on_quarantine: Optional[Callable[[str, str],
                                                  None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.verify = verify if verify is not None \
            else _env_bool("HOROVOD_SERVE_REFRESH_VERIFY", True)
        self._engine = engine
        self._on_quarantine = on_quarantine
        self._clock = clock
        self._lock = threading.Lock()
        self._active: Dict[str, Tuple[Any, int]] = {}
        self._staged: Dict[str, _Staged] = {}
        self._seq = 0
        self.flips = 0
        self.rollbacks = 0
        self.superseded = 0
        self.quarantined: List[Tuple[str, str]] = []

    # -- registration -------------------------------------------------------

    def register(self, model_id: str, params: Any) -> int:
        """Install the initial serving weights; returns their
        fingerprint (stamped on every response until the first flip)."""
        from horovod_tpu.guard.checksum import fingerprint

        fp = fingerprint(params)
        with self._lock:
            self._active[model_id] = (params, fp)
        return fp

    def active(self, model_id: str) -> Tuple[Any, Optional[int]]:
        """The serving buffer: ``(params, fingerprint)`` — snapshot it
        ONCE per batch (FleetBatcher does) so the batch can never mix
        weights."""
        with self._lock:
            return self._active.get(model_id, (None, None))

    def fingerprint_of(self, model_id: str) -> Optional[int]:
        with self._lock:
            entry = self._active.get(model_id)
        return entry[1] if entry else None

    # -- stage --------------------------------------------------------------

    def stage(self, model_id: str, params: Any, tag: str = "",
              expected_fp: Optional[int] = None) -> str:
        """Stream a new checkpoint into the standby buffer; the flip
        itself waits for the next between-batches window.  Latest-wins:
        a stage arriving while another is pending replaces it whole.
        Returns the stage tag (auto-derived when empty)."""
        from horovod_tpu.guard.checksum import fingerprint

        if expected_fp is None:
            # producer-side fingerprint, taken BEFORE the transfer —
            # the verify step re-hashes after it, so a corrupted hop
            # cannot go unnoticed
            expected_fp = fingerprint(params)
        with self._lock:
            self._seq += 1
            tag = tag or f"refresh-{model_id}-{self._seq}"
        if self._engine is not None:
            # the double-buffered H2D path: async D2H into host RAM,
            # blocking H2D restore; a fault on either hop degrades to
            # the retained reference (bit-identical, nothing lost)
            self._engine.offload(tag, params)
            params = self._engine.fetch(tag, params)
        with self._lock:
            if model_id in self._staged:
                self.superseded += 1
                _TEL_SUPERSEDED.inc(model=model_id)
            self._staged[model_id] = _Staged(params, expected_fp, tag)
        _TEL_STAGED.inc(model=model_id)
        return tag

    def pending(self, model_id: str) -> bool:
        with self._lock:
            return model_id in self._staged

    # -- flip ---------------------------------------------------------------

    def maybe_flip(self, model_id: str) -> bool:
        """Commit the pending stage for ``model_id`` if there is one —
        called between batches only.  Returns True on a committed
        flip; False when nothing was pending *or* the flip rolled back
        (old weights keep serving either way)."""
        with self._lock:
            staged = self._staged.pop(model_id, None)
        if staged is None:
            return False
        try:
            tampered = faults.inject("serve.refresh",
                                     value=staged.params)
            if tampered is not None:
                staged.params = tampered
            actual = staged.expected_fp
            if self.verify:
                from horovod_tpu.guard.checksum import fingerprint

                actual = fingerprint(staged.params)
            if actual != staged.expected_fp:
                return self._rollback(
                    model_id, staged,
                    f"fingerprint mismatch {actual:#x} != "
                    f"{staged.expected_fp:#x}")
        except faults.WorkerCrash:
            raise
        except Exception as e:  # noqa: BLE001 — flip faults roll back
            return self._rollback(model_id, staged,
                                  f"{type(e).__name__}: {e}")
        with self._lock:
            self._active[model_id] = (staged.params, staged.expected_fp)
            self.flips += 1
        _TEL_FLIPS.inc(model=model_id)
        hvd_logging.info(
            "serve: model %s flipped to %s (fp %#x)", model_id,
            staged.tag, staged.expected_fp)
        return True

    def _rollback(self, model_id: str, staged: _Staged,
                  why: str) -> bool:
        """The fingerprint-verify/rollback edge: discard the staged
        buffer, quarantine the checkpoint tag, keep serving the old
        weights — zero requests shed on this path."""
        with self._lock:
            self.rollbacks += 1
            self.quarantined.append((model_id, staged.tag))
        _TEL_ROLLBACKS.inc(model=model_id)
        hvd_logging.warning(
            "serve: model %s refresh %s ROLLED BACK (%s) — old "
            "weights keep serving, checkpoint quarantined",
            model_id, staged.tag, why)
        if self._on_quarantine is not None:
            try:
                self._on_quarantine(model_id, staged.tag)
            except Exception as e:  # noqa: BLE001 — hook is best-effort
                hvd_logging.warning(
                    "serve: quarantine hook for %s failed: %s",
                    staged.tag, e)
        return False
