"""Multi-tenant admission: per-model queues behind weighted-fair
scheduling with SLO-classed overload shedding (docs/serving.md).

One serving fleet hosts many models.  Each tenant model gets its own
:class:`~horovod_tpu.serve.queue.AdmissionQueue` (so one tenant's
backlog cannot consume another's admission budget) and the
:class:`MultiTenantQueue` arbitrates *which* model's queue the batcher
drains next with smooth weighted round-robin — the deterministic
weighted-fair discipline with a provable starvation bound: among
backlogged tenants with total weight ``W``, a tenant of weight ``w``
is picked at least once in any window of ``ceil(W / w)`` consecutive
picks, and its long-run share of picks converges to ``w / W``
(pinned by test, required by ISSUE 20's tenancy criteria).

**SLO classes** map deadline tiers to shed priority under overload:

============  ==============  =========  =================================
class         deadline        shed tier  overload behavior
              budget (s)
============  ==============  =========  =================================
interactive   0.25            0          never overload-shed (only
                                         ``shed_full`` / ``shed_deadline``)
standard      2.0             1          shed when the fleet fill factor
                                         reaches midway between the
                                         overload watermark and full
batch         0 (none)        2          shed first, at the overload
                                         watermark itself
============  ==============  =========  =================================

The watermark is ``HOROVOD_SERVE_OVERLOAD_FRACTION`` (default 0.75) of
the fleet's total queue capacity; a class's deadline budget is applied
at submit when the request carries no deadline of its own, so the
tier→deadline mapping and the tier→shed-priority mapping stay one
table.  Overload sheds are *tenant-layer* verdicts
(``queue.SHED_OVERLOAD``, counted on ``hvd_serve_tenant_shed_total``)
— the per-model queue's own verdict vocabulary is untouched.

Satellite fix (ISSUE 20): :meth:`MultiTenantQueue.add_model` seeds the
per-model queue's EWMA batch-service estimate from the cost model's
``plan_cost_s`` for the model's plan, so the *first* wave of
deadline-tiered requests is judged against a real estimate instead of
the unseeded zero that admitted guaranteed-late work until the first
batch completed.

:class:`FleetBatcher` is the engine loop over all of it: weighted-fair
pick → per-model executable hot-swap (``ExecutableCache`` keyed
``(model_id, signature, bucket)``) → atomic weight flip *between*
batches via the :class:`~horovod_tpu.serve.refresh.WeightRefresher`,
with the weights buffer + fingerprint snapshotted once per batch so a
refresh can never produce a mixed-weights batch.

Fault site ``serve.tenant`` fires on every weighted-fair pick — a
``hang``/``raise`` there models a wedged arbiter (docs/faults.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_float
from horovod_tpu.serve.batcher import _TEL_OCCUPANCY, ContinuousBatcher
from horovod_tpu.serve.pool import ReplicaPool
from horovod_tpu.serve.queue import (
    ADMITTED,
    SHED_OVERLOAD,
    AdmissionQueue,
)
from horovod_tpu.serve.request import InferenceRequest, InferenceResponse

DEFAULT_OVERLOAD_FRACTION = 0.75

_TEL_TENANT_ADMITTED = telemetry.counter(
    "hvd_serve_tenant_admitted_total",
    "requests admitted per tenant model (model=)")
_TEL_TENANT_SHED = telemetry.counter(
    "hvd_serve_tenant_shed_total",
    "tenant-layer sheds (model=, reason=shed_overload|unknown_model)")
_TEL_TENANT_PICKS = telemetry.counter(
    "hvd_serve_tenant_picks_total",
    "weighted-fair scheduler picks per tenant model (model=)")
_TEL_TENANT_SHARE = telemetry.gauge(
    "hvd_serve_tenant_share",
    "observed fraction of scheduler picks per tenant model (model=)")


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One deadline tier: the default deadline budget applied at
    submit and the shed priority under overload (higher tier sheds
    earlier; tier 0 is never overload-shed)."""

    name: str
    deadline_budget_s: float
    shed_tier: int


#: the closed class table (module docstring) — tier 0 must stay the
#: strictest deadline AND the last to shed, or overload would starve
#: exactly the traffic the fleet exists to protect
SLO_CLASSES: Dict[str, SLOClass] = {
    "interactive": SLOClass("interactive", 0.25, 0),
    "standard": SLOClass("standard", 2.0, 1),
    "batch": SLOClass("batch", 0.0, 2),
}
_MAX_TIER = max(c.shed_tier for c in SLO_CLASSES.values())


@dataclasses.dataclass
class TenantSpec:
    """One tenant model's registration: scheduling weight, SLO class,
    and its per-model admission queue."""

    model_id: str
    weight: float
    slo: SLOClass
    queue: AdmissionQueue


class MultiTenantQueue:
    """Per-model admission queues behind a smooth weighted round-robin
    arbiter (module docstring).

    Implements the same ``submit`` / ``take`` / ``complete`` /
    ``requeue`` / ``__len__`` surface as a single
    :class:`AdmissionQueue`, so :class:`~horovod_tpu.serve.pool.
    ReplicaPool` plugs in unchanged — a dead replica's lease requeues
    into each request's *owning* model queue, preserving the
    exactly-once transition rule per model.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 overload_fraction: Optional[float] = None):
        self._clock = clock
        self.overload_fraction = overload_fraction \
            if overload_fraction is not None \
            else _env_float("HOROVOD_SERVE_OVERLOAD_FRACTION",
                            DEFAULT_OVERLOAD_FRACTION)
        self._lock = threading.Lock()
        self._tenants: Dict[str, TenantSpec] = {}
        self._order: List[str] = []              # registration order
        self._current: Dict[str, float] = {}     # SWRR running credit
        self._owner: Dict[str, str] = {}         # request_id -> model
        self.pick_counts: Dict[str, int] = {}
        self._total_picks = 0

    # -- registration -------------------------------------------------------

    def add_model(self, model_id: str, weight: float = 1.0,
                  slo_class: str = "standard",
                  plan: Optional[Any] = None,
                  payload_bytes: float = 0.0,
                  depth: Optional[int] = None,
                  max_requeues: Optional[int] = None) -> TenantSpec:
        """Register a tenant model.  ``plan`` + ``payload_bytes`` seed
        the model queue's EWMA service estimate from the cost model
        (``plan_cost_s``) so first-wave deadline verdicts are real."""
        if weight <= 0:
            raise ValueError(f"tenant {model_id!r}: weight must be > 0")
        slo = SLO_CLASSES.get(slo_class)
        if slo is None:
            raise ValueError(
                f"tenant {model_id!r}: unknown SLO class {slo_class!r} "
                f"(have {sorted(SLO_CLASSES)})")
        est = None
        if plan is not None:
            from horovod_tpu.analysis.cost_model import plan_cost_s

            est = plan_cost_s(plan, payload_bytes)
        spec = TenantSpec(
            model_id=model_id, weight=float(weight), slo=slo,
            queue=AdmissionQueue(depth=depth, max_requeues=max_requeues,
                                 clock=self._clock, service_est_s=est))
        with self._lock:
            if model_id in self._tenants:
                raise ValueError(f"tenant {model_id!r} already registered")
            self._tenants[model_id] = spec
            self._order.append(model_id)
            self._current[model_id] = 0.0
            self.pick_counts[model_id] = 0
        return spec

    def models(self) -> List[str]:
        with self._lock:
            return list(self._order)

    def queue_for(self, model_id: str) -> AdmissionQueue:
        with self._lock:
            return self._tenants[model_id].queue

    # -- admission ----------------------------------------------------------

    def _overload_threshold(self, tier: int) -> Optional[float]:
        """Fill factor at which ``tier`` sheds; None = never.  Higher
        tiers shed at the watermark, lower ones progressively closer
        to full, tier 0 never (module docstring table)."""
        if tier <= 0:
            return None
        f = self.overload_fraction
        return f + (1.0 - f) * (_MAX_TIER - tier) / _MAX_TIER

    def fill_factor(self) -> float:
        """Total queued work over total queue capacity, 0..1."""
        with self._lock:
            specs = list(self._tenants.values())
        cap = sum(s.queue.depth for s in specs)
        if not cap:
            return 0.0
        return sum(len(s.queue) for s in specs) / cap

    def submit(self, req: InferenceRequest) -> str:
        """Route one request to its model's queue; apply the SLO
        class's deadline budget when the request has none, and the
        class's overload shed priority when the fleet is past its
        watermark."""
        with self._lock:
            spec = self._tenants.get(req.model_id)
        if spec is None:
            _TEL_TENANT_SHED.inc(model=req.model_id or "?",
                                 reason="unknown_model")
            return SHED_OVERLOAD
        if req.deadline_s == 0 and spec.slo.deadline_budget_s > 0:
            req.deadline_s = self._clock() + spec.slo.deadline_budget_s
        threshold = self._overload_threshold(spec.slo.shed_tier)
        if threshold is not None and self.fill_factor() >= threshold:
            _TEL_TENANT_SHED.inc(model=req.model_id,
                                 reason=SHED_OVERLOAD)
            return SHED_OVERLOAD
        verdict = spec.queue.submit(req)
        if verdict == ADMITTED:
            with self._lock:
                self._owner[req.request_id] = req.model_id
            _TEL_TENANT_ADMITTED.inc(model=req.model_id)
        return verdict

    def stop_admitting(self) -> None:
        with self._lock:
            specs = list(self._tenants.values())
        for spec in specs:
            spec.queue.stop_admitting()

    # -- weighted-fair dequeue ----------------------------------------------

    def take_model(self, max_n: int
                   ) -> Tuple[Optional[str], List[InferenceRequest]]:
        """One smooth-weighted-round-robin pick over backlogged
        tenants, then lease up to ``max_n`` batch-compatible requests
        from the winner's queue.  Returns ``(None, [])`` when every
        queue is empty.  Deterministic: credits are pure arithmetic
        over the registration order, ties break on registration order.
        """
        faults.inject("serve.tenant")
        with self._lock:
            eligible = [m for m in self._order
                        if len(self._tenants[m].queue)]
            if not eligible:
                return None, []
            total_w = sum(self._tenants[m].weight for m in eligible)
            for m in eligible:
                self._current[m] += self._tenants[m].weight
            winner = max(eligible, key=lambda m: self._current[m])
            # max() keeps the first maximum → registration-order ties
            self._current[winner] -= total_w
            self.pick_counts[winner] += 1
            self._total_picks += 1
            picks = dict(self.pick_counts)
            total = self._total_picks
            queue = self._tenants[winner].queue
        _TEL_TENANT_PICKS.inc(model=winner)
        for m, n in picks.items():
            _TEL_TENANT_SHARE.set(n / total, model=m)
        return winner, queue.take(max_n)

    def take(self, max_n: int, signature=None) -> List[InferenceRequest]:
        """Single-queue compatibility shim (ReplicaPool never calls
        this, but code written against AdmissionQueue may)."""
        _, batch = self.take_model(max_n)
        return batch

    # -- completion / requeue (exactly-once, per owning model) --------------

    def complete(self, request_ids) -> None:
        groups: Dict[str, List[str]] = {}
        with self._lock:
            for rid in request_ids:
                owner = self._owner.get(rid)
                if owner is not None:
                    groups.setdefault(owner, []).append(rid)
            specs = {m: self._tenants[m] for m in groups}
        for m, rids in groups.items():
            specs[m].queue.complete(rids)

    def requeue(self, reqs) -> int:
        groups: Dict[str, List[InferenceRequest]] = {}
        with self._lock:
            for req in reqs:
                owner = self._owner.get(req.request_id, req.model_id)
                if owner in self._tenants:
                    groups.setdefault(owner, []).append(req)
            specs = {m: self._tenants[m] for m in groups}
        return sum(specs[m].queue.requeue(rs)
                   for m, rs in groups.items())

    def note_service_time(self, service_s: float,
                          model_id: Optional[str] = None) -> None:
        """Feed one observed batch service time back to the owning
        model's admission EWMA (all models when ``model_id`` is None —
        the single-queue shim path)."""
        with self._lock:
            specs = [self._tenants[model_id]] if model_id is not None \
                and model_id in self._tenants \
                else list(self._tenants.values())
        for spec in specs:
            spec.queue.note_service_time(service_s)

    # -- introspection ------------------------------------------------------

    def state_of(self, request_id: str) -> Optional[str]:
        with self._lock:
            owner = self._owner.get(request_id)
            spec = self._tenants.get(owner) if owner else None
        return spec.queue.state_of(request_id) if spec else None

    def __len__(self) -> int:
        with self._lock:
            specs = list(self._tenants.values())
        return sum(len(s.queue) for s in specs)

    @property
    def admitting(self) -> bool:
        with self._lock:
            specs = list(self._tenants.values())
        return any(s.queue.admitting for s in specs)


class FleetBatcher(ContinuousBatcher):
    """Engine loop for the fleet: weighted-fair pick → executable
    hot-swap per leased batch → atomic weight flip between batches.

    The weights buffer and its fingerprint are snapshotted ONCE before
    the batch executes; every request in the batch runs against that
    snapshot and every response carries its fingerprint — a refresh
    landing mid-batch waits for the next :meth:`step` (no mixed-weights
    batch, in-flight work completes on the old weights).
    """

    def __init__(self, queue: MultiTenantQueue, pool: ReplicaPool,
                 refresher=None, **kwargs):
        super().__init__(queue, pool, **kwargs)
        self._refresher = refresher

    def step(self) -> List[InferenceResponse]:
        faults.inject("serve.feed")
        replica = self._pool.pick()
        if replica is None:
            return []
        model_id, batch = self._queue.take_model(self.max_batch)
        if not batch:
            return []
        weights = weights_fp = None
        if self._refresher is not None:
            # flips land HERE, strictly between batches
            self._refresher.maybe_flip(model_id)
            weights, weights_fp = self._refresher.active(model_id)
        _TEL_OCCUPANCY.observe(float(len(batch)))
        t0 = self._clock()
        responses = self._pool.execute(
            replica, batch, model_id=model_id, weights=weights,
            weights_fp=weights_fp)
        if responses:
            self._queue.note_service_time(
                max(self._clock() - t0, 0.0), model_id)
            if self._on_response is not None:
                for resp in responses:
                    self._on_response(resp)
        return responses
