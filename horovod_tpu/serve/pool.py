"""Replica pool: leases, crash recovery, graceful drain, scale signals.

The pool owns the mapping between replicas and the requests leased to
them, and converts replica lifecycle events into the queue's
exactly-once transitions (docs/serving.md):

* **execute** — lease a batch to a replica, run it, complete the ids
  and emit responses; a crash mid-batch (``WorkerCrash`` from the
  ``serve.batch`` fault site, or any executor error) flips the replica
  to ``DEAD`` and re-enqueues its leased requests *exactly once*
  (``AdmissionQueue.requeue`` ignores anything not in-flight);
* **drain** — the planned-departure path: stop routing to the replica,
  let in-flight work finish inside ``HOROVOD_SERVE_DRAIN_TIMEOUT_S``,
  then announce the departure to the elastic driver
  (:class:`ElasticServeBridge`) so the exit is graceful — no
  blacklist, no quarantine, no sibling abort.  A drain that cannot
  finish in the window (wedged replica, ``serve.drain`` fault) falls
  back to the dead path;
* **scale signals** — queue depth against
  ``HOROVOD_SERVE_SCALE_UP_DEPTH`` / ``HOROVOD_SERVE_SCALE_DOWN_DEPTH``
  yields +1/0/−1 deltas the :class:`~horovod_tpu.serve.autoscale.
  AutoscaleController` closes into actual acquire/release actions (a
  deep queue asks for a replica, an idle pool releases one through the
  same graceful drain).  The signal source carries its own hysteresis
  (``HOROVOD_SERVE_SCALE_HOLD_S``): after a nonzero signal, the
  *opposite* direction is suppressed for the hold window, so a queue
  depth oscillating across a threshold cannot emit alternating ±1
  every poll — flap damping belongs at the sensor too, not only in
  the controller's cooldown.

Every lifecycle transition lands in the ``hvd_serve_*`` registry
(closed vocabulary: ``analysis/metrics_schema.py SERVE_SERIES``).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_float, _env_int
from horovod_tpu.serve.queue import AdmissionQueue
from horovod_tpu.serve.replica import DEAD, DEPARTED, Replica
from horovod_tpu.serve.request import InferenceRequest, InferenceResponse
from horovod_tpu.utils import logging as hvd_logging

DEFAULT_DRAIN_TIMEOUT_S = 30.0
DEFAULT_SCALE_UP_DEPTH = 32
DEFAULT_SCALE_DOWN_DEPTH = 2
DEFAULT_SCALE_HOLD_S = 5.0

_TEL_REPLICAS = telemetry.gauge(
    "hvd_serve_replicas", "replicas currently able to take batches")
_TEL_DEATHS = telemetry.counter(
    "hvd_serve_replica_deaths_total",
    "replicas lost to crashes or drain timeouts")
_TEL_DRAINS = telemetry.counter(
    "hvd_serve_drains_total",
    "graceful replica drains completed (planned departure)")
_TEL_DRAIN_TIMEOUTS = telemetry.counter(
    "hvd_serve_drain_timeouts_total",
    "drains that fell back to the dead path")
_TEL_SCALE = telemetry.counter(
    "hvd_serve_scale_events_total",
    "scale signals emitted (direction=up|down)")
_TEL_SCALE_SUPPRESSED = telemetry.counter(
    "hvd_serve_scale_suppressed_total",
    "scale signals swallowed by source hysteresis "
    "(HOROVOD_SERVE_SCALE_HOLD_S)")
_TEL_LATENCY = telemetry.histogram(
    "hvd_serve_latency_seconds",
    "request latency, admission to response")


class ElasticServeBridge:
    """Glue between the pool and the elastic control plane: two
    callbacks, buildable from a live :class:`ElasticDriver` so serving
    rides the exact code paths training recovery already proved."""

    def __init__(self,
                 on_dead: Optional[Callable[[str, int], None]] = None,
                 notify_departure: Optional[Callable[[str, int],
                                                     None]] = None):
        self.on_dead = on_dead
        self.notify_departure = notify_departure

    @classmethod
    def for_driver(cls, driver) -> "ElasticServeBridge":
        """A crashed replica takes the failure-exit path (quarantine +
        regeneration); a drained one announces a planned departure
        first, so its exit is graceful."""
        return cls(
            on_dead=lambda h, lr: driver.record_worker_exit(h, lr, 1),
            notify_departure=lambda h, lr: driver.announce_departure(
                h, lr))


class ReplicaPool:
    def __init__(self, queue: AdmissionQueue,
                 bridge: Optional[ElasticServeBridge] = None,
                 drain_timeout_s: Optional[float] = None,
                 scale_up_depth: Optional[int] = None,
                 scale_down_depth: Optional[int] = None,
                 scale_hold_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._queue = queue
        self._bridge = bridge or ElasticServeBridge()
        self.drain_timeout_s = drain_timeout_s \
            if drain_timeout_s is not None \
            else _env_float("HOROVOD_SERVE_DRAIN_TIMEOUT_S",
                            DEFAULT_DRAIN_TIMEOUT_S)
        self.scale_up_depth = scale_up_depth \
            if scale_up_depth is not None \
            else _env_int("HOROVOD_SERVE_SCALE_UP_DEPTH",
                          DEFAULT_SCALE_UP_DEPTH)
        self.scale_down_depth = scale_down_depth \
            if scale_down_depth is not None \
            else _env_int("HOROVOD_SERVE_SCALE_DOWN_DEPTH",
                          DEFAULT_SCALE_DOWN_DEPTH)
        self.scale_hold_s = scale_hold_s if scale_hold_s is not None \
            else _env_float("HOROVOD_SERVE_SCALE_HOLD_S",
                            DEFAULT_SCALE_HOLD_S)
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: List[Replica] = []
        self._leases: Dict[str, List[InferenceRequest]] = {}
        self._rr = 0
        #: replica deaths observed so far — the autoscale controller
        #: diffs this to treat a chaos kill as lost capacity (a killed
        #: replica both requeues its lease AND feeds the scale loop)
        self.deaths = 0
        self._last_signal = 0
        self._last_signal_t = float("-inf")

    # -- membership ---------------------------------------------------------

    def add_replica(self, replica: Replica) -> Replica:
        with self._lock:
            self._replicas.append(replica)
            _TEL_REPLICAS.set(self._serving_count_locked())
        return replica

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    def _serving_count_locked(self) -> int:
        return sum(1 for r in self._replicas if r.serving)

    def serving_count(self) -> int:
        with self._lock:
            return self._serving_count_locked()

    def pick(self) -> Optional[Replica]:
        """Round-robin over SERVING replicas (deterministic for the
        seeded scenarios); None when the pool has no capacity."""
        with self._lock:
            serving = [r for r in self._replicas if r.serving]
            if not serving:
                return None
            replica = serving[self._rr % len(serving)]
            self._rr += 1
            return replica

    # -- execution ----------------------------------------------------------

    def execute(self, replica: Replica,
                reqs: List[InferenceRequest],
                model_id: Optional[str] = None,
                weights=None,
                weights_fp: Optional[int] = None
                ) -> List[InferenceResponse]:
        """Run one leased batch.  Success completes every id; a crash
        (``WorkerCrash`` or executor error) marks the replica dead and
        re-enqueues the lease exactly once.

        Fleet callers (serve/tenancy.py FleetBatcher) pass the leased
        batch's ``model_id`` plus the weights buffer + fingerprint
        snapshotted *once* before this call — every request in the
        batch runs against that single snapshot (never mixed weights)
        and every response carries its fingerprint."""
        if not reqs:
            return []
        with self._lock:
            self._leases[replica.name] = list(reqs)
        try:
            if model_id is None:
                results = replica.run_batch([r.payload for r in reqs])
            else:
                results = replica.run_batch(
                    [r.payload for r in reqs], model_id=model_id,
                    weights=weights)
        except (faults.WorkerCrash, Exception) as e:  # noqa: BLE001
            self.mark_dead(replica, reason=f"{type(e).__name__}: {e}")
            return []
        now = self._clock()
        with self._lock:
            self._leases.pop(replica.name, None)
        self._queue.complete([r.request_id for r in reqs])
        responses = []
        for req, result in zip(reqs, results):
            latency = max(now - req.arrival_s, 0.0)
            _TEL_LATENCY.observe(latency)
            responses.append(InferenceResponse(
                request_id=req.request_id, result=result,
                replica=replica.name, latency_s=latency,
                requeues=req.requeues, model_id=model_id or "",
                weights_fp=weights_fp))
        return responses

    def mark_dead(self, replica: Replica, reason: str = "") -> int:
        """The crash path: flip to DEAD, re-enqueue the lease (exactly
        once — completed or already-requeued ids are ignored by the
        queue), tell the elastic plane it was a failure exit.  Returns
        how many requests were re-enqueued."""
        with self._lock:
            already_dead = replica.state == DEAD
            replica.state = DEAD
            lease = self._leases.pop(replica.name, [])
            _TEL_REPLICAS.set(self._serving_count_locked())
        if already_dead and not lease:
            return 0
        with self._lock:
            self.deaths += 1
        _TEL_DEATHS.inc()
        requeued = self._queue.requeue(lease)
        hvd_logging.warning(
            "serve: replica %s died (%s) — re-enqueued %d of %d "
            "in-flight request(s)", replica.name, reason or "unknown",
            requeued, len(lease))
        if self._bridge.on_dead is not None:
            self._bridge.on_dead(replica.host, replica.local_rank)
        return requeued

    # -- graceful drain -----------------------------------------------------

    def drain(self, replica: Replica,
              wait: Optional[Callable[[], None]] = None) -> bool:
        """Planned departure (quarantine notice, SIGTERM, scale-down):
        stop routing to the replica, let the in-flight lease finish
        within ``drain_timeout_s`` (``wait`` is called between polls —
        inject a scheduler or fake-clock advance in tests), then
        announce the departure.  Returns True for a graceful drain,
        False when it fell back to the dead path."""
        replica.begin_drain()
        deadline = self._clock() + self.drain_timeout_s
        while True:
            with self._lock:
                pending = bool(self._leases.get(replica.name))
            if not pending:
                break
            if self._clock() >= deadline:
                _TEL_DRAIN_TIMEOUTS.inc()
                self.mark_dead(replica, reason="drain timeout")
                return False
            if wait is not None:
                wait()
        try:
            # chaos hook: a raise/hang here models a drain wedged past
            # its grace window — the replica must fall back to the
            # normal dead path instead of departing half-drained
            faults.inject("serve.drain")
        except Exception as e:  # noqa: BLE001 — fault actions vary
            _TEL_DRAIN_TIMEOUTS.inc()
            self.mark_dead(replica, reason=f"drain fault: {e}")
            return False
        if self._bridge.notify_departure is not None:
            try:
                self._bridge.notify_departure(replica.host,
                                              replica.local_rank)
            except Exception as e:  # noqa: BLE001 — notice is best-effort
                hvd_logging.warning(
                    "serve: departure notice for %s failed: %s",
                    replica.name, e)
        replica.state = DEPARTED
        with self._lock:
            _TEL_REPLICAS.set(self._serving_count_locked())
        _TEL_DRAINS.inc()
        hvd_logging.info("serve: replica %s drained gracefully "
                         "(planned departure)", replica.name)
        return True

    def drain_all(self) -> None:
        """SIGTERM for the whole plane: stop admitting, then drain every
        live replica (docs/serving.md shutdown sequence)."""
        self._queue.stop_admitting()
        for replica in self.replicas():
            if replica.alive:
                self.drain(replica)

    # -- scaling ------------------------------------------------------------

    def scale_signal(self) -> int:
        """+1 (add a replica), −1 (drain one), or 0 — queue depth vs
        the scale thresholds.  The autoscale controller (or the elastic
        driver's discovery plane) is the actuator; this is the sensor.

        Source hysteresis: after a nonzero signal, the *opposite*
        direction is suppressed (0, counted on
        ``hvd_serve_scale_suppressed_total``) until ``scale_hold_s``
        elapses — a depth flapping across ``scale_up_depth`` emits one
        +1 and then silence, not an alternating ±1 train."""
        depth = len(self._queue)
        serving = self.serving_count()
        raw = 0
        if depth >= self.scale_up_depth:
            raw = 1
        elif depth <= self.scale_down_depth and serving > 1:
            raw = -1
        if raw == 0:
            return 0
        now = self._clock()
        with self._lock:
            if raw == -self._last_signal and \
                    now < self._last_signal_t + self.scale_hold_s:
                _TEL_SCALE_SUPPRESSED.inc()
                return 0
            self._last_signal = raw
            self._last_signal_t = now
        _TEL_SCALE.inc(direction="up" if raw > 0 else "down")
        return raw
