"""Seeded serve-chaos smoke for ``hvdci`` (analysis/ci.py gate 5).

A sub-second, CPU-only, logical-clock run of the serving plane's whole
robustness story: an open-loop generator admits a seeded request
stream, the continuous batcher packs it onto two replicas, a seeded
``serve.batch`` crash kills one replica mid-batch, its leased requests
re-enqueue exactly once (no lost, no duplicated response), and the
surviving replica finishes the stream then drains gracefully through
the planned-departure path — twice, so determinism itself is gated.

Returns error strings (empty = pass) in the same idiom as
``guard.smoke`` so ci.py folds it straight into its exit code.
Budget: well under a second — pure numpy payloads, a logical clock the
fake executor advances, ~24 requests.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from horovod_tpu import faults
from horovod_tpu.faults import FaultPlan
from horovod_tpu.serve.batcher import ContinuousBatcher
from horovod_tpu.serve.pool import ReplicaPool
from horovod_tpu.serve.queue import ADMITTED, AdmissionQueue
from horovod_tpu.serve.replica import DEAD, DEPARTED, Replica
from horovod_tpu.serve.request import InferenceRequest

SEED = 1234
N_REQUESTS = 24
MAX_BATCH = 4
CRASH_AT = 3       # third serve.batch hit → replica r0's second batch
MAX_STEPS = 200    # engine-loop runaway guard


def _scenario() -> Dict[str, Any]:
    plan = FaultPlan(seed=SEED, sim=True).add(
        "serve.batch", "crash", at=CRASH_AT)
    faults.set_plan(plan)
    try:
        now = [0.0]

        def clock() -> float:
            return now[0]

        def executor(payloads):
            # service time is a pure function of occupancy, so the
            # logical clock — and every latency derived from it — is
            # identical across runs
            now[0] += 0.004 + 0.001 * len(payloads)
            return [round(float(np.asarray(p).sum()), 6)
                    for p in payloads]

        queue = AdmissionQueue(depth=64, max_requeues=3, clock=clock)
        pool = ReplicaPool(queue, drain_timeout_s=1.0, clock=clock)
        replicas = [pool.add_replica(
            Replica(f"r{i}", executor, host=f"host-{i}", local_rank=0,
                    clock=clock)) for i in range(2)]

        got: Dict[str, List[Any]] = {}
        batcher = ContinuousBatcher(
            queue, pool, max_batch=MAX_BATCH, clock=clock,
            on_response=lambda r: got.setdefault(
                r.request_id, []).append((r.result, r.requeues, r.replica)))

        rng = np.random.RandomState(SEED)
        admitted: List[str] = []
        for i in range(N_REQUESTS):
            req = InferenceRequest(
                request_id=f"req-{i:03d}",
                payload=rng.rand(4).astype(np.float32),
                deadline_s=now[0] + 10.0)
            if queue.submit(req) == ADMITTED:
                admitted.append(req.request_id)
            now[0] += 0.001   # open-loop: arrivals march on regardless

        steps = 0
        while len(queue) and steps < MAX_STEPS:
            batcher.step()
            steps += 1
            if pool.serving_count() == 0:
                break

        drains = [pool.drain(r) for r in pool.replicas() if r.alive]
        return {
            "admitted": admitted,
            "responses": sorted((rid, tuple(rs)) for rid, rs in got.items()),
            "requeued_ids": sorted(rid for rid, rs in got.items()
                                   if any(r[1] > 0 for r in rs)),
            "states": [r.state for r in replicas],
            "drains": drains,
            "steps": steps,
            "clock": round(now[0], 6),
        }
    finally:
        faults.clear_plan()


def run_smoke() -> List[str]:
    """Run the seeded serve-chaos scenario twice; returns a list of
    error strings (empty = pass)."""
    errors: List[str] = []
    r1 = _scenario()
    r2 = _scenario()
    responded = {rid for rid, _ in r1["responses"]}
    lost = sorted(set(r1["admitted"]) - responded)
    if lost:
        errors.append(f"serve-smoke: {len(lost)} admitted request(s) "
                      f"lost ({lost[:3]}...)")
    dupes = sorted(rid for rid, rs in r1["responses"] if len(rs) != 1)
    if dupes:
        errors.append(f"serve-smoke: duplicated responses for {dupes[:3]}")
    if not r1["requeued_ids"]:
        errors.append("serve-smoke: crash fired but no request was "
                      "re-executed (requeue path untested)")
    if len(r1["requeued_ids"]) > MAX_BATCH:
        errors.append(f"serve-smoke: {len(r1['requeued_ids'])} requests "
                      f"requeued — more than one lease of {MAX_BATCH}")
    if sorted(r1["states"]) != sorted([DEAD, DEPARTED]):
        errors.append(f"serve-smoke: replica states {r1['states']}, "
                      f"expected one dead (crash) one departed (drain)")
    if not all(r1["drains"]):
        errors.append("serve-smoke: survivor drain was not graceful")
    if r1 != r2:
        errors.append("serve-smoke: two seeded runs were not identical")
    return errors
