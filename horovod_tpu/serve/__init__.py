"""hvdserve: resilient serving plane on the elastic runtime.

The serving plane (docs/serving.md) turns the substrate PRs 3–11 built
— AOT executable store, heartbeat/health plane, quarantine-with-decay,
deterministic fault injection, telemetry registry — into a request
path that degrades gracefully instead of dropping or duplicating work:

* :mod:`~horovod_tpu.serve.request` — request/response records; the
  request id is the exactly-once token;
* :mod:`~horovod_tpu.serve.queue` — bounded admission queue:
  deadline-aware shedding + backpressure at the front door, and the
  ``queued → inflight → done`` state machine that makes crash
  re-enqueue exactly-once;
* :mod:`~horovod_tpu.serve.replica` — one serving slot with the
  SERVING → DRAINING → DEPARTED / DEAD lifecycle;
* :mod:`~horovod_tpu.serve.batcher` — continuous batcher packing
  signature-compatible requests into AOT-cached executables
  (:class:`~horovod_tpu.serve.batcher.ExecutableCache`);
* :mod:`~horovod_tpu.serve.pool` — replica pool: leases, crash
  recovery, graceful drain via the planned-departure path, and
  queue-depth scale signals for the elastic driver
  (:class:`~horovod_tpu.serve.pool.ElasticServeBridge`);
* :mod:`~horovod_tpu.serve.smoke` — the seeded sub-second chaos
  scenario hvdci gate 5 runs twice and diffs bit-for-bit.

Fault sites: ``serve.batch`` (replica crash mid-batch), ``serve.feed``
(queue-feeder hang), ``serve.drain`` (drain wedged past its window).
Metrics: the closed ``hvd_serve_*`` vocabulary in
``analysis/metrics_schema.py SERVE_SERIES``.
"""

from horovod_tpu.serve.batcher import ContinuousBatcher, ExecutableCache
from horovod_tpu.serve.pool import ElasticServeBridge, ReplicaPool
from horovod_tpu.serve.queue import (
    ADMITTED,
    SHED_DEADLINE,
    SHED_DUPLICATE,
    SHED_FULL,
    SHED_REQUEUE_BUDGET,
    AdmissionQueue,
)
from horovod_tpu.serve.replica import (
    DEAD,
    DEPARTED,
    DRAINING,
    SERVING,
    Replica,
)
from horovod_tpu.serve.request import (
    InferenceRequest,
    InferenceResponse,
    payload_signature,
)

__all__ = [
    "ADMITTED", "SHED_DEADLINE", "SHED_DUPLICATE", "SHED_FULL",
    "SHED_REQUEUE_BUDGET", "AdmissionQueue", "ContinuousBatcher",
    "DEAD", "DEPARTED", "DRAINING", "ElasticServeBridge",
    "ExecutableCache", "InferenceRequest", "InferenceResponse",
    "Replica", "ReplicaPool", "SERVING", "payload_signature",
]
