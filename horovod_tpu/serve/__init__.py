"""hvdserve: resilient serving plane on the elastic runtime.

The serving plane (docs/serving.md) turns the substrate PRs 3–11 built
— AOT executable store, heartbeat/health plane, quarantine-with-decay,
deterministic fault injection, telemetry registry — into a request
path that degrades gracefully instead of dropping or duplicating work:

* :mod:`~horovod_tpu.serve.request` — request/response records; the
  request id is the exactly-once token;
* :mod:`~horovod_tpu.serve.queue` — bounded admission queue:
  deadline-aware shedding + backpressure at the front door, and the
  ``queued → inflight → done`` state machine that makes crash
  re-enqueue exactly-once;
* :mod:`~horovod_tpu.serve.replica` — one serving slot with the
  SERVING → DRAINING → DEPARTED / DEAD lifecycle;
* :mod:`~horovod_tpu.serve.batcher` — continuous batcher packing
  signature-compatible requests into AOT-cached executables
  (:class:`~horovod_tpu.serve.batcher.ExecutableCache`);
* :mod:`~horovod_tpu.serve.pool` — replica pool: leases, crash
  recovery, graceful drain via the planned-departure path, and
  hysteresis-damped queue-depth scale signals
  (:class:`~horovod_tpu.serve.pool.ElasticServeBridge`);
* :mod:`~horovod_tpu.serve.smoke` — the seeded sub-second chaos
  scenario hvdci gate 5 runs twice and diffs bit-for-bit.

The **hvdfleet** layer (ISSUE 20) turns the one-model plane into a
multi-tenant fleet:

* :mod:`~horovod_tpu.serve.tenancy` — per-model admission queues
  behind a smooth-weighted-round-robin arbiter with SLO-classed
  overload shedding, plus the :class:`~horovod_tpu.serve.tenancy.
  FleetBatcher` engine loop;
* :mod:`~horovod_tpu.serve.refresh` — live weight refresh without
  drain: double-buffered staging on the host-offload H2D path, atomic
  between-batches flips, fingerprint verify with rollback +
  checkpoint quarantine;
* :mod:`~horovod_tpu.serve.autoscale` — the closed loop over
  ``scale_signal()``: acquire (warm start through the AOT cache) /
  release (graceful drain) with cooldown, bounds and death repair;
* :mod:`~horovod_tpu.serve.fleet_smoke` — the seeded 3-model
  enqueue → refresh-mid-load → kill → scale-up → drain scenario hvdci
  gate 11 runs twice and diffs bit-for-bit.

Fault sites: ``serve.batch`` (replica crash mid-batch), ``serve.feed``
(queue-feeder hang), ``serve.drain`` (drain wedged past its window),
``serve.tenant`` (weighted-fair pick), ``serve.refresh`` (flip
attempt — ``corrupt`` must be caught by the fingerprint verify),
``serve.scale`` (autoscale poll).  Metrics: the closed ``hvd_serve_*``
vocabulary in ``analysis/metrics_schema.py SERVE_SERIES``.
"""

from horovod_tpu.serve.autoscale import AutoscaleController
from horovod_tpu.serve.batcher import ContinuousBatcher, ExecutableCache
from horovod_tpu.serve.pool import ElasticServeBridge, ReplicaPool
from horovod_tpu.serve.queue import (
    ADMITTED,
    SHED_DEADLINE,
    SHED_DUPLICATE,
    SHED_FULL,
    SHED_OVERLOAD,
    SHED_REQUEUE_BUDGET,
    AdmissionQueue,
)
from horovod_tpu.serve.refresh import WeightRefresher
from horovod_tpu.serve.tenancy import (
    SLO_CLASSES,
    FleetBatcher,
    MultiTenantQueue,
    SLOClass,
    TenantSpec,
)
from horovod_tpu.serve.replica import (
    DEAD,
    DEPARTED,
    DRAINING,
    SERVING,
    Replica,
)
from horovod_tpu.serve.request import (
    InferenceRequest,
    InferenceResponse,
    payload_signature,
)

__all__ = [
    "ADMITTED", "SHED_DEADLINE", "SHED_DUPLICATE", "SHED_FULL",
    "SHED_OVERLOAD", "SHED_REQUEUE_BUDGET", "AdmissionQueue",
    "AutoscaleController", "ContinuousBatcher", "DEAD", "DEPARTED",
    "DRAINING", "ElasticServeBridge", "ExecutableCache",
    "FleetBatcher", "InferenceRequest", "InferenceResponse",
    "MultiTenantQueue", "Replica", "ReplicaPool", "SERVING",
    "SLOClass", "SLO_CLASSES", "TenantSpec", "WeightRefresher",
    "payload_signature",
]
