"""Request/response records for the serving plane (docs/serving.md).

A request enters the plane with a caller-chosen **request id** — the
exactly-once token every downstream guarantee hangs off: admission
dedups resubmissions of an id it already holds, the replica pool
leases ids to the replica executing them, and a dead replica's leased
ids are re-enqueued at most once (``AdmissionQueue.requeue``) so a
crash mid-batch can neither lose a response nor produce two.

The **signature** is the batch-compatibility key: requests sharing a
signature (same input shape/dtype, same model entry point) may be
packed into one executable call by the continuous batcher.  Use
:func:`payload_signature` for array-like payloads.

Under the fleet model (serve/tenancy.py) a request also names the
**model** it targets — the tenancy layer routes it to that model's
admission queue and the batcher hot-swaps the model's executable per
leased batch — and every response carries the **weights fingerprint**
(guard/checksum.py) of the exact parameter buffer that produced it,
so weight freshness after a live refresh (serve/refresh.py) is
verifiable end to end.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

#: queue states an admitted request id moves through (queue.py)
QUEUED = "queued"
INFLIGHT = "inflight"
DONE = "done"


def payload_signature(payload: Any) -> Tuple:
    """Batch-compatibility key for an array-like payload: ``(shape,
    dtype)`` when the payload exposes them, else its type name — two
    requests are packable iff their signatures compare equal."""
    shape = getattr(payload, "shape", None)
    dtype = getattr(payload, "dtype", None)
    if shape is not None:
        return (tuple(shape), str(dtype))
    return (type(payload).__name__,)


@dataclasses.dataclass
class InferenceRequest:
    """One unit of admitted work.

    ``deadline_s`` is an *absolute* clock reading (same clock the queue
    was built with); 0 means no deadline.  ``requeues`` counts crash
    re-executions — bounded by ``HOROVOD_SERVE_MAX_REQUEUES`` so a
    poison request that kills every replica it touches is eventually
    shed instead of cycling forever."""

    request_id: str
    payload: Any
    signature: Tuple = ()
    arrival_s: float = 0.0
    deadline_s: float = 0.0
    requeues: int = 0
    #: fleet routing key — which model's admission queue this request
    #: belongs to ("" = the single-model plane of PR 12)
    model_id: str = ""

    def __post_init__(self) -> None:
        if not self.signature:
            self.signature = payload_signature(self.payload)


@dataclasses.dataclass
class InferenceResponse:
    """The completion record the batcher hands back: result plus the
    latency/provenance fields the SLO probe aggregates."""

    request_id: str
    result: Any
    replica: str = ""
    latency_s: float = 0.0
    requeues: int = 0
    error: Optional[str] = None
    #: fleet provenance: the model that served it and the fingerprint
    #: of the weights buffer the batch ran against (None on the
    #: single-model plane or when no refresher is wired)
    model_id: str = ""
    weights_fp: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.error is None
