"""Seeded fleet-chaos smoke for ``hvdci`` (analysis/ci.py gate 11).

A sub-second, CPU-only, logical-clock run of the hvdfleet story end to
end: three tenant models (weights 4/2/1 across the three SLO classes)
admit a seeded open-loop stream through the weighted-fair scheduler; a
live weight refresh for the heavy tenant stages mid-load and flips
atomically between batches (responses before the flip carry the old
fingerprint, responses after it the new one — never a mix inside one
batch); a seeded ``serve.batch`` crash kills a replica mid-load and
its lease re-enqueues exactly once; the autoscale controller sees the
death plus the deep queue and acquires a replacement (scale-up); the
stream completes with zero lost and zero duplicated responses and the
survivors drain gracefully — twice, so determinism itself is gated.

Returns error strings (empty = pass) in the same idiom as
``serve.smoke`` so ci.py folds it straight into its exit code.
Budget: well under a second — pure numpy payloads, a logical clock the
fake executor advances, ~30 requests, no offload engine (the engine
path is covered by tests/test_serve_fleet.py).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from horovod_tpu import faults
from horovod_tpu.faults import FaultPlan
from horovod_tpu.serve.autoscale import AutoscaleController
from horovod_tpu.serve.pool import ReplicaPool
from horovod_tpu.serve.queue import ADMITTED
from horovod_tpu.serve.refresh import WeightRefresher
from horovod_tpu.serve.replica import Replica
from horovod_tpu.serve.request import InferenceRequest
from horovod_tpu.serve.tenancy import FleetBatcher, MultiTenantQueue

SEED = 20240
N_REQUESTS = 30
MAX_BATCH = 4
CRASH_AT = 4       # fourth serve.batch hit → kill mid-load
REFRESH_AT = 12    # stage the m0 weight swap after this many submits
MAX_STEPS = 300    # engine-loop runaway guard

MODELS = (("m0", 4.0, "interactive"), ("m1", 2.0, "standard"),
          ("m2", 1.0, "batch"))


def _scenario() -> Dict[str, Any]:
    plan = FaultPlan(seed=SEED, sim=True).add(
        "serve.batch", "crash", at=CRASH_AT)
    faults.set_plan(plan)
    try:
        now = [0.0]

        def clock() -> float:
            return now[0]

        def executor(payloads, model_id=None, weights=None):
            # service time is a pure function of occupancy, result a
            # pure function of payload + weights → bit-identical runs
            now[0] += 0.004 + 0.001 * len(payloads)
            w = float(np.asarray(weights).sum())
            return [round(float(np.asarray(p).sum()) + w, 6)
                    for p in payloads]

        fleet = MultiTenantQueue(clock=clock)
        for model_id, weight, slo in MODELS:
            fleet.add_model(model_id, weight=weight, slo_class=slo,
                            depth=32)

        refresher = WeightRefresher(clock=clock)
        fps = {m: refresher.register(
            m, np.full(4, i + 1.0, np.float32))
            for i, (m, _, _) in enumerate(MODELS)}

        pool = ReplicaPool(fleet, drain_timeout_s=1.0,
                           scale_up_depth=6, scale_down_depth=0,
                           scale_hold_s=0.05, clock=clock)
        for i in range(2):
            pool.add_replica(Replica(f"r{i}", executor,
                                     host=f"fleet-host-{i}",
                                     clock=clock))

        got: Dict[str, List[Any]] = {}
        batcher = FleetBatcher(
            fleet, pool, refresher=refresher, max_batch=MAX_BATCH,
            clock=clock,
            on_response=lambda r: got.setdefault(
                r.request_id, []).append(
                    (r.model_id, r.weights_fp, r.result, r.requeues)))

        names = [0]

        def acquire() -> Replica:
            names[0] += 1
            return Replica(f"scale-{names[0]}", executor,
                           host=f"fleet-scale-{names[0]}", clock=clock)

        controller = AutoscaleController(
            pool, acquire, cooldown_s=0.05, min_replicas=1,
            max_replicas=4, clock=clock)

        rng = np.random.RandomState(SEED)
        new_fp = None
        admitted: List[str] = []
        for i in range(N_REQUESTS):
            model_id = MODELS[i % len(MODELS)][0]
            req = InferenceRequest(
                request_id=f"req-{i:03d}",
                payload=rng.rand(4).astype(np.float32),
                model_id=model_id, deadline_s=now[0] + 10.0)
            if fleet.submit(req) == ADMITTED:
                admitted.append(req.request_id)
            if i == REFRESH_AT:
                # the live weight swap, staged mid-load: the flip
                # itself waits for the next between-batches window
                refresher.stage("m0",
                                np.full(4, 9.0, np.float32))
            if i % 2:
                batcher.step()   # interleave so pre-flip batches run
            now[0] += 0.001      # open-loop: arrivals march on

        steps = 0
        while len(fleet) and steps < MAX_STEPS:
            batcher.step()
            controller.poll()
            steps += 1
            if pool.serving_count() == 0:
                break

        drains = [pool.drain(r) for r in pool.replicas() if r.alive]
        new_fp = refresher.fingerprint_of("m0")
        m0_fps = [fp for rs in got.values() for m, fp, _, _ in [rs[0]]
                  if m == "m0"]
        return {
            "admitted": admitted,
            "responses": sorted((rid, tuple(rs))
                                for rid, rs in got.items()),
            "requeued_ids": sorted(rid for rid, rs in got.items()
                                   if any(r[3] > 0 for r in rs)),
            "flips": refresher.flips,
            "rollbacks": refresher.rollbacks,
            "old_fp_m0": fps["m0"],
            "new_fp_m0": new_fp,
            "m0_fp_mix": sorted(set(m0_fps)),
            "scale_ups": controller.scale_ups,
            "deaths": pool.deaths,
            "picks": dict(fleet.pick_counts),
            "drains": drains,
            "steps": steps,
            "clock": round(now[0], 6),
        }
    finally:
        faults.clear_plan()


def run_smoke() -> List[str]:
    """Run the seeded fleet-chaos scenario twice; returns a list of
    error strings (empty = pass)."""
    errors: List[str] = []
    r1 = _scenario()
    r2 = _scenario()
    responded = {rid for rid, _ in r1["responses"]}
    lost = sorted(set(r1["admitted"]) - responded)
    if lost:
        errors.append(f"fleet-smoke: {len(lost)} admitted request(s) "
                      f"lost ({lost[:3]}...)")
    dupes = sorted(rid for rid, rs in r1["responses"] if len(rs) != 1)
    if dupes:
        errors.append(f"fleet-smoke: duplicated responses for "
                      f"{dupes[:3]}")
    if not r1["requeued_ids"]:
        errors.append("fleet-smoke: crash fired but no request was "
                      "re-executed (requeue path untested)")
    if r1["deaths"] != 1:
        errors.append(f"fleet-smoke: expected exactly 1 replica death, "
                      f"saw {r1['deaths']}")
    if r1["flips"] != 1 or r1["rollbacks"] != 0:
        errors.append(f"fleet-smoke: expected 1 clean flip, saw "
                      f"flips={r1['flips']} "
                      f"rollbacks={r1['rollbacks']}")
    if r1["new_fp_m0"] == r1["old_fp_m0"]:
        errors.append("fleet-smoke: refresh flipped but the active "
                      "fingerprint did not change")
    want_mix = sorted({r1["old_fp_m0"], r1["new_fp_m0"]})
    if r1["m0_fp_mix"] != want_mix:
        errors.append(f"fleet-smoke: m0 responses carried fps "
                      f"{r1['m0_fp_mix']}, expected pre-flip + "
                      f"post-flip {want_mix}")
    if r1["scale_ups"] < 1:
        errors.append("fleet-smoke: replica killed under load but the "
                      "autoscale loop never acquired a replacement")
    if min(r1["picks"].values()) < 1:
        errors.append(f"fleet-smoke: a tenant was starved of scheduler "
                      f"picks entirely: {r1['picks']}")
    if not all(r1["drains"]):
        errors.append("fleet-smoke: survivor drain was not graceful")
    if r1 != r2:
        errors.append("fleet-smoke: two seeded runs were not identical")
    return errors
