"""Bounded admission queue for the serving plane (docs/serving.md).

Overload policy is decided at the *front door*, not by collapse: the
queue holds at most ``HOROVOD_SERVE_QUEUE_DEPTH`` requests and sheds
instead of growing — a full queue rejects with backpressure
(``shed_full``), and a request whose deadline cannot be met even if it
ran *right now* (less than the EWMA service-time estimate of budget
left) is shed at admission (``shed_deadline``) rather than queued to
time out after consuming a batch slot.  Requests that expire while
queued are shed at dequeue for the same reason.

Exactly-once bookkeeping: every admitted id carries a state —
``queued`` → ``inflight`` (leased to a replica by :meth:`take`) →
``done`` (:meth:`complete`).  :meth:`requeue` re-admits **only** ids
currently ``inflight``; a second requeue attempt for the same lease, a
resubmission of a live id, or a requeue after completion is a no-op.
That single transition rule is what makes "a replica died mid-batch"
re-execute each in-flight request exactly once (docs/serving.md walks
the proof obligations; ``bench.py --serve`` asserts them under a
seeded crash).

Every mutation is lock-guarded: the continuous batcher's feeder thread
calls :meth:`take`/:meth:`complete` while client threads call
:meth:`submit` (HVD004 discipline).  ``clock`` is injectable so the
smoke/bench scenarios run on a logical clock.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from horovod_tpu import telemetry
from horovod_tpu.runtime.config import _env_int
from horovod_tpu.serve.request import DONE, INFLIGHT, QUEUED, \
    InferenceRequest

DEFAULT_QUEUE_DEPTH = 256
DEFAULT_MAX_REQUEUES = 3

#: admission verdicts (the ``reason`` label on ``hvd_serve_shed_total``)
ADMITTED = "admitted"
SHED_FULL = "shed_full"
SHED_DEADLINE = "shed_deadline"
SHED_DUPLICATE = "shed_duplicate"
SHED_REQUEUE_BUDGET = "shed_requeue_budget"
#: emitted by the tenancy layer (serve/tenancy.py), not this queue: a
#: sheddable SLO class rejected while the fleet is past its overload
#: watermark — counted on ``hvd_serve_tenant_shed_total``
SHED_OVERLOAD = "shed_overload"

_TEL_DEPTH = telemetry.gauge(
    "hvd_serve_queue_depth", "requests waiting for a batch slot")
_TEL_ADMITTED = telemetry.counter(
    "hvd_serve_admitted_total", "requests admitted past the front door")
_TEL_SHED = telemetry.counter(
    "hvd_serve_shed_total",
    "requests shed (reason=shed_full|shed_deadline|shed_duplicate|"
    "shed_requeue_budget)")
_TEL_REQUEUED = telemetry.counter(
    "hvd_serve_requeued_total",
    "in-flight requests re-enqueued after a replica death")
_TEL_COMPLETED = telemetry.counter(
    "hvd_serve_completed_total", "requests completed with a response")


class AdmissionQueue:
    """Bounded FIFO with deadline-aware shedding and exactly-once
    requeue semantics (module docstring)."""

    def __init__(self, depth: Optional[int] = None,
                 max_requeues: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 service_est_s: Optional[float] = None):
        self.depth = depth if depth is not None \
            else _env_int("HOROVOD_SERVE_QUEUE_DEPTH", DEFAULT_QUEUE_DEPTH)
        self.max_requeues = max_requeues if max_requeues is not None \
            else _env_int("HOROVOD_SERVE_MAX_REQUEUES",
                          DEFAULT_MAX_REQUEUES)
        self._clock = clock
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._state: Dict[str, str] = {}
        # EWMA of observed batch service time — the admission
        # controller's "could this run in time if it ran right now"
        # estimate; fed back by the batcher after every batch.  Seed it
        # (``service_est_s``, typically the cost model's plan_cost_s
        # for the model's plan — serve/tenancy.py does) so the FIRST
        # wave of deadline-tiered requests is judged against a real
        # estimate instead of the unseeded zero that admitted
        # guaranteed-late work until the first batch completed.
        self._service_est_s = float(service_est_s or 0.0)
        self._admitting = True

    # -- admission ----------------------------------------------------------

    def submit(self, req: InferenceRequest) -> str:
        """Admit or shed one request; returns the verdict string."""
        now = self._clock()
        with self._lock:
            if not self._admitting:
                return self._shed_locked(SHED_FULL)
            if req.request_id in self._state and \
                    self._state[req.request_id] != DONE:
                # a live id resubmitted (client retry racing its own
                # response) must not yield two responses
                return self._shed_locked(SHED_DUPLICATE)
            if req.deadline_s > 0 and \
                    req.deadline_s - now < self._service_est_s:
                return self._shed_locked(SHED_DEADLINE)
            if len(self._queue) >= self.depth:
                return self._shed_locked(SHED_FULL)
            if not req.arrival_s:
                req.arrival_s = now
            self._queue.append(req)
            self._state[req.request_id] = QUEUED
            _TEL_ADMITTED.inc()
            _TEL_DEPTH.set(len(self._queue))
            return ADMITTED

    def stop_admitting(self) -> None:
        """Drain mode for the whole plane: every subsequent submit is
        shed with backpressure; queued/in-flight work still completes."""
        with self._lock:
            self._admitting = False

    def _shed_locked(self, reason: str) -> str:
        _TEL_SHED.inc(reason=reason)
        return reason

    # -- dequeue / completion ----------------------------------------------

    def take(self, max_n: int, signature=None) -> List[InferenceRequest]:
        """Lease up to ``max_n`` batch-compatible requests (the head's
        signature, or ``signature`` when given); expired-deadline
        requests are shed in passing.  Leased ids go ``inflight``."""
        now = self._clock()
        out: List[InferenceRequest] = []
        with self._lock:
            skipped: List[InferenceRequest] = []
            while self._queue and len(out) < max_n:
                req = self._queue.popleft()
                if req.deadline_s > 0 and now >= req.deadline_s:
                    self._state[req.request_id] = DONE
                    self._shed_locked(SHED_DEADLINE)
                    continue
                if signature is None:
                    signature = req.signature
                if req.signature != signature:
                    skipped.append(req)
                    continue
                self._state[req.request_id] = INFLIGHT
                out.append(req)
            # incompatible signatures return to the head in order
            self._queue.extendleft(reversed(skipped))
            _TEL_DEPTH.set(len(self._queue))
        return out

    def complete(self, request_ids: Iterable[str]) -> None:
        """Mark responded ids ``done`` — after this a requeue of the
        same lease is a no-op (the exactly-once edge)."""
        with self._lock:
            n = 0
            for rid in request_ids:
                if self._state.get(rid) == INFLIGHT:
                    self._state[rid] = DONE
                    n += 1
            if n:
                _TEL_COMPLETED.inc(n)

    def requeue(self, reqs: Iterable[InferenceRequest]) -> int:
        """Re-enqueue a dead replica's leased requests — exactly once
        per lease: only ids currently ``inflight`` re-admit (front of
        the queue, preserving age order); ids past their requeue budget
        are shed instead.  Returns how many re-admitted."""
        with self._lock:
            readmitted: List[InferenceRequest] = []
            for req in reqs:
                if self._state.get(req.request_id) != INFLIGHT:
                    continue
                req.requeues += 1
                if req.requeues > self.max_requeues:
                    self._state[req.request_id] = DONE
                    self._shed_locked(SHED_REQUEUE_BUDGET)
                    continue
                self._state[req.request_id] = QUEUED
                readmitted.append(req)
            self._queue.extendleft(reversed(readmitted))
            if readmitted:
                _TEL_REQUEUED.inc(len(readmitted))
            _TEL_DEPTH.set(len(self._queue))
            return len(readmitted)

    # -- introspection ------------------------------------------------------

    def note_service_time(self, service_s: float) -> None:
        """Batcher feedback: fold one observed batch service time into
        the admission controller's EWMA estimate."""
        with self._lock:
            self._service_est_s = service_s if not self._service_est_s \
                else 0.8 * self._service_est_s + 0.2 * service_s

    @property
    def service_estimate_s(self) -> float:
        """Current EWMA batch-service estimate (seeded or observed)."""
        with self._lock:
            return self._service_est_s

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def admitting(self) -> bool:
        with self._lock:
            return self._admitting

    def state_of(self, request_id: str) -> Optional[str]:
        with self._lock:
            return self._state.get(request_id)
