"""Closed-loop autoscaling: the scale signal finally has a consumer
(docs/serving.md).

PR 12 left ``ReplicaPool.scale_signal()`` as a sensor nobody read.
:class:`AutoscaleController` closes the loop: each :meth:`poll` folds
the signal, the queue depth, and the p99 latency EWMA into a target
replica count, then actuates —

* **acquire** (scale up, or replace a killed replica): the injected
  ``acquire()`` factory builds a replica and the controller adds it to
  the pool.  The factory's executor is typically an
  :class:`~horovod_tpu.serve.batcher.ExecutableCache` routed through
  the AOT disk cache, so a cold replica *deserializes* its executable
  set instead of recompiling — warm start;
* **release** (scale down): the PR 12 graceful drain —
  ``pool.drain()`` on the most recently added serving replica, so the
  departure announces itself to the elastic driver and nothing is
  lost.

**Oscillation-freedom** is layered: the signal source suppresses
direction reversals for ``HOROVOD_SERVE_SCALE_HOLD_S`` (pool.py), and
the controller adds an actuation cooldown
(``HOROVOD_SERVE_SCALE_COOLDOWN_S``) — after any scale action, further
*signal-driven* actions wait out the cooldown.  Capacity lost to a
death bypasses the cooldown (restoring what the target already calls
for is not an oscillation): ``pool.deaths`` is diffed every poll, so a
killed replica both requeues its lease exactly-once (pool.mark_dead)
AND feeds the scale loop.  A seeded open-loop trace with depth
flapping across the threshold is pinned oscillation-free by test.

``on_capacity_change(serving_count)`` fires after every actuation or
observed death — wire it to the PR 14 degrade machinery
(``DegradeController.on_world_change`` / ``DegradedPlanResolver``) so
capacity lost mid-traffic re-resolves the serving plan the same way a
training world-change does.

Fault site ``serve.scale`` fires at the top of every poll; a ``hang``
there models a wedged control loop, a ``raise`` a flaky actuator
(docs/faults.md).  Bounds: ``HOROVOD_SERVE_SCALE_MIN_REPLICAS`` /
``HOROVOD_SERVE_SCALE_MAX_REPLICAS``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_float, _env_int
from horovod_tpu.serve.pool import ReplicaPool
from horovod_tpu.serve.replica import Replica
from horovod_tpu.utils import logging as hvd_logging

DEFAULT_COOLDOWN_S = 30.0
DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 8

_TEL_UPS = telemetry.counter(
    "hvd_serve_scale_ups_total",
    "replicas acquired by the autoscale controller")
_TEL_DOWNS = telemetry.counter(
    "hvd_serve_scale_downs_total",
    "replicas released (graceful drain) by the autoscale controller")
_TEL_TARGET = telemetry.gauge(
    "hvd_serve_scale_target",
    "the autoscale controller's current target replica count")


class AutoscaleController:
    """Sensor → target → actuator loop over a :class:`ReplicaPool`
    (module docstring).

    ``p99_target_s`` > 0 arms the latency term: when the p99 EWMA
    (fed by :meth:`note_latency`, folded at each poll) exceeds the
    target, the controller scales up even if the depth signal is
    quiet — queues hide behind deep batches; tails do not.
    """

    def __init__(self, pool: ReplicaPool,
                 acquire: Callable[[], Replica],
                 cooldown_s: Optional[float] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 p99_target_s: float = 0.0,
                 ewma_alpha: float = 0.2,
                 on_capacity_change: Optional[Callable[[int],
                                                       None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._pool = pool
        self._acquire = acquire
        self.cooldown_s = cooldown_s if cooldown_s is not None \
            else _env_float("HOROVOD_SERVE_SCALE_COOLDOWN_S",
                            DEFAULT_COOLDOWN_S)
        self.min_replicas = min_replicas if min_replicas is not None \
            else _env_int("HOROVOD_SERVE_SCALE_MIN_REPLICAS",
                          DEFAULT_MIN_REPLICAS)
        self.max_replicas = max_replicas if max_replicas is not None \
            else _env_int("HOROVOD_SERVE_SCALE_MAX_REPLICAS",
                          DEFAULT_MAX_REPLICAS)
        self.p99_target_s = p99_target_s
        self.ewma_alpha = ewma_alpha
        self._on_capacity_change = on_capacity_change
        self._clock = clock
        self._lock = threading.Lock()
        self._window: List[float] = []
        self.p99_ewma = 0.0
        self._target = max(pool.serving_count(), self.min_replicas)
        self._deaths_seen = pool.deaths
        self._last_action_t = float("-inf")
        self.scale_ups = 0
        self.scale_downs = 0

    # -- sensors ------------------------------------------------------------

    def note_latency(self, latency_s: float) -> None:
        """Feed one response latency (wire to the batcher's
        ``on_response``); folded into the p99 EWMA at the next poll."""
        with self._lock:
            self._window.append(float(latency_s))

    def _fold_window_locked(self) -> None:
        if not self._window:
            return
        window = sorted(self._window)
        self._window = []
        # nearest-rank p99 of the window, EWMA-folded across polls —
        # pure arithmetic, deterministic for the seeded scenarios
        p99 = window[min(len(window) - 1,
                         int(0.99 * (len(window) - 1) + 0.5))]
        self.p99_ewma = p99 if not self.p99_ewma else \
            (1.0 - self.ewma_alpha) * self.p99_ewma \
            + self.ewma_alpha * p99

    @property
    def target(self) -> int:
        with self._lock:
            return self._target

    # -- the loop -----------------------------------------------------------

    def poll(self) -> int:
        """One control iteration; returns the net replica delta
        actuated (+n acquired, −1 released, 0 held)."""
        faults.inject("serve.scale")
        with self._lock:
            self._fold_window_locked()
            p99_breach = self.p99_target_s > 0 \
                and self.p99_ewma > self.p99_target_s
        serving = self._pool.serving_count()
        deaths = self._pool.deaths
        now = self._clock()
        with self._lock:
            new_deaths = deaths - self._deaths_seen
            self._deaths_seen = deaths
            cooled = now >= self._last_action_t + self.cooldown_s
            target = self._target
            if cooled:
                signal = self._pool.scale_signal()
                if signal > 0 or p99_breach:
                    target = serving + 1
                elif signal < 0:
                    target = serving - 1
            target = max(self.min_replicas,
                         min(self.max_replicas, target))
            self._target = target
            _TEL_TARGET.set(target)
        delta = 0
        # deficit repair (death replacement) ignores the cooldown:
        # restoring already-wanted capacity is not an oscillation
        while serving + delta < target and (cooled or new_deaths > 0):
            replica = self._acquire()
            self._pool.add_replica(replica)
            delta += 1
            with self._lock:
                self.scale_ups += 1
            _TEL_UPS.inc()
            hvd_logging.info(
                "serve: autoscale acquired %s (serving %d → target %d"
                "%s)", replica.name, serving, target,
                ", death repair" if new_deaths > 0 else "")
        if delta == 0 and cooled and serving > target:
            victim = next(
                (r for r in reversed(self._pool.replicas())
                 if r.serving), None)
            if victim is not None:
                self._pool.drain(victim)
                delta -= 1
                with self._lock:
                    self.scale_downs += 1
                _TEL_DOWNS.inc()
                hvd_logging.info(
                    "serve: autoscale released %s (serving %d → "
                    "target %d)", victim.name, serving, target)
        if delta != 0:
            with self._lock:
                self._last_action_t = now
            if self._on_capacity_change is not None:
                self._on_capacity_change(self._pool.serving_count())
        elif new_deaths > 0 and self._on_capacity_change is not None:
            self._on_capacity_change(serving)
        return delta
