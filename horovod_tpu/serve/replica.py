"""One serving replica: executable wrapper + drain state machine.

A replica is a worker slot (``host:local_rank`` under the elastic
driver, or an in-process stand-in for tests) that executes batches.
Its lifecycle mirrors the training worker's (docs/serving.md drain
state machine)::

    SERVING ──begin_drain()──> DRAINING ──finish──> DEPARTED
       │                           │
       └── crash / drain timeout ──┴──────────────> DEAD

``DRAINING`` is the planned-departure path from guard/preempt.py
re-used for serving: the pool stops routing new batches here, in-flight
work finishes, and the departure notice (``PlannedDepartureRequest``)
tells the elastic driver the exit is graceful — no blacklist, no
quarantine, no sibling abort.  ``DEAD`` is the crash path: the pool
re-enqueues the replica's leased requests exactly once.

Fault sites (docs/faults.md): ``serve.batch`` fires before every batch
execution — a ``crash`` (sim → :class:`~horovod_tpu.faults.WorkerCrash`)
models a replica dying mid-batch; ``serve.drain`` fires on the drain
path — a ``raise``/``hang`` models a drain that cannot complete inside
the grace window, which must fall back to the dead path.
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

from horovod_tpu import faults, telemetry

SERVING = "serving"
DRAINING = "draining"
DEPARTED = "departed"
DEAD = "dead"

_TEL_BATCHES = telemetry.counter(
    "hvd_serve_batches_total", "batches executed (per replica label)")


class Replica:
    """One executable-serving slot.  ``executor`` maps a list of
    payloads to a list of results (the batcher packs/unpacks requests
    around it); it is typically a hot-swapped AOT executable from the
    compile cache (batcher.py) or a plain callable in tests."""

    def __init__(self, name: str,
                 executor: Callable[[Sequence[Any]], List[Any]],
                 host: str = "", local_rank: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.executor = executor
        self.host = host or name
        self.local_rank = local_rank
        self._clock = clock
        self.state = SERVING
        self.batches = 0

    @property
    def serving(self) -> bool:
        return self.state == SERVING

    @property
    def alive(self) -> bool:
        return self.state in (SERVING, DRAINING)

    def run_batch(self, payloads: Sequence[Any],
                  model_id: Optional[str] = None,
                  weights: Any = None) -> List[Any]:
        """Execute one packed batch.  The ``serve.batch`` fault site
        fires first: a sim ``crash`` here raises
        :class:`~horovod_tpu.faults.WorkerCrash` mid-batch, which the
        pool converts into the dead path (requeue the lease).

        Fleet callers pass ``model_id`` (the executable hot-swap key —
        serve/batcher.py ExecutableCache) and ``weights`` (the param
        buffer snapshotted once for the whole batch by the refresher's
        atomic flip discipline — serve/refresh.py); both are forwarded
        to the executor as keywords.  Single-model callers keep the
        bare ``executor(payloads)`` contract of PR 12."""
        faults.inject("serve.batch")
        if model_id is None:
            results = self.executor(payloads)
        else:
            results = self.executor(payloads, model_id=model_id,
                                    weights=weights)
        self.batches += 1
        _TEL_BATCHES.inc(replica=self.name)
        return results

    def begin_drain(self) -> None:
        """Stop accepting new batches; in-flight work continues.  The
        pool completes the drain once the lease clears
        (:meth:`ReplicaPool.drain`)."""
        if self.state == SERVING:
            self.state = DRAINING

    def __repr__(self) -> str:
        return f"Replica({self.name!r}, state={self.state})"
