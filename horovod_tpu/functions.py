"""State-synchronization helpers over pytrees and Python objects.

Reference: ``horovod/tensorflow/functions.py`` (``broadcast_variables:47``,
``broadcast_object:59``, ``allgather_object:136``) and
``horovod/torch/functions.py`` (``broadcast_parameters:30``,
``broadcast_optimizer_state:62``).  JAX model state is a pytree, so all
four collapse to pytree-walking wrappers over the eager collectives.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.ops import eager


def broadcast_variables(variables, root_rank: int = 0, name: Optional[str] = None):
    """Broadcast a pytree of arrays from ``root_rank`` to all processes
    (reference ``broadcast_variables`` / the post-restore sync in the
    5-line recipe, ``tensorflow/functions.py:47``).

    Single-process SPMD note: with one process there is nothing to sync —
    all chips already read the same host values; returns input unchanged.
    """
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    prefix = name or "broadcast_variables"
    out = [eager.broadcast(leaf, root_rank, name=f"{prefix}.{i}")
           for i, leaf in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


# torch-flavored alias (reference torch/functions.py:30)
def broadcast_parameters(params, root_rank: int = 0):
    return broadcast_variables(params, root_rank=root_rank,
                               name="broadcast_parameters")


def broadcast_optimizer_state(opt_state, root_rank: int = 0):
    """Broadcast optimizer state (reference ``torch/functions.py:62`` walks
    the torch state dict; optax state is already a pytree)."""
    return broadcast_variables(opt_state, root_rank=root_rank,
                               name="broadcast_optimizer_state")


def _obj_to_bytes_tensor(obj: Any) -> jnp.ndarray:
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    return jnp.frombuffer(np.frombuffer(buf.getvalue(), np.uint8), jnp.uint8)


def _bytes_tensor_to_obj(t) -> Any:
    return pickle.loads(np.asarray(t).tobytes())


def broadcast_object(obj: Any = None, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Serialize an arbitrary Python object on ``root_rank`` and broadcast
    it (reference ``tensorflow/functions.py:59`` / ``torch/functions.py``:
    pickle → length bcast → payload bcast)."""
    name = name or "broadcast_object"
    if eager.process_mesh().devices.size == 1:
        return obj
    if jax.process_index() == root_rank:
        payload = _obj_to_bytes_tensor(obj)
        length = jnp.asarray([payload.size], jnp.int64)
    else:
        payload = jnp.zeros((0,), jnp.uint8)
        length = jnp.asarray([0], jnp.int64)
    length = eager.broadcast(length, root_rank, name=f"{name}.len")
    n = int(length[0])
    if jax.process_index() != root_rank:
        payload = jnp.zeros((n,), jnp.uint8)
    payload = eager.broadcast(payload, root_rank, name=f"{name}.payload")
    return _bytes_tensor_to_obj(payload)


def allgather_object(obj: Any, name: Optional[str] = None) -> list:
    """Gather one Python object per process into an ordered list (reference
    ``tensorflow/functions.py:136``)."""
    name = name or "allgather_object"
    nproc = eager.process_mesh().devices.size
    if nproc == 1:
        return [obj]
    payload = _obj_to_bytes_tensor(obj)
    # the gather negotiates per-process sizes internally; reuse them rather
    # than running a second collective for the same numbers
    gathered, sizes_np = eager.allgather_with_sizes(payload, name=name)
    out, off = [], 0
    for p in range(nproc):
        n = int(sizes_np[p])
        out.append(_bytes_tensor_to_obj(gathered[off:off + n]))
        off += n
    return out
