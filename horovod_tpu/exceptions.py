"""The two control-flow exceptions of elastic training (reference
``horovod/common/exceptions.py``)."""

from __future__ import annotations


class HorovodInternalError(Exception):
    """Internal error raised from a collective — under elastic training this
    triggers state restore + reinitialization (reference ``exceptions.py:18``)."""


class HostsUpdatedInterrupt(Exception):
    """Raised between batches when the host set changed; training continues
    with current (not rolled back) state after re-rendezvous (reference
    ``exceptions.py:26``)."""

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class HorovodTpuError(RuntimeError):
    """Generic framework error."""
