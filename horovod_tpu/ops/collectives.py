"""SPMD collective primitives — the TPU data plane.

This is the TPU-native replacement for the reference's op layer
(``horovod/common/ops/``): where ``NCCLAllreduce::Execute``
(``nccl_operations.cc:126``) launches ``ncclAllReduce`` on a side stream,
these functions emit XLA collectives (``lax.psum``/``all_gather``/
``all_to_all``/``ppermute``) *inside* the compiled step, where the compiler
overlaps them with compute — the role the reference's dedicated GPU streams
and event queues played by hand (``gpu_operations.h:51-127``).

Every function here must be called under ``shard_map``/``pmap`` with a bound
axis name.  Defaults reduce over the full (dcn, ici) runtime mesh; passing
``axis=AXIS_ICI`` or ``AXIS_DCN`` reproduces the reference's LOCAL/CROSS
communicator collectives (``common.h:113-117``).

Capability parity (reference collective inventory, ``operations.cc:677-1068``):
allreduce (sum/average/adasum + pre/postscale), allgather (incl. variable
first dim), broadcast, alltoall (with splits), reducescatter, barrier, and
the bitwise AND/OR bitvector reductions the controller uses internally
(``mpi_controller.cc:88-106``).
"""

from __future__ import annotations

import dataclasses
import enum
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu.runtime.topology import AXIS_DCN, AXIS_ICI, GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


class ReduceOp(enum.IntEnum):
    """Reduction selector (reference ``ReduceOp``: Average=0, Sum=1, Adasum=2
    in ``horovod/torch/mpi_ops.py``; extended with elementwise min/max/product
    which the XLA backend gets for free)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Aliases matching the reference Python API surface
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM


def axis_size(axis: AxisSpec = GLOBAL_AXES) -> jax.Array:
    if isinstance(axis, str):
        return lax.axis_size(axis)
    n = 1
    for a in axis:
        n *= lax.axis_size(a)
    return n


def axis_index(axis: AxisSpec = GLOBAL_AXES) -> jax.Array:
    """Linearized rank of this shard along ``axis`` (row-major over the
    axis tuple, matching mesh order)."""
    if isinstance(axis, str):
        return lax.axis_index(axis)
    idx = jnp.int32(0)
    for a in axis:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def _scale(x: jax.Array, factor: Optional[float]) -> jax.Array:
    if factor is None or factor == 1.0:
        return x
    # match reference DoAllreduce: scaling in fp32 for low-precision inputs
    # when the factor is not exactly representable (operations.cc:851-866)
    if x.dtype in (jnp.float16, jnp.bfloat16):
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return x * factor


def allreduce(x: jax.Array,
              op: ReduceOp = Average,
              axis: AxisSpec = GLOBAL_AXES,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None) -> jax.Array:
    """Allreduce over mesh axis(es) with reference semantics.

    Average divides by the axis size (reference postscale 1/size,
    ``operations.cc:851-854``); Adasum dispatches to the adaptive-summation
    reduction (``ops/adasum/adasum.h``; see ``horovod_tpu.ops.adasum``).
    """
    if op == ReduceOp.ADASUM:
        from horovod_tpu.ops.adasum import adasum_allreduce

        return _scale(adasum_allreduce(_scale(x, prescale_factor), axis=axis),
                      postscale_factor)

    x = _scale(x, prescale_factor)
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
        y = lax.psum(x, axis)
        if op == ReduceOp.AVERAGE:
            y = _scale(y, 1.0 / axis_size(axis))
    elif op == ReduceOp.MIN:
        y = lax.pmin(x, axis)
    elif op == ReduceOp.MAX:
        y = lax.pmax(x, axis)
    elif op == ReduceOp.PRODUCT:
        # no product collective in XLA: gather-then-reduce (small tensors
        # only; the reference has no product op at all)
        gathered = x[None]
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        for a in reversed(axes):
            gathered = lax.all_gather(gathered, a, tiled=True)
        y = jnp.prod(gathered, axis=0)
    else:
        raise ValueError(f"unsupported ReduceOp {op}")
    return _scale(y, postscale_factor)


def grouped_allreduce(xs: Sequence[jax.Array],
                      op: ReduceOp = Average,
                      axis: AxisSpec = GLOBAL_AXES,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None,
                      quantized_bits: Optional[int] = None) -> list:
    """Fused allreduce of many tensors — Tensor Fusion, compiler-era.

    The reference packs small gradients into one 64 MiB fusion buffer
    (``fusion_buffer_manager.{h,cc}``, ``controller.cc:686 FuseResponses``)
    to amortize per-collective latency.  Under XLA a *grouped* psum of a
    pytree gives the combiner the same opportunity without the double
    memcpy: we flatten-concatenate per dtype and issue one psum per dtype
    group, then split back — one collective per dtype regardless of tensor
    count.

    ``quantized_bits=8`` routes each *float* dtype group through
    :func:`quantized_allreduce` (int8 wire, shared-scale); integer
    groups stay on the exact psum.
    """
    if not xs:
        return []
    if quantized_bits is not None and op not in (ReduceOp.SUM,
                                                 ReduceOp.AVERAGE):
        raise ValueError("quantized_bits supports op=Sum/Average")
    if op == ReduceOp.ADASUM:
        from horovod_tpu.ops.adasum import adasum_grouped_allreduce

        return adasum_grouped_allreduce(
            [_scale(x, prescale_factor) for x in xs], axis=axis)

    groups: dict = {}
    for i, x in enumerate(xs):
        groups.setdefault(x.dtype, []).append(i)
    out: list = [None] * len(xs)
    for dtype, idxs in groups.items():
        flat = jnp.concatenate(
            [jnp.ravel(_scale(xs[i], prescale_factor)) for i in idxs])
        if quantized_bits is not None and \
                jnp.issubdtype(dtype, jnp.floating):
            red = _scale(
                quantized_allreduce(
                    flat, axis=axis, op=op, bits=quantized_bits,
                    segments=tuple(int(xs[i].size) for i in idxs)),
                postscale_factor)
        else:
            red = allreduce(flat, op=op, axis=axis,
                            postscale_factor=postscale_factor)
        offset = 0
        for i in idxs:
            n = xs[i].size
            out[i] = red[offset:offset + n].reshape(xs[i].shape)
            offset += n
    return out


#: Valid wire codecs for the quantized (DCN) exchange hop
#: (``HOROVOD_EXCHANGE_WIRE_DTYPE``): shared-scale int8 (exact int32
#: accumulation, the PR 2 codec) or fp8 e4m3 (floating wire — graceful
#: within-segment dynamic range at a coarser 3-bit mantissa; EQuARX's
#: low-precision-wire argument, arXiv:2506.17615).
WIRE_DTYPES = ("int8", "fp8_e4m3")

#: absmax quantization targets per wire codec: int8 clips at ±127,
#: e4m3's largest finite is ±448
_WIRE_QMAX = {"int8": 127.0, "fp8_e4m3": 448.0}


def _resolve_wire_dtype(wire_dtype: Optional[str]) -> str:
    """Wire codec resolution: explicit argument > runtime config
    (``HOROVOD_EXCHANGE_WIRE_DTYPE``) > int8 default."""
    if wire_dtype is None:
        from horovod_tpu.runtime import state as _rt

        if _rt.is_initialized():
            wire_dtype = getattr(_rt.global_state().config,
                                 "exchange_wire_dtype", "int8")
        else:
            import os

            wire_dtype = os.environ.get(
                "HOROVOD_EXCHANGE_WIRE_DTYPE", "int8").lower() or "int8"
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(
            f"exchange wire dtype must be one of {WIRE_DTYPES}, got "
            f"{wire_dtype!r}")
    return wire_dtype


#: Reduction operators of the sharded exchange
#: (``HOROVOD_EXCHANGE_REDUCTION``): plain summation, or AdaSum
#: adaptive summation (arXiv 2006.02924) on the OUTERMOST topology
#: level only — orthogonal gradients add, near-parallel gradients
#: average, so a 2-4x larger global batch keeps the small-batch loss
#: trajectory where plain averaging stalls (docs/adasum.md).
REDUCTIONS = ("sum", "adasum")


def _resolve_reduction(reduction: Optional[str]) -> str:
    """Reduction-operator resolution: explicit argument > runtime config
    (``HOROVOD_EXCHANGE_REDUCTION``) > plain-sum default."""
    if reduction is None:
        from horovod_tpu.runtime import state as _rt

        if _rt.is_initialized():
            reduction = getattr(_rt.global_state().config,
                                "exchange_reduction", "sum")
        else:
            import os

            reduction = os.environ.get(
                "HOROVOD_EXCHANGE_REDUCTION", "sum").lower() or "sum"
    if reduction not in REDUCTIONS:
        raise ValueError(
            f"exchange reduction must be one of {REDUCTIONS}, got "
            f"{reduction!r}")
    return reduction


def adasum_pair(a, b, xp=jnp):
    """One pairwise AdaSum combine (arXiv 2006.02924, the reference's
    ``adasum.h`` coefficient rule)::

        a·(1 − ⟨a,b⟩/2‖a‖²) + b·(1 − ⟨a,b⟩/2‖b‖²)

    which is ``a+b`` for orthogonal gradients and the average for
    parallel ones.  Dot/norms accumulate in fp32 regardless of input
    dtype (the reference widens its fp16 path the same way), and a
    zero-norm operand degrades its coefficient to 1 — the plain-sum
    guard, so all-zero gradients pass through exactly.

    ``xp``-generic (jnp or numpy) so the pure-sim smoke gate
    (``analysis/adasum_smoke.py``) and the traced exchange share these
    exact numerics; the eager numpy path additionally counts actual
    zero-norm fallbacks into telemetry (the traced path cannot observe
    data-dependent events at trace time).
    """
    af = a.astype(xp.float32)
    bf = b.astype(xp.float32)
    dot = xp.vdot(af, bf)
    anormsq = xp.vdot(af, af)
    bnormsq = xp.vdot(bf, bf)
    acoeff = xp.where(anormsq >= 1e-30,
                      1.0 - dot / (2.0 * anormsq + 1e-30), 1.0)
    bcoeff = xp.where(bnormsq >= 1e-30,
                      1.0 - dot / (2.0 * bnormsq + 1e-30), 1.0)
    if xp is np:
        fallbacks = int(anormsq < 1e-30) + int(bnormsq < 1e-30)
        if fallbacks:
            from horovod_tpu import telemetry

            telemetry.counter(
                "hvd_adasum_zero_norm_fallbacks_total",
                "zero-norm plain-sum guard activations in adasum_pair"
            ).inc(fallbacks)
    return (acoeff * af + bcoeff * bf).astype(a.dtype)


def quantized_allreduce(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
                        op: ReduceOp = Average,
                        bits: int = 8,
                        segments: Sequence[int] = (),
                        wire_dtype: Optional[str] = None) -> jax.Array:
    """Average/sum with an int8-quantized wire (EQuARX-style, arXiv
    2506.17615): agree on a shared scale via one ``pmax``, quantize to
    int8, accumulate the psum in int32 (no overflow, exact integer
    summation), dequantize with the shared scale.  Wire cost of the main
    reduction is 1 byte/element vs 4 for fp32; accuracy cost is one
    absmax-scaled rounding, identical on every shard.

    ``segments`` gives per-tensor lengths of a fused flat buffer: each
    segment then gets its *own* shared scale (one small-vector ``pmax``),
    so a small-magnitude gradient fused next to a large one is not
    rounded to zero — the quantization error is bounded per tensor, and
    the wire still carries a single fused int8 psum.

    ``wire_dtype`` selects the codec (default: the runtime's
    ``HOROVOD_EXCHANGE_WIRE_DTYPE``): ``"int8"`` keeps the exact-int32
    accumulation above; ``"fp8_e4m3"`` casts the absmax-scaled values
    to e4m3 on the wire and accumulates in fp32 — a coarser 3-bit
    mantissa, but each element keeps ~2 decimal digits of *relative*
    precision instead of sharing one absolute step across the segment.
    """
    if bits != 8:
        raise ValueError("only 8-bit quantization is supported")
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("quantized_allreduce supports Sum/Average")
    wire = _resolve_wire_dtype(wire_dtype)
    x32 = x.astype(jnp.float32)
    scale = _shared_wire_scale(x32, segments, axis, qmax=_WIRE_QMAX[wire])
    if wire == "fp8_e4m3":
        q8 = jnp.clip(x32 / scale, -448.0, 448.0) \
            .astype(jnp.float8_e4m3fn)
        total = lax.psum(q8.astype(jnp.float32), axis)
        y = total * scale
    else:
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
        total = lax.psum(q.astype(jnp.int32), axis)
        y = total.astype(jnp.float32) * scale
    if op == ReduceOp.AVERAGE:
        y = y / axis_size(axis)
    return y.astype(x.dtype)


def _shared_wire_scale(x32: jax.Array, segments: Sequence[int],
                       axis: AxisSpec, qmax: float = 127.0) -> jax.Array:
    """Shared quantization scale(s) for a (fused) flat buffer —
    the codec core of :func:`quantized_allreduce`, reused by
    :func:`quantized_reducescatter`.  One ``pmax`` agrees on the
    per-segment absmax across shards; returns a scalar (no segments)
    or a per-element scale vector (one scale per fused tensor).
    ``qmax`` is the codec's largest representable magnitude (127 for
    int8, 448 for fp8 e4m3)."""
    if segments and len(segments) > 1:
        if x32.ndim != 1 or sum(segments) != x32.shape[0]:
            raise ValueError("segments must partition a flat buffer")
        bounds = np.cumsum([0] + list(segments))
        local_amax = jnp.stack(
            [jnp.max(jnp.abs(x32[bounds[i]:bounds[i + 1]]))
             for i in range(len(segments))])
        scales = lax.pmax(local_amax, axis) / qmax
        scales = jnp.maximum(scales, 1e-30)
        return jnp.repeat(scales, np.asarray(segments),
                          total_repeat_length=x32.shape[0])
    local_amax = jnp.max(jnp.abs(x32))
    scale = lax.pmax(local_amax, axis) / qmax
    return jnp.maximum(scale, 1e-30)


def quantized_reducescatter(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
                            op: ReduceOp = Average,
                            bits: int = 8,
                            segments: Sequence[int] = (),
                            wire_dtype: Optional[str] = None) -> jax.Array:
    """Reduce-scatter with the low-precision wire of
    :func:`quantized_allreduce` (same shared-scale codec: one ``pmax``
    agrees the scale; int8 wire with exact int32 accumulation, or the
    fp8 e4m3 wire with fp32 accumulation per ``wire_dtype`` /
    ``HOROVOD_EXCHANGE_WIRE_DTYPE``).  ``x`` must be flat with
    length divisible by the axis world size; each shard receives its
    dequantized 1/world slice.  With ``segments``, per-tensor scales
    are used and this shard dequantizes with the scale entries of its
    own slice."""
    if bits != 8:
        raise ValueError("only 8-bit quantization is supported")
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("quantized_reducescatter supports Sum/Average")
    wire = _resolve_wire_dtype(wire_dtype)
    world = axis_size(axis)
    if x.ndim != 1 or x.shape[0] % world:
        raise ValueError(
            f"quantized_reducescatter needs a flat buffer divisible by "
            f"world size {world}, got shape {x.shape}")
    x32 = x.astype(jnp.float32)
    scale = _shared_wire_scale(x32, segments, axis, qmax=_WIRE_QMAX[wire])
    ax = axis if isinstance(axis, str) else tuple(axis)
    if wire == "fp8_e4m3":
        q8 = jnp.clip(x32 / scale, -448.0, 448.0) \
            .astype(jnp.float8_e4m3fn)
        total = lax.psum_scatter(q8.astype(jnp.float32), ax, tiled=True)
    else:
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
        total = lax.psum_scatter(q.astype(jnp.int32), ax, tiled=True) \
            .astype(jnp.float32)
    shard = x.shape[0] // world
    if scale.ndim:          # per-segment scales: this shard's slice
        scale = lax.dynamic_slice(scale, (axis_index(axis) * shard,),
                                  (shard,))
    y = total * scale
    if op == ReduceOp.AVERAGE:
        y = y / world
    return y.astype(x.dtype)


def ef_quantized_reducescatter(x: jax.Array,
                               axis: AxisSpec = GLOBAL_AXES,
                               op: ReduceOp = Average,
                               residual: Optional[jax.Array] = None,
                               bits: int = 8,
                               segments: Sequence[int] = (),
                               wire_dtype: Optional[str] = None):
    """:func:`quantized_reducescatter` with error-feedback residuals
    (EF-SGD / 1-bit-Adam lineage): the quantization rounding error of
    step *t* is carried locally and added back to the input of step
    *t+1*, so the bias of the low-precision wire telescopes away
    instead of accumulating into the trajectory.

    Per step, with ``r`` the carried residual::

        e   = x + r                  # error-compensated input (fp32)
        q   = Q(e)                   # shared-scale int8 / fp8 codec
        r'  = e - dQ(q)              # what the wire failed to carry
        out = reduce_scatter(q)      # exact low-precision reduction

    ``dQ(q)`` is this rank's *own* dequantized contribution at full
    buffer length (the codec's exact int32 / fp32 accumulation means
    the reduced sum is the sum of exactly these per-rank values, so
    each rank's residual accounts for precisely its share of the
    total error).  ``op=Average`` scales only the reduced shard; the
    residual stays in per-rank sum-contribution units, matching the
    next step's pre-reduction input.

    Returns ``(shard, new_residual)`` — the dequantized 1/world slice
    (like :func:`quantized_reducescatter`) plus the full-length fp32
    residual to feed back next step.
    """
    if bits != 8:
        raise ValueError("only 8-bit quantization is supported")
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("ef_quantized_reducescatter supports "
                         "Sum/Average")
    wire = _resolve_wire_dtype(wire_dtype)
    world = axis_size(axis)
    if x.ndim != 1 or x.shape[0] % world:
        raise ValueError(
            f"ef_quantized_reducescatter needs a flat buffer divisible "
            f"by world size {world}, got shape {x.shape}")
    x32 = x.astype(jnp.float32)
    if residual is not None:
        x32 = x32 + residual.astype(jnp.float32)
    scale = _shared_wire_scale(x32, segments, axis, qmax=_WIRE_QMAX[wire])
    ax = axis if isinstance(axis, str) else tuple(axis)
    if wire == "fp8_e4m3":
        sent = jnp.clip(x32 / scale, -448.0, 448.0) \
            .astype(jnp.float8_e4m3fn).astype(jnp.float32)
        total = lax.psum_scatter(sent, ax, tiled=True)
    else:
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
        sent = q.astype(jnp.float32)
        total = lax.psum_scatter(q.astype(jnp.int32), ax, tiled=True) \
            .astype(jnp.float32)
    new_residual = x32 - sent * scale
    shard = x.shape[0] // world
    if scale.ndim:          # per-segment scales: this shard's slice
        scale = lax.dynamic_slice(scale, (axis_index(axis) * shard,),
                                  (shard,))
    y = total * scale
    if op == ReduceOp.AVERAGE:
        y = y / world
    return y.astype(x.dtype), new_residual


@dataclasses.dataclass(frozen=True)
class ShardGroup:
    """One fused wire buffer of the sharded exchange: the leaves of a
    single (bucket, dtype) cell, concatenated flat and padded to a
    shard-divisible length."""

    key: str                        # "b<bucket>/<dtype>" — shard dict key
    dtype: str                      # jnp dtype name
    indices: Tuple[int, ...]        # original leaf indices, bucket order
    sizes: Tuple[int, ...]          # per-leaf element counts
    shapes: Tuple[Tuple[int, ...], ...]
    padded: int                     # flat length after zero-padding
    shard: int                      # padded // world — this rank's slice


@dataclasses.dataclass(frozen=True)
class FusionSpec:
    """Static reassembly plan for a bucketed sharded exchange.

    Built from leaf shapes only (deterministic across shards — the
    same invariant the eager :class:`~horovod_tpu.ops.bucketing.Bucketer`
    keeps by flushing on program order, here enforced by construction).
    Carries everything :func:`grouped_allgather` needs to reverse
    :func:`grouped_reducescatter`."""

    groups: Tuple[ShardGroup, ...]
    world: int
    num_leaves: int


def make_fusion_spec(leaves: Sequence[jax.Array], world: int,
                     bucket_bytes: Optional[int] = None) -> FusionSpec:
    """Plan the bucketed sharded exchange for ``leaves``.

    Buckets come from :func:`horovod_tpu.ops.bucketing.plan_buckets`
    in reverse-layer order (see there for why); within a bucket the
    leaves split per dtype — mixed-dtype buckets ride as one bucket
    with one wire collective per member dtype, exactly like
    :func:`grouped_allreduce`'s dtype groups.  Each group's flat
    length is padded up to the next multiple of ``world`` so
    ``psum_scatter`` tiles evenly."""
    from horovod_tpu.ops.bucketing import plan_buckets

    nbytes = [x.size * x.dtype.itemsize for x in leaves]
    buckets = plan_buckets(nbytes, bucket_bytes, reverse=True)
    groups: List[ShardGroup] = []
    for b, idxs in enumerate(buckets):
        by_dtype: Dict[str, List[int]] = {}
        for i in idxs:
            by_dtype.setdefault(jnp.dtype(leaves[i].dtype).name,
                                []).append(i)
        for dtype, members in by_dtype.items():
            total = sum(leaves[i].size for i in members)
            padded = -(-max(total, 1) // world) * world
            groups.append(ShardGroup(
                key=f"b{b}/{dtype}", dtype=dtype,
                indices=tuple(members),
                sizes=tuple(int(leaves[i].size) for i in members),
                shapes=tuple(tuple(leaves[i].shape) for i in members),
                padded=padded, shard=padded // world))
    return FusionSpec(groups=tuple(groups), world=world,
                      num_leaves=len(leaves))


def _group_flat(group: ShardGroup, leaves: Sequence[jax.Array],
                prescale: Optional[float] = None) -> jax.Array:
    """Concatenate + zero-pad a group's leaves into its wire buffer."""
    flat = jnp.concatenate(
        [jnp.ravel(_scale(leaves[i], prescale)) for i in group.indices]) \
        if group.indices else jnp.zeros((0,), jnp.dtype(group.dtype))
    pad = group.padded - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def local_fusion_shards(leaves: Sequence[jax.Array], spec: FusionSpec,
                        axis: AxisSpec = GLOBAL_AXES) -> Dict[str, jax.Array]:
    """This rank's slice of every fused group buffer — no collective,
    just concat + ``dynamic_slice`` at ``rank * shard``.  The sharded
    optimizer uses this to see the *parameter* values co-located with
    the gradient shard it owns."""
    me = axis_index(axis)
    out: Dict[str, jax.Array] = {}
    for g in spec.groups:
        flat = _group_flat(g, leaves)
        out[g.key] = lax.dynamic_slice(flat, (me * g.shard,), (g.shard,))
    return out


#: Tile count of the tile-granular final-bucket exchange
#: (``fused_tail``, docs/fused_kernels.md): the last bucket's wire is
#: split into this many independent sub-collectives so the scheduler
#: can overlap tile k's exchange with the shard-update math consuming
#: tile k-1 — the serial tail the bucketed overlap cannot hide.
FUSED_TAIL_TILES = 4


def _count_fused_tail() -> None:
    from horovod_tpu import telemetry

    telemetry.counter(
        "hvd_pallas_fused_launches_total",
        "tile-fused matmul-collective kernel constructions per kernel"
    ).inc(kernel="tail_reducescatter")


def _tiled_psum_scatter(flat: jax.Array, ax, world: int,
                        tiles: int = FUSED_TAIL_TILES) -> jax.Array:
    """Tile-granular ``psum_scatter`` of one fused flat buffer: the
    per-rank shard splits into ``tiles`` segments, each exchanged by
    its own independent collective, and the reduced shard is their
    concatenation — numerically identical to the monolithic scatter
    (same summation structure per element), but the compiler is free
    to start tile k+1's wire while tile k's output is already being
    consumed.  This is the ZeRO final-bucket form of the tile-fused
    exchange (the matmul⊗collective kernels in
    :mod:`~horovod_tpu.ops.pallas_kernels` are the tensor-parallel
    form)."""
    shard = flat.shape[0] // world
    tiles = max(1, min(int(tiles), shard if shard else 1))
    if tiles == 1 or world == 1:
        return lax.psum_scatter(flat, ax, tiled=True)
    _count_fused_tail()
    x = flat.reshape(world, shard)
    outs = []
    for t in range(tiles):
        lo = t * shard // tiles
        hi = (t + 1) * shard // tiles
        if hi == lo:
            continue
        seg = x[:, lo:hi].reshape(-1)
        outs.append(lax.psum_scatter(seg, ax, tiled=True))
    return jnp.concatenate(outs)


def grouped_reducescatter(xs: Sequence[jax.Array],
                          op: ReduceOp = Sum,
                          axis: AxisSpec = GLOBAL_AXES,
                          prescale_factor: Optional[float] = None,
                          postscale_factor: Optional[float] = None,
                          quantized_bits: Optional[int] = None,
                          bucket_bytes: Optional[int] = None,
                          spec: Optional[FusionSpec] = None,
                          fused_tail: bool = False,
                          residuals: Optional[Dict[str, jax.Array]] = None):
    """Fused reduce-scatter of many tensors — the first half of the
    ZeRO-style rewrite of :func:`grouped_allreduce` (reduce-scatter →
    shard-local math → allgather), with the same fusion machinery:
    per-(bucket, dtype) flat buffers, zero-padding to shard-divisible
    lengths, and the int8 wire of :func:`quantized_allreduce` via
    ``quantized_bits=8``.

    Returns ``(shards, spec)``: ``shards`` maps each
    :class:`ShardGroup` key to this rank's reduced ``(shard,)`` slice;
    ``spec`` is the static plan :func:`grouped_allgather` (or
    :func:`local_fusion_shards`) consumes.  ``bucket_bytes`` splits
    the exchange into reverse-layer-order buckets so XLA can overlap
    each bucket's collective with the rest of backward (see
    :func:`horovod_tpu.ops.bucketing.plan_buckets`); ``None`` keeps
    the monolithic single-bucket exchange.  ``fused_tail=True`` splits
    the LAST group's wire into :data:`FUSED_TAIL_TILES` independent
    sub-collectives (:func:`_tiled_psum_scatter`) — the tile-granular
    form of the final-bucket exchange, which no remaining backward
    work can hide (docs/fused_kernels.md); numerics are identical,
    only the schedule changes.  The quantized wire keeps its
    monolithic shared-scale collective (the codec scale is agreed per
    buffer).

    ``residuals`` (a ``{group key: (padded,) fp32}`` dict) switches the
    quantized groups to the error-feedback codec
    (:func:`ef_quantized_reducescatter`) and changes the return to
    ``(shards, spec, new_residuals)`` — feed ``new_residuals`` back on
    the next call so the wire's rounding bias telescopes away.  Groups
    without a residual entry (non-floating, or quantization off) pass
    through unchanged.

    Degenerate 1-shard worlds reduce to plain identity semantics: the
    "shard" is the whole (padded) buffer and ``psum_scatter`` over a
    size-1 axis is the local value itself.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("grouped_reducescatter supports op=Sum/Average")
    world = int(axis_size(axis))
    if spec is None:
        spec = make_fusion_spec(xs, world, bucket_bytes)
    elif spec.world != world:
        raise ValueError(
            f"spec was planned for world {spec.world}, axis has {world}")
    ax = axis if isinstance(axis, str) else tuple(axis)
    shards: Dict[str, jax.Array] = {}
    new_residuals: Dict[str, jax.Array] = \
        dict(residuals) if residuals is not None else {}
    for gi, g in enumerate(spec.groups):
        flat = _group_flat(g, xs, prescale_factor)
        floating = jnp.issubdtype(flat.dtype, jnp.floating)
        tail = fused_tail and gi == len(spec.groups) - 1
        if quantized_bits is not None and floating:
            # pad rides the last segment: zeros never raise its absmax
            segs = list(g.sizes)
            segs[-1] += g.padded - sum(g.sizes)
            if residuals is not None and g.key in residuals:
                red, new_residuals[g.key] = ef_quantized_reducescatter(
                    flat, axis=axis, op=op, residual=residuals[g.key],
                    bits=quantized_bits, segments=tuple(segs))
            else:
                red = quantized_reducescatter(flat, axis=axis, op=op,
                                              bits=quantized_bits,
                                              segments=tuple(segs))
        elif tail:
            red = _tiled_psum_scatter(flat, ax, world)
            if op == ReduceOp.AVERAGE and floating:
                red = _scale(red, 1.0 / world)
            elif op == ReduceOp.AVERAGE:
                raise ValueError(
                    "op=Average requires floating dtypes, got "
                    f"{g.dtype}")
        else:
            red = lax.psum_scatter(flat, ax, tiled=True)
            if op == ReduceOp.AVERAGE and floating:
                red = _scale(red, 1.0 / world)
            elif op == ReduceOp.AVERAGE:
                raise ValueError(
                    "op=Average requires floating dtypes, got "
                    f"{g.dtype}")
        shards[g.key] = _scale(red, postscale_factor)
    if residuals is not None:
        return shards, spec, new_residuals
    return shards, spec


def exchange_index_axes(outer_axis: str = AXIS_DCN,
                        inner_axis: str = AXIS_ICI) -> Tuple[str, str]:
    """Axis tuple whose row-major linearization matches the shard
    ownership of :func:`hierarchical_reducescatter`.

    The two-level exchange reduce-scatters over ``inner_axis`` first
    (the intra-slice ICI phase), then over ``outer_axis`` (the
    cross-slice DCN phase), so the rank holding flat-buffer block ``k``
    satisfies ``k = inner_index * outer_size + outer_index`` — row-major
    over ``(inner, outer)``, NOT the mesh's usual ``(outer, inner)``.
    Feed this tuple to :func:`local_fusion_shards` /
    :func:`grouped_allgather` (and :func:`axis_index`) so parameter
    slices and reassembly line up with the hierarchical ownership."""
    return (inner_axis, outer_axis)


@dataclasses.dataclass(frozen=True)
class ExchangeLevel:
    """One level of the N-level tree exchange: the mesh axis (or axis
    tuple, for a degenerate flat level spanning the world) this level's
    collectives scope to, and the wire-codec width on its hop (None =
    full precision).  Levels are ordered INNERMOST first — chip <
    slice < pod < cluster (``runtime/topology.TopologyTree``)."""

    axis: AxisSpec
    quantized_bits: Optional[int] = None


def exchange_levels_from_topology(tree) -> Tuple["ExchangeLevel", ...]:
    """The :class:`ExchangeLevel` sequence of one resolved
    ``runtime/topology.TopologyTree``: each level scopes to its own
    mesh axis at its configured ``wire_bits`` — how the per-level
    codec knob (``HOROVOD_EXCHANGE_LEVEL_CODECS``) reaches the data
    plane."""
    return tuple(ExchangeLevel(axis=lv.axis_spec,
                               quantized_bits=lv.wire_bits)
                 for lv in tree.levels)


def tree_index_axes(levels: Sequence[ExchangeLevel]) -> Tuple[str, ...]:
    """Axis tuple whose row-major linearization matches the shard
    ownership of :func:`tree_reducescatter` — the N-level
    generalization of :func:`exchange_index_axes`.

    Phase ℓ reduce-scatters the block surviving the inner phases, so
    the rank holding flat-buffer block ``k`` satisfies ``k = i₀·(n₁·…)
    + i₁·(n₂·…) + …`` — row-major over the levels innermost-FIRST
    (level 0 is the slowest digit).  Feed this tuple to
    :func:`tree_allgather` / :func:`local_fusion_shards` /
    :func:`axis_index` so slices and reassembly line up."""
    axes: List[str] = []
    for lv in levels:
        ax = lv.axis
        if isinstance(ax, str):
            axes.append(ax)
        else:
            axes.extend(ax)
    return tuple(axes)


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _count_adasum_exchange(ax) -> None:
    from horovod_tpu import telemetry

    telemetry.counter(
        "hvd_adasum_steps_total",
        "adasum outer-level exchange constructions per level"
    ).inc(level=str(ax))


def _quantized_pair_exchange(x: jax.Array, ax, perm,
                             wire_dtype: Optional[str] = None):
    """One codec-compressed ``ppermute`` round of the adasum schedule.

    The absmax scale is agreed over the whole level with one ``pmax``
    (every rank holds the identical scale), so the XOR partner
    dequantizes the received payload exactly; BOTH sides of the combine
    see dequantized wire values — the pairwise rule stays symmetric, so
    partners compute identical results and the recursive doubling keeps
    its all-ranks-converge property under quantization."""
    wire = _resolve_wire_dtype(wire_dtype)
    x32 = x.astype(jnp.float32)
    scale = _shared_wire_scale(x32, (), ax, qmax=_WIRE_QMAX[wire])
    if wire == "fp8_e4m3":
        q = jnp.clip(x32 / scale, -448.0, 448.0) \
            .astype(jnp.float8_e4m3fn)
    else:
        q = jnp.clip(jnp.round(x32 / scale), -127, 127).astype(jnp.int8)
    own = (q.astype(jnp.float32) * scale).astype(x.dtype)
    partner = (lax.ppermute(q, ax, perm=perm).astype(jnp.float32)
               * scale).astype(x.dtype)
    return own, partner


def _adasum_combine(a: jax.Array, b: jax.Array,
                    scalar_axes=()) -> jax.Array:
    """:func:`adasum_pair` with the fp32 dot/norm scalars additionally
    psummed over ``scalar_axes`` — the inner topology levels the fused
    bucket is already scattered across.  Each inner rank holds a
    different segment of the bucket, so the local partial dots only
    become the whole-bucket ⟨a,b⟩/‖a‖²/‖b‖² after the (cheap, scalar,
    intra-slice) reduction; every rank then applies the SAME
    coefficients and the damping is consistent across the bucket's
    segments."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.vdot(af, bf)
    anormsq = jnp.vdot(af, af)
    bnormsq = jnp.vdot(bf, bf)
    if scalar_axes:
        dot = lax.psum(dot, scalar_axes)
        anormsq = lax.psum(anormsq, scalar_axes)
        bnormsq = lax.psum(bnormsq, scalar_axes)
    acoeff = jnp.where(anormsq >= 1e-30,
                       1.0 - dot / (2.0 * anormsq + 1e-30), 1.0)
    bcoeff = jnp.where(bnormsq >= 1e-30,
                       1.0 - dot / (2.0 * bnormsq + 1e-30), 1.0)
    return (acoeff * af + bcoeff * bf).astype(a.dtype)


def _adasum_psum_scatter(block: jax.Array, ax, n: int,
                         bits: Optional[int] = None,
                         wire_dtype: Optional[str] = None,
                         scalar_axes=()) -> jax.Array:
    """Recursive-doubling AdaSum reduce-scatter over one (outermost)
    topology level — the operator analogue of
    ``lax.psum_scatter(tiled=True)``, with :func:`adasum_pair` as the
    combine.  log2(n) XOR-partner ``ppermute`` rounds exchange the full
    surviving block; the dot/norms are whole-bucket per fused
    (bucket, dtype) group — the local partials over this rank's
    surviving segment are psummed over ``scalar_axes`` (the inner
    levels, :func:`_adasum_combine`), so every rank applies identical
    coefficients even though the inner scatter made segment ownership
    rank-dependent.  Every rank then slices its own tiled 1/n shard, so
    ownership matches :func:`tree_index_axes` and :func:`tree_allgather`
    reassembles unchanged.

    ``bits`` runs each round's wire through the shared-scale codec
    (:func:`_quantized_pair_exchange`) — the codec quantizes the wire,
    the operator combines the payload.  Non-power-of-two levels (and
    degenerate axis-tuple levels) gather once and run the identical
    binary tree replicated on every rank, like ``ops/adasum.py``'s
    fallback.  An extent-1 level is the identity scatter.
    """
    if n == 1:
        return lax.psum_scatter(block, ax, tiled=True)
    _count_adasum_exchange(ax)
    shard = block.shape[0] // n
    x = block
    if isinstance(ax, str) and _is_pow2(n):
        for r in range(n.bit_length() - 1):
            dist = 1 << r
            perm = [(i, i ^ dist) for i in range(n)]
            if bits is not None:
                own, partner = _quantized_pair_exchange(
                    x, ax, perm, wire_dtype)
                x = _adasum_combine(own, partner, scalar_axes)
            else:
                x = _adasum_combine(x, lax.ppermute(x, ax, perm=perm),
                                    scalar_axes)
    else:
        stacked = allgather(x, ax, tiled=False).reshape((n,) + x.shape)
        vals = [stacked[i] for i in range(n)]
        while len(vals) > 1:
            nxt = [_adasum_combine(vals[i], vals[i + 1], scalar_axes)
                   for i in range(0, len(vals) - 1, 2)]
            if len(vals) % 2:
                nxt.append(vals[-1])
            vals = nxt
        x = vals[0]
    return lax.dynamic_slice(x, (axis_index(ax) * shard,), (shard,))


def tree_reducescatter(xs: Sequence[jax.Array],
                       levels: Sequence[ExchangeLevel],
                       op: ReduceOp = Sum,
                       prescale_factor: Optional[float] = None,
                       postscale_factor: Optional[float] = None,
                       bucket_bytes: Optional[int] = None,
                       spec: Optional[FusionSpec] = None,
                       fused_tail: bool = False,
                       residuals: Optional[Dict[str, jax.Array]] = None,
                       reduction: str = "sum"):
    """N-level topology-aware reduce-scatter: the reduce phase of the
    tree exchange, composed per level from the resolved topology
    (``runtime/topology.resolve_topology``).  Phase ℓ reduce-scatters
    the block surviving phases 0..ℓ-1 over level ℓ's axis, so level
    ℓ's fabric carries only ``(nℓ−1)/nℓ · B/∏inner`` bytes — the
    hierarchical shrink that makes the slow hops cheap, now at any
    depth.  A 1-level tree is the flat exchange, a 2-level tree is
    exactly :func:`hierarchical_reducescatter` (which delegates here);
    the parity pins in ``tests/test_hierarchy_smoke.py`` and
    ``tests/test_collectives.py`` hold the degeneracies.

    Per-level codec: each :class:`ExchangeLevel` with
    ``quantized_bits`` runs its hop through the shared-scale codec.
    The INNERMOST level's codec gets per-leaf segment scales (its
    input buffer is still whole, so segment boundaries are static) and
    honors ``residuals`` (error feedback, changing the return to
    ``(shards, spec, new_residuals)``); outer levels share one scale
    per block — the inner scatter makes segment boundaries
    rank-dependent, exactly the two-level DCN-hop constraint.
    ``fused_tail`` splits the LAST group's innermost hop into
    :data:`FUSED_TAIL_TILES` sub-collectives (codec wins when both are
    requested, matching :func:`grouped_reducescatter`'s branch order).

    ``reduction="adasum"`` swaps the OUTERMOST level's combine for the
    AdaSum operator (:func:`_adasum_psum_scatter`): plain sum/RS within
    the inner levels where replicas barely diverge, adaptive summation
    on the slow outer hop where they diverge most.  The operator is
    orthogonal to hierarchy and codec — inner-level RS, per-level wire
    codecs, and error-feedback residuals stack unchanged (the codec
    quantizes the wire; the operator combines the payload).  A 1-level
    tree (single-slice world: no outer hop) and an extent-1 outermost
    level degenerate to the bit-identical plain-sum path.  With
    ``op=Average`` the inner levels deliver the inner-replica mean
    (1/inner scale folded in before the outer round) and the final
    1/world divide is skipped — adasum is itself the average-like
    cross-replica combine.

    Ownership is row-major over :func:`tree_index_axes`; reassemble
    with :func:`tree_allgather`.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("tree_reducescatter supports op=Sum/Average")
    if reduction not in REDUCTIONS:
        raise ValueError(
            f"tree_reducescatter reduction must be one of {REDUCTIONS}, "
            f"got {reduction!r}")
    levels = tuple(levels)
    if not levels:
        raise ValueError("tree_reducescatter needs >= 1 level")
    if residuals is not None and levels[0].quantized_bits is None:
        raise ValueError(
            "residuals carry the innermost hop's codec error "
            "feedback; give levels[0] quantized_bits to enable it")
    sizes = [int(axis_size(lv.axis)) for lv in levels]
    world = 1
    for n in sizes:
        world *= n
    if spec is None:
        spec = make_fusion_spec(xs, world, bucket_bytes)
    elif spec.world != world:
        raise ValueError(
            f"spec was planned for world {spec.world}, the "
            f"{len(levels)}-level tree has {world}")
    # adasum rides the outermost level only, and only when there IS an
    # outer hop to ride: single-level trees and extent-1 outer levels
    # take the plain-sum path bit-identically
    adasum_outer = (reduction == "adasum" and len(levels) >= 2
                    and sizes[-1] > 1)
    adasum_scalar_axes: tuple = ()
    if adasum_outer:
        # the inner levels the bucket is scattered across at the outer
        # hop — the dot/norm partials reduce over these so every rank
        # applies whole-bucket coefficients (_adasum_combine)
        inner_axes = []
        for lv in levels[:-1]:
            if isinstance(lv.axis, str):
                inner_axes.append(lv.axis)
            else:
                inner_axes.extend(lv.axis)
        adasum_scalar_axes = tuple(inner_axes)
    shards: Dict[str, jax.Array] = {}
    new_residuals: Dict[str, jax.Array] = \
        dict(residuals) if residuals is not None else {}
    for gi, g in enumerate(spec.groups):
        block = _group_flat(g, xs, prescale_factor)
        floating = jnp.issubdtype(block.dtype, jnp.floating)
        adasum_done = False
        if op == ReduceOp.AVERAGE and not floating:
            raise ValueError(
                f"op=Average requires floating dtypes, got {g.dtype}")
        for li, lv in enumerate(levels):
            ax = lv.axis if isinstance(lv.axis, str) else tuple(lv.axis)
            bits = lv.quantized_bits
            if li == 0 and bits is not None and floating:
                # innermost hop: whole buffer, static per-leaf segment
                # boundaries — pad rides the last segment (zeros never
                # raise its absmax); EF when the caller carries state
                segs = list(g.sizes)
                segs[-1] += g.padded - sum(g.sizes)
                if residuals is not None and g.key in residuals:
                    block, new_residuals[g.key] = \
                        ef_quantized_reducescatter(
                            block, axis=ax, op=ReduceOp.SUM,
                            residual=residuals[g.key], bits=bits,
                            segments=tuple(segs))
                else:
                    block = quantized_reducescatter(
                        block, axis=ax, op=ReduceOp.SUM, bits=bits,
                        segments=tuple(segs))
            elif li == 0 and fused_tail and gi == len(spec.groups) - 1:
                block = _tiled_psum_scatter(block, ax, sizes[0])
            elif adasum_outer and li == len(levels) - 1 and floating:
                # outermost hop: AdaSum adaptive combine; Average means
                # the inner levels must deliver the inner-replica mean
                # (fold the 1/inner scale in now) and the final 1/world
                # divide is skipped — adasum IS the cross-replica
                # average-like operator
                if op == ReduceOp.AVERAGE:
                    block = _scale(block, float(sizes[li]) / world)
                block = _adasum_psum_scatter(
                    block, ax, sizes[li], bits=bits,
                    scalar_axes=adasum_scalar_axes)
                adasum_done = True
            elif bits is not None and floating:
                # outer hop: the surviving block, one shared scale —
                # segment boundaries are rank-dependent after the
                # inner scatter, so per-leaf scales cannot ride here
                block = quantized_reducescatter(
                    block, axis=ax, op=ReduceOp.SUM, bits=bits)
            else:
                block = lax.psum_scatter(block, ax, tiled=True)
        if op == ReduceOp.AVERAGE and not adasum_done:
            block = _scale(block, 1.0 / world)
        shards[g.key] = _scale(block, postscale_factor)
    if residuals is not None:
        return shards, spec, new_residuals
    return shards, spec


def tree_allgather(shards: Dict[str, jax.Array], spec: FusionSpec,
                   levels: Sequence[ExchangeLevel]) -> list:
    """Reassemble the shards of :func:`tree_reducescatter` — the
    gather phase of the tree exchange, mirrored outermost-first: each
    level's all-gather runs while the buffers are still shrunk by
    every level inside it, so every fabric moves the minimum possible
    bytes (the N-level form of :func:`hierarchical_allgather`).
    Gathering over :func:`tree_index_axes` makes the concatenation
    order row-major over exactly the scatter's ownership
    linearization, so this is its precise inverse."""
    return grouped_allgather(shards, spec, axis=tree_index_axes(levels))


def hierarchical_reducescatter(xs: Sequence[jax.Array],
                               op: ReduceOp = Sum,
                               outer_axis: str = AXIS_DCN,
                               inner_axis: str = AXIS_ICI,
                               prescale_factor: Optional[float] = None,
                               postscale_factor: Optional[float] = None,
                               quantized_bits: Optional[int] = None,
                               bucket_bytes: Optional[int] = None,
                               spec: Optional[FusionSpec] = None,
                               fused_tail: bool = False,
                               quantize_inner: bool = False,
                               inner_residuals: Optional[
                                   Dict[str, jax.Array]] = None,
                               reduction: str = "sum"):
    """Topology-aware two-level reduce-scatter — the reduce phase of the
    hierarchical exchange (reference ``NCCLHierarchicalAllreduce``,
    ``nccl_operations.cc:191-341``: NCCL inside the node, MPI across).

    Phase 1 reduce-scatters each fused group buffer over ``inner_axis``
    (chips within an ICI slice: the cheap torus hop carries the full
    ``(n_ici-1)/n_ici·B``).  Phase 2 reduce-scatters the surviving
    ``1/n_ici`` partial-sum block over ``outer_axis`` — the slow DCN hop
    therefore carries only ``(n_dcn-1)/n_dcn·B/n_ici`` bytes, which is
    the whole point of splitting the levels.  ``quantized_bits=8`` puts
    the int8 shared-scale codec of :func:`quantized_reducescatter` on
    the DCN phase ONLY: wire compression where the fabric is slow, full
    precision where it is already fast (EQuARX's topology-scoped
    compression argument, arXiv:2506.17615).  The codec scale is shared
    per (bucket, dtype, inner-shard) block — per-leaf segment scales
    cannot ride this hop because the inner scatter makes segment
    boundaries rank-dependent (and XLA shapes must be static).

    Returns ``(shards, spec)`` exactly like
    :func:`grouped_reducescatter`, with the one twist that shard
    ownership is linearized row-major over ``(inner, outer)`` — see
    :func:`exchange_index_axes`.  Reassemble with
    :func:`hierarchical_allgather` (cross-slice gather first, then
    intra-slice — each level's traffic stays on its own fabric).

    ``quantize_inner=True`` (requires ``quantized_bits``) additionally
    puts the codec on the ICI phase — double-compressed wire, for
    bandwidth-bound multi-slice runs.  Pass ``inner_residuals``
    (``{group key: (padded,) fp32}``) to run that hop through
    :func:`ef_quantized_reducescatter` so the extra rounding is
    error-fed-back instead of biasing the trajectory; the return then
    becomes ``(shards, spec, new_inner_residuals)``.  Per-leaf segment
    scales *do* ride the inner hop (the input buffer is still whole,
    unlike the DCN phase), so small leaves keep their own codec step.

    ``reduction="adasum"`` puts the AdaSum combine on the DCN phase
    (plain RS stays on ICI) — see :func:`tree_reducescatter`; a size-1
    ``outer_axis`` degenerates it to the bit-identical plain sum.

    Degenerate axes (size-1 dcn on a single slice, or size-1 ici) fall
    through cleanly: a ``psum_scatter`` over a 1-extent axis is the
    local value, so the two-level form equals the flat one.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError("hierarchical_reducescatter supports "
                         "op=Sum/Average")
    if quantize_inner and quantized_bits is None:
        raise ValueError(
            "quantize_inner puts the codec on the ICI phase; pass "
            "quantized_bits=8 to select it")
    if inner_residuals is not None and not quantize_inner:
        raise ValueError(
            "inner_residuals carry the ICI codec's error feedback; "
            "pass quantize_inner=True to enable that hop")
    n_inner = int(lax.axis_size(inner_axis))
    n_outer = int(lax.axis_size(outer_axis))
    world = n_inner * n_outer
    if spec is None:
        spec = make_fusion_spec(xs, world, bucket_bytes)
    elif spec.world != world:
        raise ValueError(
            f"spec was planned for world {spec.world}, mesh "
            f"({outer_axis},{inner_axis}) has {world}")
    # the two-level exchange is the 2-level degenerate tree: ICI is the
    # innermost level (per-leaf segment codec iff quantize_inner, the
    # fused tail), DCN the outer (shared-scale codec iff quantized_bits)
    levels = (ExchangeLevel(inner_axis,
                            quantized_bits if quantize_inner else None),
              ExchangeLevel(outer_axis, quantized_bits))
    return tree_reducescatter(xs, levels, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor,
                              spec=spec, fused_tail=fused_tail,
                              residuals=inner_residuals,
                              reduction=reduction)


def hierarchical_allgather(shards: Dict[str, jax.Array], spec: FusionSpec,
                           outer_axis: str = AXIS_DCN,
                           inner_axis: str = AXIS_ICI) -> list:
    """Reassemble the shards of :func:`hierarchical_reducescatter` —
    the gather phase of the two-level exchange, mirrored: all-gather
    across ``outer_axis`` first while the buffers are still 1/world
    sized (the DCN hop moves the minimum possible bytes), then across
    ``inner_axis`` on the fast fabric.  Gathering over the
    ``(inner, outer)`` tuple makes the concatenation order row-major
    over exactly the ownership linearization of the scatter (see
    :func:`exchange_index_axes`), so this is its precise inverse."""
    return tree_allgather(shards, spec,
                          (ExchangeLevel(inner_axis),
                           ExchangeLevel(outer_axis)))


def grouped_allgather(shards: Dict[str, jax.Array], spec: FusionSpec,
                      axis: AxisSpec = GLOBAL_AXES) -> list:
    """Reassemble per-rank group shards into full tensors — the second
    half of the sharded exchange.  All-gathers each group buffer
    (innermost mesh axis first, so concatenation order matches
    :func:`axis_index`'s row-major linearization), strips the padding,
    and splits back into the original leaf order.  The exact inverse
    of :func:`grouped_reducescatter`'s packing."""
    out: list = [None] * spec.num_leaves
    for g in spec.groups:
        flat = allgather(shards[g.key], axis=axis, tiled=True)
        offset = 0
        for i, n, shape in zip(g.indices, g.sizes, g.shapes):
            out[i] = flat[offset:offset + n].reshape(shape)
            offset += n
    return out


def sparse_allreduce(values: jax.Array, indices: jax.Array,
                     dense_rows: int, axis: AxisSpec = GLOBAL_AXES,
                     op: ReduceOp = Average) -> jax.Array:
    """Sparse (row-indexed) gradient reduction — the reference's
    ``IndexedSlices`` path (``tensorflow/__init__.py:100-110``): sparse
    grads become allgather(values) + allgather(indices) instead of a
    dense allreduce.  Static-shape TPU form: gather both, scatter-add
    into the dense result.  Returns the dense ``(dense_rows, ...)``
    reduced gradient (the ``sparse_as_dense`` output shape).
    """
    world = axis_size(axis)
    all_vals = allgather(values, axis=axis, tiled=False)
    all_idx = allgather(indices, axis=axis, tiled=False)
    all_vals = all_vals.reshape((-1,) + values.shape)
    all_idx = all_idx.reshape((-1,) + indices.shape)
    dense = jnp.zeros((dense_rows,) + values.shape[1:],
                      jnp.promote_types(values.dtype, jnp.float32))
    for s in range(world):
        dense = dense.at[all_idx[s]].add(all_vals[s].astype(dense.dtype))
    if op == ReduceOp.AVERAGE:
        dense = dense / world
    elif op != ReduceOp.SUM:
        raise ValueError("sparse_allreduce supports Sum/Average")
    return dense.astype(values.dtype)


def allgather(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
              tiled: bool = True) -> jax.Array:
    """Allgather along the first tensor dimension (reference
    ``EnqueueTensorAllgather``, ``operations.cc:903``; same-shape case).

    With ``tiled=True`` the result concatenates shards along dim 0 —
    Horovod's layout.  Variable first-dim gathers (``MPIAllgather`` recvcount
    machinery, ``mpi_operations.cc:96``) are handled by
    :func:`allgather_v`.
    """
    if isinstance(axis, str):
        return lax.all_gather(x, axis, tiled=tiled)
    y = x
    # gather innermost axis first so the final ordering is row-major over
    # the axis tuple, matching axis_index()
    for a in reversed(tuple(axis)):
        y = lax.all_gather(y, a, tiled=tiled)
    return y


def allgather_v(x: jax.Array, valid_count: jax.Array,
                max_count: int, axis: AxisSpec = GLOBAL_AXES):
    """Variable-first-dim allgather.

    Each shard contributes ``valid_count`` ≤ ``max_count`` rows of ``x``
    (padded to ``max_count``).  Returns ``(gathered, counts)`` where
    ``gathered`` is ``(world, max_count, ...)`` and ``counts`` the per-rank
    valid sizes — the displacement bookkeeping of ``AllgatherOp``
    (``collective_operations.h:127-176``) in static-shape form.  Callers
    compact on host or mask in-graph; XLA needs the static bound.
    """
    pad_shape = (max_count,) + x.shape[1:]
    padded = jnp.zeros(pad_shape, x.dtype).at[:x.shape[0]].set(x) \
        if x.shape[0] != max_count else x
    gathered = allgather(padded, axis=axis, tiled=False)
    # non-tiled gather over an axis tuple stacks one leading dim per axis
    # (row-major by construction); flatten them into the world dim
    gathered = gathered.reshape((-1,) + pad_shape)
    counts = allgather(jnp.asarray(valid_count, jnp.int32)[None],
                       axis=axis, tiled=True)
    return gathered, counts


def allgather_v_mask(counts: jax.Array, max_count: int) -> jax.Array:
    """``(world, max_count)`` bool mask of the valid rows in an
    :func:`allgather_v` result — the in-graph masking idiom, provided
    once so call sites don't re-derive it::

        gathered, counts = allgather_v(x, n, max_count)
        mask = allgather_v_mask(counts, max_count)
        total = jnp.sum(jnp.where(mask[..., None], gathered, 0), (0, 1))
    """
    return jnp.arange(max_count)[None, :] < counts[:, None]


def allgather_v_compact(gathered, counts) -> "np.ndarray":
    """Host-side compaction of an :func:`allgather_v` result: drop the
    padding and concatenate every shard's valid rows along dim 0 —
    Horovod's variable allgather output layout (``MPI_Allgatherv``
    displacement packing, ``mpi_operations.cc:96``).  Call *outside*
    jit: the output's first dim is data-dependent.
    """
    g = np.asarray(gathered)
    c = np.asarray(counts).reshape(-1)
    return np.concatenate([g[i, :int(c[i])] for i in range(len(c))],
                          axis=0)


def broadcast(x: jax.Array, root_rank: int = 0,
              axis: AxisSpec = GLOBAL_AXES) -> jax.Array:
    """Broadcast the value held by ``root_rank`` (linearized over ``axis``)
    to every shard (reference ``EnqueueTensorBroadcast``,
    ``operations.cc:928``).

    Implemented as select+psum: contributions from non-root shards are
    zeroed, so the reduction *is* the broadcast.  XLA pattern-matches this
    to a collective-broadcast where profitable.
    """
    me = axis_index(axis)
    contrib = jnp.where(me == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis)


def reducescatter(x: jax.Array, op: ReduceOp = Sum,
                  axis: str = AXIS_ICI,
                  scatter_dimension: int = 0) -> jax.Array:
    """Reduce-scatter (the building block of the reference's hierarchical
    allreduce, ``nccl_operations.cc:298``): each shard gets one reduced
    1/world slice along ``scatter_dimension``."""
    y = lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                         tiled=True)
    if op == ReduceOp.AVERAGE:
        y = _scale(y, 1.0 / axis_size(axis))
    return y


def alltoall(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
             split_axis: int = 0, concat_axis: int = 0) -> jax.Array:
    """Equal-splits alltoall (reference ``EnqueueTensorAlltoall``,
    ``operations.cc:979``; ``NCCLAlltoall`` P2P impl
    ``nccl_operations.cc:569``).  The variable-``splits`` form of the
    reference maps to :func:`alltoall_v`.

    Over an axis *tuple* (the reference's GLOBAL communicator over the
    (dcn, ici) mesh) the exchange decomposes into one per-axis
    ``all_to_all`` per mesh level: with destination ranks linearized
    row-major as ``(s, t)``, exchanging the ``t``-index over ici and the
    ``s``-index over dcn commute and compose to the global permutation
    ``out[s, t] = in_{(s,t)}[p, q]`` — each level's traffic rides that
    level's interconnect (ICI stays on ICI; only the dcn-level exchange
    crosses DCN), which is strictly better than flattening to one big
    ring the way a rank-linearized NCCL alltoall would.
    """
    if isinstance(axis, (tuple, list)) and len(axis) == 1:
        axis = axis[0]
    if isinstance(axis, str):
        return lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)
    axes = tuple(axis)
    sizes = [lax.axis_size(a) for a in axes]
    n = axis_size(axes)
    if x.shape[split_axis] % n:
        raise ValueError(
            f"alltoall split dim {x.shape[split_axis]} not divisible by "
            f"world size {n}")
    chunk = x.shape[split_axis] // n
    lead, tail = x.shape[:split_axis], x.shape[split_axis + 1:]
    # expose one dim per mesh level (row-major, matching axis_index), then
    # exchange each level's index along its own axis
    y = x.reshape(lead + tuple(sizes) + (chunk,) + tail)
    for k, a in enumerate(axes):
        y = lax.all_to_all(y, a, split_axis=split_axis + k,
                           concat_axis=split_axis + k, tiled=True)
    if concat_axis == split_axis:
        return y.reshape(lead + (n * chunk,) + tail)
    # chunks received from the n peers concatenate along a different dim:
    # isolate the peer dim, move it to just before the concat target, merge
    y = y.reshape(lead + (n, chunk) + tail)
    y = jnp.moveaxis(y, split_axis, concat_axis)
    out_shape = list(x.shape)
    out_shape[split_axis] = chunk
    out_shape[concat_axis] *= n
    return y.reshape(out_shape)


def alltoall_v(x: jax.Array, send_counts: jax.Array, max_count: int,
               axis: AxisSpec = AXIS_ICI):
    """Variable-splits alltoall on top of the equal-tile primitive.

    Reference semantics (``AlltoallOp::PrepareOutputAndParams``,
    ``collective_operations.h:206-256``): rank r sends ``send_counts[d]``
    rows to each destination d.  Static-shape formulation: the caller packs
    rows destined to d into slot d of a ``(world, max_count, ...)`` buffer
    (d linearized row-major over an axis tuple, matching ``axis_index``);
    we alltoall the slots and return ``(received, recv_counts)`` — the
    recv-splits negotiation (``mpi_controller.cc:212``) becomes one tiny
    int alltoall.  Works over a single axis or the full (dcn, ici) tuple.
    """
    world = int(axis_size(axis))
    assert x.shape[0] == world and x.shape[1] == max_count, (
        "alltoall_v input must be (world, max_count, ...) slot-packed")
    received = alltoall(x, axis=axis)
    recv_counts = alltoall(jnp.asarray(send_counts, jnp.int32), axis=axis)
    return received, recv_counts


def barrier(axis: AxisSpec = GLOBAL_AXES) -> jax.Array:
    """Cross-shard barrier (reference ``MPIController::Barrier``,
    ``mpi_controller.cc:225``): a scalar psum every shard must reach."""
    return lax.psum(jnp.int32(1), axis)


def _bits(x: jax.Array, nbits: int) -> jax.Array:
    """Unpack an int array into a (..., nbits) {0,1} array.  Arithmetic
    right-shift + ``& 1`` reads every bit position incl. the sign bit."""
    shifts = jnp.arange(nbits, dtype=x.dtype)
    return (x[..., None] >> shifts) & 1


def _pack(bits: jax.Array, dtype) -> jax.Array:
    """Repack (..., nbits) {0,1} bits into ``dtype`` words.  Accumulates in
    the unsigned counterpart so the top (sign) bit packs without overflow,
    then reinterprets into the target dtype."""
    nbits = bits.shape[-1]
    acc = jnp.uint64 if nbits > 32 else jnp.uint32
    shifts = jnp.arange(nbits, dtype=acc)
    packed = jnp.sum(bits.astype(acc) << shifts, axis=-1)
    return lax.convert_element_type(packed, dtype)


def bitwise_and(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
                nbits: Optional[int] = None) -> jax.Array:
    """Cross-shard bitwise AND of int bitvectors (reference
    ``CrossRankBitwiseAnd``, ``mpi_controller.cc:88`` — the response-cache
    agreement primitive).  A bit survives iff every shard set it, i.e. its
    psum equals the world size — bit-decompose, psum, repack.  All bits of
    the input dtype participate by default (reference operates on full
    64-bit words); pass ``nbits`` to restrict to the low bits."""
    if x.dtype == jnp.bool_:
        return lax.psum(x.astype(jnp.int32), axis) == axis_size(axis)
    nbits = nbits or jnp.iinfo(x.dtype).bits
    n = axis_size(axis)
    counts = lax.psum(_bits(x, nbits).astype(jnp.int32), axis)
    return _pack((counts == n).astype(jnp.int32), x.dtype)


def bitwise_or(x: jax.Array, axis: AxisSpec = GLOBAL_AXES,
               nbits: Optional[int] = None) -> jax.Array:
    """Cross-shard bitwise OR (reference ``CrossRankBitwiseOr``,
    ``mpi_controller.cc:97``): a bit is set iff any shard set it."""
    if x.dtype == jnp.bool_:
        return lax.psum(x.astype(jnp.int32), axis) > 0
    nbits = nbits or jnp.iinfo(x.dtype).bits
    counts = lax.psum(_bits(x, nbits).astype(jnp.int32), axis)
    return _pack((counts > 0).astype(jnp.int32), x.dtype)
