"""Pallas TPU kernels for the hot ops.

Kernels, mirroring where the reference spends native effort:

* :func:`fused_scale` — the fusion-buffer scale kernel (reference
  ``ops/cuda/cuda_kernels.cu`` ``scale_buffer_k``/``ScaleBufferCudaImpl``):
  one pass over the fused gradient buffer applying the pre/postscale
  factor with an optional wire-dtype cast, saturating VPU lanes instead
  of paying two HBM round-trips for scale-then-cast.
* :func:`flash_attention` — blocked causal attention (the MXU hot loop
  of :mod:`~horovod_tpu.models.transformer`): Q blocks stream against
  K/V blocks held in VMEM with the online-softmax recurrence, never
  materializing the (T, T) score matrix in HBM.
* :func:`matmul_reducescatter` / :func:`allgather_matmul` — tile-fused
  matmul ⊗ collective ops (arXiv:2305.06942, docs/fused_kernels.md):
  the matmul at a tensor-parallel boundary decomposes into per-rank
  tiles streamed around a ``ppermute`` ring, so the exchange of tile
  *k* overlaps the MXU compute of tile *k+1* inside one op and the
  full-width serial collective at the boundary disappears from the
  schedule.  Each tile's dot runs the blocked Pallas matmul kernel on
  TPU (:func:`pallas_matmul`).

All degrade gracefully: off-TPU (or for shapes that don't meet the
tiling contract) they fall back to the identical jnp formulation, and
tests run the kernels in interpreter mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# fused scale (+ cast)
# ---------------------------------------------------------------------------

def _scale_kernel(x_ref, o_ref, *, factor):
    o_ref[:] = (x_ref[:].astype(jnp.float32) * factor).astype(o_ref.dtype)


def fused_scale(x: jax.Array, factor: float,
                out_dtype: Optional[jnp.dtype] = None,
                interpret: bool = False) -> jax.Array:
    """``x * factor`` cast to ``out_dtype`` in one fused pass (reference
    ``ScaleBufferCudaImpl``, ``cuda_kernels.cu:77``; fp16 half2
    vectorization there ≙ VPU lanes here)."""
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    if not (interpret or _on_tpu()):
        return (x.astype(jnp.float32) * factor).astype(out_dtype)
    flat = x.reshape(-1)
    # pad to a (8, 128) fp32 tile multiple
    tile = 8 * 128
    n = flat.size
    pad = (-n) % tile
    if pad:
        flat = jnp.pad(flat, (0, pad))
    arr = flat.reshape(-1, 128)
    out = pl.pallas_call(
        functools.partial(_scale_kernel, factor=factor),
        out_shape=jax.ShapeDtypeStruct(arr.shape, out_dtype),
        interpret=interpret,
    )(arr)
    return out.reshape(-1)[:n].reshape(x.shape)


# ---------------------------------------------------------------------------
# flash attention (forward + blockwise backward kernels)
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int,
                      causal: bool, scale: float, positions: bool = False):
    # blocks: q (1, BQ, D); k/v (1, T, D); o (1, BQ, D).  With
    # ``positions`` two extra int32 inputs ride along in the lse layout
    # (qpos (1, 8, BQ), kpos (1, 8, T)): GLOBAL sequence positions, so
    # the causal mask stays correct when this kernel consumes a ring
    # shard whose rows are not local-index-contiguous (the sp ring's
    # zigzag layout, :func:`ring_flash_attention`).
    # inputs stay in their native dtype (bf16): the MXU runs bf16 x bf16
    # at full rate with fp32 accumulation via preferred_element_type —
    # casting to fp32 first would forfeit the systolic-array rate
    if positions:
        qpos_ref, kpos_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    q = q_ref[0]                                      # (BQ, D)
    block_q, d = q.shape
    t = k_ref.shape[1]
    qi = pl.program_id(1)
    if positions:
        q_pos = qpos_ref[0, 0][:, None]               # (BQ, 1) global
    else:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            if positions:
                k_pos = kpos_ref[0, 0, pl.ds(kb * block_k,
                                             block_k)][None, :]
            else:
                k_pos = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        # p in the value dtype for the MXU; the o accumulator stays fp32
        o_new = o * corr[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return o_new, m_new, l_new

    num_k = t // block_k
    if causal and not positions:
        # skip blocks strictly above the diagonal (their mask is
        # all-false); ceil-divide — flooring would drop the partially
        # live diagonal block whenever block_q is not a block_k multiple.
        # With explicit positions the layout is arbitrary (zigzag), so
        # no diagonal exists to skip — every block runs, masked per row.
        num_k_live = ((qi + 1) * block_q + block_k - 1) // block_k
        num_k = jnp.minimum(num_k, jnp.maximum(num_k_live, 1))
    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (o / l_safe[:, None]).astype(o_ref.dtype)
    # per-row logsumexp: what the backward needs to rebuild p = exp(s-lse)
    # without re-running the online-softmax recurrence.  Stored with an
    # 8-sublane replication axis — Mosaic requires the last two block
    # dims be (8k, 128k) or full-size (jax's own flash kernel pads its
    # l/m residuals the same way, with 128 lanes)
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l_safe))[None, :],
                                  lse_ref.shape[1:])


def _bh_layout(q, k, v):
    b, t, h, d = q.shape

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    return to_bh(q), to_bh(k), to_bh(v)


def _pos_layout(pos):
    """A (t,) position vector in the lse residual layout (1, 8, t):
    int32 replicated over the 8-sublane axis (Mosaic tiling contract —
    same stance as the lse/delta blocks)."""
    t = pos.shape[0]
    return jnp.broadcast_to(pos.astype(jnp.int32)[None, None, :],
                            (1, 8, t))


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               qpos=None, kpos=None):
    b, t, h, d = q.shape
    qb, kb, vb = _bh_layout(q, k, v)
    grid = (b * h, t // block_q)
    positions = qpos is not None
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
    ]
    args = [qb, kb, vb]
    if positions:
        in_specs += [
            pl.BlockSpec((1, 8, block_q), lambda bh, qi: (0, 0, qi)),
            pl.BlockSpec((1, 8, t), lambda bh, qi: (0, 0, 0)),
        ]
        args += [_pos_layout(qpos), _pos_layout(kpos)]
    out, lse = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, block_k=block_k,
                          causal=causal, scale=scale, positions=positions),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, 8, block_q), lambda bh, qi: (bh, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, t), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_k: int, causal: bool,
                         scale: float, positions: bool = False):
    """dQ for one Q block: stream K/V blocks, rebuild p from the saved
    logsumexp, accumulate dq = Σ ds·K·scale (FlashAttention-2 backward,
    dS = P ∘ (dP − delta) with delta = rowsum(dO ∘ O)).  With
    ``positions``, qpos/kpos inputs carry GLOBAL sequence positions and
    the causal mask compares those (the sp ring's arbitrary layouts)."""
    if positions:
        qpos_ref, kpos_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    q = q_ref[0]                              # (BQ, D) native dtype
    do = do_ref[0]                            # (BQ, D)
    lse = lse_ref[0, 0]                       # (BQ,) (sublane 0)
    delta = delta_ref[0, 0]                   # (BQ,)
    block_q, d = q.shape
    t = k_ref.shape[1]
    qi = pl.program_id(1)
    if positions:
        q_pos = qpos_ref[0, 0][:, None]       # (BQ, 1) global
    else:
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            if positions:
                k_pos = kpos_ref[0, 0, pl.ds(kb * block_k,
                                             block_k)][None, :]
            else:
                k_pos = kb * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(k_blk.dtype)
        return dq + jnp.dot(ds, k_blk,
                            preferred_element_type=jnp.float32) * scale

    num_k = t // block_k
    if causal and not positions:
        # ceil-divide: see the forward kernel's diagonal-block note
        num_k_live = ((qi + 1) * block_q + block_k - 1) // block_k
        num_k = jnp.minimum(num_k, jnp.maximum(num_k_live, 1))
    dq = jax.lax.fori_loop(0, num_k, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, block_q: int, causal: bool,
                          scale: float, positions: bool = False):
    """dK/dV for one K block: stream Q/dO blocks; dV = Σ pᵀ·dO,
    dK = Σ dsᵀ·Q·scale.  Causal: Q blocks strictly above the diagonal
    contribute nothing and are skipped — except under ``positions``
    (global, possibly non-contiguous row positions), where no diagonal
    exists and every block runs with its per-row mask."""
    if positions:
        qpos_ref, kpos_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    k = k_ref[0]                              # (BK, D) native dtype
    v = v_ref[0]                              # (BK, D)
    block_k, d = k.shape
    t = q_ref.shape[1]
    ki = pl.program_id(1)
    if positions:
        k_pos = kpos_ref[0, 0][None, :]       # (1, BK) global
    else:
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)

    def body(qb, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse_blk = lse_ref[0, 0, pl.ds(qb * block_q, block_q)]
        delta_blk = delta_ref[0, 0, pl.ds(qb * block_q, block_q)]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            if positions:
                q_pos = qpos_ref[0, 0, pl.ds(qb * block_q,
                                             block_q)][:, None]
            else:
                q_pos = qb * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])
        if causal:
            p = jnp.where(mask, p, 0.0)
        dv = dv + jnp.dot(p.astype(do_blk.dtype).T, do_blk,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_blk[:, None])).astype(q_blk.dtype)
        dk = dk + jnp.dot(ds.T, q_blk,
                          preferred_element_type=jnp.float32) * scale
        return dk, dv

    start = 0
    if causal and not positions:
        # first Q block that reaches this K block's diagonal
        start = (ki * block_k) // block_q
    zeros = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, t // block_q, body, (zeros, zeros))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, scale, block_q, block_k,
               interpret, qpos=None, kpos=None, delta=None):
    """FlashAttention-2 blockwise backward.  ``lse``/``delta`` may be
    GLOBAL quantities (the sp ring: softmax over the whole ring's keys)
    — the FA2 decomposition is exact per K/V block given the global
    logsumexp, which is what lets :func:`ring_flash_attention` reuse
    these kernels per visiting block.  ``delta`` defaults to
    rowsum(dO ∘ O) of the given out/g; pass a precomputed ``(b·h, t)``
    row-sum to avoid recomputing it once per ring step."""
    b, t, h, d = q.shape
    qb, kb, vb = _bh_layout(q, k, v)
    do = g.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    positions = qpos is not None
    if delta is None:
        ob = out.transpose(0, 2, 1, 3).reshape(b * h, t, d)
        # delta = rowsum(dO ∘ O): tiny elementwise pass, XLA fuses it
        delta = (do.astype(jnp.float32) *
                 ob.astype(jnp.float32)).sum(-1)
    # replicated to the same 8-sublane layout as lse (tiling contract)
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, 8, t))

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, t, d), lambda bh, qi: (bh, 0, 0)),
        pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        pl.BlockSpec((1, 8, block_q), lambda bh, qi: (bh, 0, qi)),
        pl.BlockSpec((1, 8, block_q), lambda bh, qi: (bh, 0, qi)),
    ]
    dq_args = [qb, kb, vb, do, lse, delta]
    if positions:
        dq_in_specs += [
            pl.BlockSpec((1, 8, block_q), lambda bh, qi: (0, 0, qi)),
            pl.BlockSpec((1, 8, t), lambda bh, qi: (0, 0, 0)),
        ]
        dq_args += [_pos_layout(qpos), _pos_layout(kpos)]
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, positions=positions),
        grid=(b * h, t // block_q),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        interpret=interpret,
    )(*dq_args)

    dkv_in_specs = [
        pl.BlockSpec((1, t, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        pl.BlockSpec((1, t, d), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 8, t), lambda bh, ki: (bh, 0, 0)),
        pl.BlockSpec((1, 8, t), lambda bh, ki: (bh, 0, 0)),
    ]
    dkv_args = [qb, kb, vb, do, lse, delta]
    if positions:
        dkv_in_specs += [
            pl.BlockSpec((1, 8, t), lambda bh, ki: (0, 0, 0)),
            pl.BlockSpec((1, 8, block_k), lambda bh, ki: (0, 0, ki)),
        ]
        dkv_args += [_pos_layout(qpos), _pos_layout(kpos)]
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q,
                          causal=causal, scale=scale, positions=positions),
        grid=(b * h, t // block_k),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_args)

    def from_bh(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


def fit_flash_block(t: int, requested: int) -> Optional[int]:
    """Largest flash block ≤ ``requested`` that divides ``t`` — a seq
    len that is a multiple of 128 but not of the (large) default must
    shrink the block, not fall back to the dense O(T²) path.  Sequences
    shorter than one tile run as a single block (small-shape tests and
    probes); other non-128-multiples return ``None`` (the caller's
    dense/jnp fallback) — sub-tile blocks on real bf16 inputs are
    Mosaic-lowering risk.  Shared by :func:`flash_attention` and the
    :func:`ring_flash_attention` dispatch in
    :mod:`~horovod_tpu.parallel.ring_attention`."""
    if t <= 128:
        b = min(requested, t)
        if t % b == 0:
            return b
        # ragged small seq: a single whole-sequence block if it
        # tiles, else the dense fallback
        return t if t % 8 == 0 else None
    for cand in (requested, 512, 256, 128):
        if cand <= t and t % cand == 0:
            return cand
    return None


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False) -> jax.Array:
    """Blocked attention over ``(batch, seq, heads, head_dim)`` inputs.

    Falls back to the dense jnp formulation off-TPU or when ``seq`` is not
    divisible by the block sizes.  Differentiable end-to-end in Pallas:
    the forward saves per-row logsumexp and the backward runs the
    FlashAttention-2 blockwise kernels (dQ streaming K/V; dK/dV
    streaming Q/dO) — the (T, T) score matrix never exists in HBM in
    either direction.
    """
    from horovod_tpu.parallel.ring_attention import reference_attention

    b, t, h, d = q.shape
    scale = d ** -0.5 if scale is None else scale

    block_q = fit_flash_block(t, block_q)
    block_k = fit_flash_block(t, block_k)
    usable = (interpret or _on_tpu()) and \
        block_q is not None and block_k is not None
    if not usable:
        return reference_attention(q, k, v, causal=causal, scale=scale)

    @jax.custom_vjp
    def _attn(q, k, v):
        out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                            interpret)
        return out

    def _fwd(q, k, v):
        out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k,
                              interpret)
        return out, (q, k, v, out, lse)

    def _bwd(res, g):
        q, k, v, out, lse = res
        return _flash_bwd(q, k, v, out, lse, g, causal, scale,
                          block_q, block_k, interpret)

    _attn.defvjp(_fwd, _bwd)
    return _attn(q, k, v)


# ---------------------------------------------------------------------------
# fused bottleneck-segment backward (conv3x3 + inference-BN + relu)
# ---------------------------------------------------------------------------
#
# ResNet's measured gap (PERF_NOTES.md): the XLA backward spends ~35% of
# the step in VPU-bound BN dgamma/dbeta convert+reduce fusions that
# re-stream the gradient/activation tensors from HBM after the conv
# backward already read them.  This kernel computes the WHOLE backward
# of the block segment  b = relu(bn(conv3x3(a)))  (inference-mode BN —
# frozen running stats, the synthetic-bench training configuration) in
# one pass:
#
#   dz      = db * (b > 0)                  (relu)
#   dbeta  += sum(dz);  dgamma += sum(dz * yhat)      (BN param grads)
#   dy      = dz * gamma/sigma                        (BN input grad)
#   dW[tap] += a_shifted^T @ dy             (9 tap matmuls, MXU)
#   da      = sum_tap dy_shifted @ W[tap]^T (9 tap matmuls, MXU)
#
# so db/b/a cross HBM exactly once and the channel reductions ride the
# VMEM tiles the matmuls already hold.  The reference has no analogue —
# cuDNN owns its conv backward — this is the "fuse across the block
# boundary" lever the round-4 review left on the table.

def _cbr_bwd_kernel(db_ref, b_ref, ap_ref, w_ref, beta_ref, gamma_ref,
                    seff_ref, da_ref, dw_ref, dgamma_ref, dbeta_ref,
                    dypad_ref, *, hh: int, ww: int):
    """Grid is (batch_tiles,) with the 9-tap loop unrolled in the body.

    Accumulator layout constraint: Pallas TPU output windows are only
    defined across CONSECUTIVE same-index grid steps, so every
    accumulated output (dW, dgamma, dbeta) must keep a constant block
    index over the whole grid — a tap-in-the-grid variant (dW blocked
    per tap, revisited once per tile) silently accumulates into stale
    buffers on hardware.  The price of the unrolled body is Mosaic
    stack pressure (~48 B/tile element live), paid for with a smaller
    batch tile (see the caller's budget)."""
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dgamma_ref[...] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[...] = jnp.zeros_like(dbeta_ref)

    db = db_ref[...].astype(jnp.float32)          # (nb, H, W, C)
    b = b_ref[...].astype(jnp.float32)
    beta = beta_ref[0]                            # (C,)
    gamma = gamma_ref[0]
    seff = seff_ref[0]

    dz = jnp.where(b > 0, db, 0.0)
    dbeta_ref[...] += jnp.broadcast_to(
        dz.sum((0, 1, 2))[None, :], dbeta_ref.shape)
    # yhat = (z - beta)/gamma; on active lanes z == b, on inactive ones
    # dz == 0 annihilates the (wrong) yhat — no mask needed.  gamma==0
    # destroys the information needed to recover yhat from the relu
    # output at all (z is constant beta), so the safe divide pins that
    # channel's dgamma to 0 instead of NaN (docstring caveat in
    # fused_conv_bn_relu).
    gamma_safe = jnp.where(jnp.abs(gamma) < 1e-12, 1.0, gamma)
    dgamma_ref[...] += jnp.broadcast_to(
        (dz * ((b - beta) / gamma_safe)).sum((0, 1, 2))[None, :],
        dgamma_ref.shape)

    dy = (dz * seff).astype(db_ref.dtype)         # conv-output grad
    nb, h, w, c = dy.shape
    rows = nb * h * w
    dy2 = dy.reshape(rows, c)

    # dW[tap] += a_pad[:, kh:kh+H, kw:kw+W]^T @ dy   (contract rows)
    for kh in range(3):
        for kw in range(3):
            a_tap = ap_ref[:, kh:kh + hh, kw:kw + ww, :] \
                .reshape(rows, ap_ref.shape[-1])
            dw_ref[3 * kh + kw] += jax.lax.dot_general(
                a_tap, dy2, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    # da = sum_tap dy_pad[:, 2-kh : 2-kh+H, 2-kw : 2-kw+W] @ W[tap]^T
    dypad_ref[...] = jnp.zeros_like(dypad_ref)
    dypad_ref[:, 1:hh + 1, 1:ww + 1, :] = dy
    acc = None
    for kh in range(3):
        for kw in range(3):
            d_tap = dypad_ref[:, 2 - kh:2 - kh + hh,
                              2 - kw:2 - kw + ww, :].reshape(rows, c)
            part = jax.lax.dot_general(
                d_tap, w_ref[3 * kh + kw], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc = part if acc is None else acc + part
    da_ref[...] = acc.reshape(da_ref.shape).astype(da_ref.dtype)


def _cbr_bwd_reference(db, b, a, w, gamma, beta, scale_eff):
    """jnp oracle of the fused backward (also the off-TPU fallback):
    relu/BN grads by hand, conv grads through jax.vjp of the forward
    conv — exactly what XLA autodiff produces, unfused."""
    f32 = jnp.float32
    dz = jnp.where(b > 0, db.astype(f32), 0.0)
    dbeta = dz.sum((0, 1, 2))
    gamma_safe = jnp.where(jnp.abs(gamma) < 1e-12, 1.0, gamma)
    dgamma = (dz * ((b.astype(f32) - beta) / gamma_safe)).sum((0, 1, 2))
    dy = (dz * scale_eff).astype(a.dtype)
    dn = jax.lax.conv_dimension_numbers(a.shape, w.shape,
                                        ("NHWC", "HWIO", "NHWC"))

    def conv(a_, w_):
        return jax.lax.conv_general_dilated(
            a_, w_, (1, 1), "SAME", dimension_numbers=dn)

    _, vjp = jax.vjp(conv, a, w.astype(a.dtype))
    da, dw = vjp(dy)
    return da, dw.astype(f32), dgamma, dbeta


def fused_conv_bn_relu_bwd(db, b, a, w, gamma, beta, scale_eff,
                           interpret: bool = False):
    """Backward of ``relu(bn_inference(conv3x3_same(a, w)))``.

    Returns ``(da, dw, dgamma, dbeta)``.  One fused pass on TPU (see
    the kernel above); jnp fallback elsewhere or for shapes outside the
    tiling contract (stride-1 SAME 3x3, channels a lane multiple).
    """
    n, hh, ww, cin = a.shape
    c = w.shape[-1]
    # the dW accumulator (9*Cin*C fp32) lives in VMEM for the whole
    # grid; past 256x256 channels it plus the tiles exceeds the ~16 MB
    # scoped-vmem budget (measured: 512x512 OOMs at 19.3 MB), so wide
    # segments keep the XLA path — the dominant stages (PERF_NOTES
    # profile) are the 128/256-channel ones anyway
    dw_bytes = 9 * cin * c * 4
    usable = (interpret or _on_tpu()) and w.shape[:2] == (3, 3) and \
        c % 128 == 0 and cin % 128 == 0 and db.shape == b.shape and \
        db.shape[:3] == (n, hh, ww) and dw_bytes <= 2_400_000
    if not usable:
        return _cbr_bwd_reference(db, b, a, w, gamma, beta, scale_eff)

    from jax.experimental.pallas import tpu as pltpu

    # batch tile: keep dW + the per-tile working set within the 16 MB
    # scoped-vmem budget.  The unrolled 9-tap body keeps ~48 B of live
    # temporaries per tile element on the Mosaic stack (measured:
    # 21.3 MB at nb=8, 14x14x256); nb must divide N
    tile_budget = max(10e6 - dw_bytes, 1e6)
    target = max(1, int(tile_budget // (hh * ww * max(c, cin) * 48)))
    nb = 1
    while nb * 2 <= min(target, n) and n % (nb * 2) == 0:
        nb *= 2
    grid = (n // nb,)

    a_pad = jnp.pad(a, ((0, 0), (1, 1), (1, 1), (0, 0)))
    w9 = w.astype(jnp.float32).reshape(9, cin, c)
    rep = (8, c)
    gamma8 = jnp.broadcast_to(gamma.astype(jnp.float32)[None, :], rep)
    beta8 = jnp.broadcast_to(beta.astype(jnp.float32)[None, :], rep)
    seff8 = jnp.broadcast_to(scale_eff.astype(jnp.float32)[None, :], rep)

    da, dw, dgamma8, dbeta8 = pl.pallas_call(
        functools.partial(_cbr_bwd_kernel, hh=hh, ww=ww),
        grid=grid,
        in_specs=[
            pl.BlockSpec((nb, hh, ww, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((nb, hh, ww, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((nb, hh + 2, ww + 2, cin),
                         lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9, cin, c), lambda i: (0, 0, 0)),
            pl.BlockSpec(rep, lambda i: (0, 0)),
            pl.BlockSpec(rep, lambda i: (0, 0)),
            pl.BlockSpec(rep, lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((nb, hh, ww, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9, cin, c), lambda i: (0, 0, 0)),
            pl.BlockSpec(rep, lambda i: (0, 0)),
            pl.BlockSpec(rep, lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(a.shape, a.dtype),
            jax.ShapeDtypeStruct((9, cin, c), jnp.float32),
            jax.ShapeDtypeStruct(rep, jnp.float32),
            jax.ShapeDtypeStruct(rep, jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((nb, hh + 2, ww + 2, c), db.dtype),
        ],
        interpret=interpret,
    )(db, b, a_pad, w9, beta8, gamma8, seff8)
    return da, dw.reshape(w.shape), dgamma8[0], dbeta8[0]


def fused_conv_bn_relu(a, w, gamma, beta, mean, var,
                       eps: float = 1e-5, interpret: bool = False):
    """``relu(bn_inference(conv3x3_same(a, w)))`` with the one-pass
    fused backward above wired in via custom_vjp.  The forward stays
    plain XLA (its conv+affine+relu already fuse optimally); only the
    backward — where XLA re-streams tensors for the channel reductions
    — is replaced.  ``mean``/``var`` are frozen running stats and get
    zero gradients (they are buffers, not parameters).

    Caveat: dgamma is reconstructed from the relu output as
    ``sum(dz * (z - beta)/gamma)`` — only the relu output is saved, so
    a channel whose ``gamma`` reaches exactly 0 has no recoverable
    normalized activation and its dgamma is pinned to 0 (instead of
    NaN).  Autodiff of the unfused segment (which saves the conv
    output) stays exact there; don't enable the fused path if BN
    scales are expected to cross zero."""

    @jax.custom_vjp
    def _run(a, w, gamma, beta, mean, var):
        return _fwd(a, w, gamma, beta, mean, var)[0]

    def _fwd(a, w, gamma, beta, mean, var):
        dn = jax.lax.conv_dimension_numbers(a.shape, w.shape,
                                            ("NHWC", "HWIO", "NHWC"))
        y = jax.lax.conv_general_dilated(a, w.astype(a.dtype), (1, 1),
                                         "SAME", dimension_numbers=dn)
        scale_eff = (gamma / jnp.sqrt(var + eps)).astype(jnp.float32)
        z = y.astype(jnp.float32) * scale_eff + \
            (beta - mean * scale_eff)
        out = jnp.maximum(z, 0.0).astype(a.dtype)
        return out, (a, w, out, gamma, beta, scale_eff, mean, var)

    def _bwd(res, db):
        a, w, out, gamma, beta, scale_eff, mean, var = res
        da, dw, dgamma, dbeta = fused_conv_bn_relu_bwd(
            db, out, a, w, gamma.astype(jnp.float32),
            beta.astype(jnp.float32), scale_eff, interpret=interpret)
        return (da, dw.astype(w.dtype), dgamma.astype(gamma.dtype),
                dbeta.astype(beta.dtype), jnp.zeros_like(mean),
                jnp.zeros_like(var))

    _run.defvjp(_fwd, _bwd)
    return _run(a, w, gamma, beta, mean, var)


# ---------------------------------------------------------------------------
# tile-fused matmul ⊗ collective kernels
# ---------------------------------------------------------------------------
#
# Bucketed async RS/AG overlap (PR 1-2) hides the gradient exchange
# behind backward compute — except at the boundaries where no compute
# remains: the LAST bucket's exchange, and the collective every
# tensor-parallel matmul pays at the row/column boundary.  These ops
# close that tail the way "Optimizing Distributed ML Communication with
# Fused Computation-Collective Operations" (arXiv:2305.06942) does:
# decompose the matmul along the sharded dimension into one tile per
# rank and stream the tiles around a ppermute ring, so the wire
# transfer of tile k runs concurrently with the MXU compute of tile
# k+1 *inside one op* — the serial full-width collective disappears
# from the schedule (the HLO guard pins exactly this: ring
# collective-permutes, no boundary-wide reduce-scatter/all-gather).
# Each tile's dot runs the blocked Pallas matmul on TPU; off-TPU the
# tile dot is the identical jnp formulation, so the ring is still the
# compiled structure tier-1 asserts on the CPU mesh.

#: Valid values of the ``fused_collectives`` knob
#: (``HOROVOD_FUSED_COLLECTIVES``, docs/fused_kernels.md).
FUSED_COLLECTIVES_MODES = ("auto", "on", "off")


def resolve_fused_collectives(mode: str = "auto") -> bool:
    """Resolve the ``fused_collectives="auto"|"on"|"off"`` knob.

    ``"auto"`` enables the tile-fused path exactly when a TPU backend
    is present — the ring's per-hop latency is what the ICI fabric
    hides; on the CPU twin the fused path is opt-in (``"on"``) so the
    structural tests and probes can exercise it deliberately.
    """
    if mode not in FUSED_COLLECTIVES_MODES:
        raise ValueError(
            f"fused_collectives must be one of {FUSED_COLLECTIVES_MODES},"
            f" got {mode!r}")
    if mode == "on":
        return True
    if mode == "off":
        return False
    return _on_tpu()


def _count_fused_launch(kernel: str) -> None:
    """hvd_pallas_fused_launches_total{kernel}: one count per fused-path
    construction (trace time — the in-graph op then runs every step;
    docs/metrics.md notes the trace-time semantics)."""
    from horovod_tpu import telemetry

    telemetry.counter(
        "hvd_pallas_fused_launches_total",
        "tile-fused matmul-collective kernel constructions per kernel"
    ).inc(kernel=kernel)


def _fit_mm_block(dim: int, candidates) -> Optional[int]:
    for c in candidates:
        if c <= dim and dim % c == 0:
            return c
    return None


def _mm_kernel(x_ref, w_ref, o_ref):
    # bf16 inputs ride the MXU at full rate with fp32 accumulation via
    # preferred_element_type (same stance as the flash kernels)
    o_ref[...] = jnp.dot(x_ref[...], w_ref[...],
                         preferred_element_type=jnp.float32
                         ).astype(o_ref.dtype)


def pallas_matmul(x: jax.Array, w: jax.Array,
                  out_dtype=None,
                  interpret: bool = False) -> jax.Array:
    """``x @ w`` as a blocked Pallas kernel (fp32 MXU accumulation).

    Tiling contract: ``x`` is ``(m, k)``, ``w`` ``(k, n)`` with
    ``m % 8 == 0`` and ``k, n % 128 == 0`` (fp32 sublane/lane tiles);
    anything else — or no TPU and not interpret mode — falls back to
    the identical ``jnp.dot`` formulation.  This is the per-tile
    compute of the fused collective ops below.
    """
    out_dtype = jnp.dtype(out_dtype or jnp.result_type(x.dtype, w.dtype))
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = _fit_mm_block(m, (512, 256, 128, 64, 32, 16, 8))
    bn = _fit_mm_block(n, (512, 256, 128))
    usable = (interpret or _on_tpu()) and bm is not None \
        and bn is not None and k % 128 == 0
    if not usable:
        return jnp.dot(x, w, preferred_element_type=jnp.float32
                       ).astype(out_dtype)
    return pl.pallas_call(
        _mm_kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )(x, w)


def matmul_reducescatter(x: jax.Array, w: jax.Array, axis: str,
                         fused: bool = True,
                         interpret: bool = False) -> jax.Array:
    """Fused ``psum_scatter(x @ w)`` over mesh axis ``axis`` — the
    row-parallel boundary op.

    ``x`` is ``(m, k)`` with ``m`` divisible by the axis size, ``w``
    this rank's ``(k, n)`` contraction shard; returns the reduced
    ``(m/world, n)`` row block this rank owns (identical semantics to
    ``lax.psum_scatter(x @ w, axis, scatter_dimension=0, tiled=True)``,
    row blocks rank-major).

    Fused schedule: the output rows split into one tile per rank; each
    ring step computes ONE tile's partial product (Pallas matmul on
    TPU) while the accumulated partial for the previous tile crosses
    the wire via ``ppermute`` — after ``world-1`` hops every rank holds
    its fully-reduced tile without any boundary-wide collective.  The
    partials accumulate in fp32 regardless of input dtype.
    ``fused=False`` (or a size-1 axis) keeps the unfused formulation.
    """
    from jax import lax

    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(
            f"matmul_reducescatter takes 2-D operands, got {x.shape} @ "
            f"{w.shape} (flatten leading dims first)")
    world = int(lax.axis_size(axis))
    m = x.shape[0]
    if m % world:
        raise ValueError(
            f"matmul_reducescatter rows {m} not divisible by axis "
            f"{axis!r} size {world}")
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    if not fused or world == 1:
        y = pallas_matmul(x, w, interpret=interpret)
        if world == 1:
            return y
        return lax.psum_scatter(y, axis, scatter_dimension=0, tiled=True)
    _count_fused_launch("matmul_reducescatter")
    me = lax.axis_index(axis)
    tiles = x.reshape(world, m // world, x.shape[1])
    perm = [(i, (i + 1) % world) for i in range(world)]
    # start at tile (me-1) so that after world-1 send-right hops each
    # rank ends holding its OWN fully-reduced tile (ownership matches
    # psum_scatter's rank-major row blocks)
    idx0 = (me + world - 1) % world
    acc = pallas_matmul(jnp.take(tiles, idx0, axis=0), w,
                        out_dtype=jnp.float32, interpret=interpret)
    for s in range(1, world):
        # the ppermute and the tile matmul are data-independent: the
        # scheduler overlaps tile k's wire hop with tile k+1's compute
        acc = lax.ppermute(acc, axis, perm)
        idx = (me + world - 1 - s) % world
        acc = acc + pallas_matmul(jnp.take(tiles, idx, axis=0), w,
                                  out_dtype=jnp.float32,
                                  interpret=interpret)
    return acc.astype(out_dtype)


def expert_chunk_mlp(chunk: jax.Array, w1: jax.Array, w2: jax.Array,
                     interpret: bool = False) -> jax.Array:
    """Per-expert gelu MLP over one ``(e_local, slots, d)`` token chunk
    — the per-tile compute of :func:`expert_alltoall_ffn`.  Each
    expert's two dots run the blocked Pallas matmul on TPU
    (:func:`pallas_matmul`; off-contract shapes fall back to the
    identical jnp dot inside it), batched by a Python loop over the
    (small) local expert count so every dot is a 2-D MXU tile."""
    outs = []
    for ei in range(chunk.shape[0]):
        h = pallas_matmul(chunk[ei], w1[ei], interpret=interpret)
        outs.append(pallas_matmul(jax.nn.gelu(h), w2[ei],
                                  out_dtype=chunk.dtype,
                                  interpret=interpret))
    return jnp.stack(outs)


def expert_alltoall_ffn(dispatch: jax.Array, expert_fn,
                        axis: str, fused: bool = True,
                        interpret: bool = False) -> jax.Array:
    """Fused ``a2a ⊗ expert-matmul``: the MoE dispatch→expert→combine
    exchange over mesh axis ``axis`` with the token movement streamed
    around a ``ppermute`` ring instead of two boundary-wide
    ``all_to_all``\\ s.

    ``dispatch`` is this rank's ``(world, e_local, capacity, d)``
    routed-token buffer (dim 0 = destination expert rank, the layout
    :func:`~horovod_tpu.parallel.expert.expert_parallel_ffn` builds);
    ``expert_fn`` applies this rank's local experts to an
    ``(e_local, slots, d)`` token buffer and MUST be token-wise (each
    slot independent — true of any per-token MLP): the fused schedule
    computes it per source-rank tile, the unfused one over the whole
    ``world·capacity`` buffer, and only a slot-independent body makes
    the two identical.  Returns the combined ``(world, e_local,
    capacity, d)`` expert outputs back at the origin rank, dim 0 = the
    expert rank that computed them — exactly the unfused formulation::

        received = lax.all_to_all(dispatch, axis, 0, 0)
        outputs  = expert_fn(received … reshaped)
        combined = lax.all_to_all(outputs …)

    Fused schedule: hop ``s`` moves ONE ``(e_local, capacity, d)``
    token tile to expert rank ``me+s`` while the tile that arrived at
    hop ``s-1`` is inside its expert matmul, and each tile's outputs
    ride the inverse permute home as soon as they exist — expert
    ``k+1``'s tokens are in flight while expert ``k``'s matmul
    computes, and the boundary-wide all-to-all disappears from the
    schedule (the HLO guard pins ``2·(world−1)`` collective-permutes,
    zero all-to-all).  Differentiable end-to-end: every op is a lax
    primitive with a transpose (the grads run the ring backwards).
    ``fused=False`` keeps the unfused all_to_all formulation — the
    numerics oracle and the off-contract fallback.
    """
    from jax import lax

    if dispatch.ndim != 4:
        raise ValueError(
            f"expert_alltoall_ffn takes a (world, e_local, capacity, d) "
            f"dispatch buffer, got shape {dispatch.shape}")
    world = int(lax.axis_size(axis))
    if dispatch.shape[0] != world:
        raise ValueError(
            f"dispatch dim 0 is {dispatch.shape[0]} but axis {axis!r} "
            f"has size {world}")
    _, e_local, capacity, d = dispatch.shape
    if not fused or world == 1:
        if world == 1:
            return expert_fn(dispatch[0])[None]
        received = lax.all_to_all(dispatch, axis, split_axis=0,
                                  concat_axis=0, tiled=False)
        buffers = received.transpose(1, 0, 2, 3).reshape(
            e_local, world * capacity, d)
        outputs = expert_fn(buffers)
        outputs = outputs.reshape(e_local, world, capacity, d) \
            .transpose(1, 0, 2, 3)
        return lax.all_to_all(outputs, axis, split_axis=0,
                              concat_axis=0, tiled=False)
    _count_fused_launch("a2a_matmul")
    me = lax.axis_index(axis)
    # tile for my own experts never touches the wire: compute first so
    # its matmul overlaps hop 1's transfer
    chunks = [expert_fn(jnp.take(dispatch, me, axis=0))]
    for s in range(1, world):
        fwd = [(i, (i + s) % world) for i in range(world)]
        bwd = [(i, (i - s) % world) for i in range(world)]
        # hop s: send the tile destined for rank me+s; what arrives is
        # rank me-s's tile for MY experts.  The sends are mutually
        # data-independent, so tile s+1's wire overlaps tile s's dot.
        got = lax.ppermute(
            jnp.take(dispatch, (me + s) % world, axis=0), axis, fwd)
        # the outputs ride the inverse permute home immediately —
        # rank p receives its own tokens' results from rank p+s
        chunks.append(lax.ppermute(expert_fn(got), axis, bwd))
    # chunks[s] holds my tokens' outputs from expert rank (me+s):
    # rotate shift-major -> rank-major so dim 0 matches the unfused
    # all_to_all's source-rank ordering
    return jnp.roll(jnp.stack(chunks), me, axis=0)


def allgather_matmul(x: jax.Array, w: jax.Array, axis: str,
                     fused: bool = True,
                     interpret: bool = False) -> jax.Array:
    """Fused ``all_gather(x) @ w`` over mesh axis ``axis`` — the
    column-parallel boundary op.

    ``x`` is this rank's ``(m_local, k)`` row shard (rank-major),
    ``w`` the ``(k, n)`` kernel (typically a column shard); returns the
    full ``(world·m_local, n)`` product, identical to
    ``jnp.dot(lax.all_gather(x, axis, tiled=True), w)``.

    Fused schedule: each ring step multiplies the row shard currently
    held (Pallas matmul on TPU) while the next shard arrives via
    ``ppermute`` — the gather never materializes as a boundary-wide
    all-gather and the wire hides under the MXU.
    """
    from jax import lax

    if x.ndim != 2 or w.ndim != 2:
        raise ValueError(
            f"allgather_matmul takes 2-D operands, got {x.shape} @ "
            f"{w.shape} (flatten leading dims first)")
    world = int(lax.axis_size(axis))
    if not fused or world == 1:
        y = lax.all_gather(x, axis, tiled=True) if world > 1 else x
        return pallas_matmul(y, w, interpret=interpret)
    _count_fused_launch("allgather_matmul")
    me = lax.axis_index(axis)
    m_local = x.shape[0]
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    out = jnp.zeros((world * m_local, w.shape[1]), out_dtype)
    cur = x
    # send left = receive from the right neighbor: after s hops this
    # rank holds shard (me + s) % world
    perm = [(i, (i - 1) % world) for i in range(world)]
    for s in range(world):
        src = (me + s) % world
        part = pallas_matmul(cur, w, out_dtype=out_dtype,
                             interpret=interpret)
        out = lax.dynamic_update_slice(out, part, (src * m_local, 0))
        if s < world - 1:
            cur = lax.ppermute(cur, axis, perm)
    return out


# ---------------------------------------------------------------------------
# ring-flash attention: the sp ring fused with the flash kernels
# ---------------------------------------------------------------------------
#
# The naive jnp ring (parallel/ring_attention.py) materializes a full
# (b, h, tq, tk) fp32 score tensor per visiting block and leaves each
# ppermute serial between steps.  Here every visiting K/V block runs
# the Pallas flash kernels instead — the online-softmax partials merge
# across ring steps in log-space, so no per-block score tensor exists
# and nothing upcasts to fp32 beyond the flash accumulator — while the
# NEXT block's ppermute is issued before the current block's kernel
# (data-independent sends, the same double-buffering contract as
# expert_alltoall_ffn's dispatch ring).  docs/fused_kernels.md
# "Ring-flash attention".

#: Sequence layouts the sp ring understands (``HOROVOD_SP_LAYOUT``).
RING_LAYOUTS = ("contiguous", "zigzag")


def ring_layout_positions(rank, world: int, seq_local: int,
                          layout: str) -> jax.Array:
    """Global sequence positions shard ``rank`` holds under ``layout``.

    ``contiguous``: shard r is global chunk r of ``world`` chunks.
    ``zigzag``: shard r holds chunks ``(r, 2·world−1−r)`` of ``2·world``
    equal chunks — pairing an early (causally busy) chunk with a late
    one so the causal mask load-balances across ranks, and no causal
    ring step is ever fully masked: the low chunk of any rank precedes
    the high chunk of every rank, so every (q shard, k/v shard) pair
    has at least one allowed position.  ``rank`` may be a traced
    ``lax.axis_index``.
    """
    if layout not in RING_LAYOUTS:
        raise ValueError(
            f"sp layout must be one of {RING_LAYOUTS}, got {layout!r}")
    if layout == "contiguous":
        return rank * seq_local + jnp.arange(seq_local, dtype=jnp.int32)
    if seq_local % 2:
        raise ValueError(
            f"zigzag layout needs an even per-shard seq, got {seq_local}")
    half = seq_local // 2
    ar = jnp.arange(half, dtype=jnp.int32)
    return jnp.concatenate(
        [rank * half + ar, (2 * world - 1 - rank) * half + ar])


def zigzag_sequence_indices(world: int, seq_global: int) -> jax.Array:
    """Permutation σ with ``x_zigzag = x[σ]`` along the sequence dim.

    Contiguous (rank-major) sharding of the permuted sequence hands
    shard r exactly its zigzag chunks ``(r, 2·world−1−r)`` — the
    host-side pre-pass that makes the zigzag layout a pure relabeling
    (undo on outputs with ``jnp.argsort`` of the same indices)."""
    if seq_global % (2 * world):
        raise ValueError(
            f"zigzag needs seq divisible by 2·world={2 * world}, "
            f"got {seq_global}")
    half = seq_global // (2 * world)
    idx = []
    for r in range(world):
        idx.extend(range(r * half, (r + 1) * half))
        idx.extend(range((2 * world - 1 - r) * half,
                         (2 * world - r) * half))
    return jnp.asarray(idx, dtype=jnp.int32)


def ring_step_schedule(world: int, causal: bool = False,
                       layout: str = "contiguous") -> dict:
    """Static kernel-launch schedule of the sp ring — pure Python.

    A causal (rank, step) pair whose visiting K/V block lies entirely
    in the query shard's future launches no kernel (the runtime skip in
    :func:`ring_flash_attention`).  Chunk-level comparison is exact:
    the whole step is masked iff ``max(q chunk) < min(k/v chunk)``.
    Under ``contiguous`` that skips ``world·(world−1)/2`` of the
    ``world²`` launches — all stacked on the low ranks; ``zigzag``
    skips none because no pair is ever fully masked, and the *partial*
    mask work balances across ranks instead.  The cost model and the
    zigzag acceptance pin both read this."""
    if layout not in RING_LAYOUTS:
        raise ValueError(
            f"sp layout must be one of {RING_LAYOUTS}, got {layout!r}")

    def chunks(r):
        return (r,) if layout == "contiguous" else (r, 2 * world - 1 - r)

    skipped = []
    for r in range(world):
        n = 0
        if causal:
            qmax = max(chunks(r))
            for s in range(world):
                kmin = min(chunks((r - s) % world))
                if qmax < kmin:
                    n += 1
        skipped.append(n)
    total = sum(skipped)
    return {
        "world": world, "causal": causal, "layout": layout,
        "steps_per_rank": world,
        "launches": world * world - total,
        "skipped": total,
        "skipped_by_rank": tuple(skipped),
    }


def ring_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, causal: bool = False,
                         scale: Optional[float] = None,
                         layout: str = "contiguous",
                         block_q: int = 512, block_k: int = 512,
                         interpret: bool = False) -> jax.Array:
    """Fused sp-ring ⊗ flash attention over mesh axis ``axis_name``.

    Same contract as :func:`~horovod_tpu.parallel.ring_attention.
    ring_attention` — call inside ``shard_map`` with ``(batch,
    seq_local, heads, head_dim)`` shards, returns the exact softmax
    attention over the full global sequence — but each visiting K/V
    block is consumed by the Pallas flash kernels and the per-step
    normalized partials ``(out_s, lse_s)`` merge in log-space::

        lse  = logaddexp(lse, lse_s)
        out  = out·exp(lse_prev − lse) + out_s·exp(lse_s − lse)

    initialized at the finite ``_NEG_INF`` sentinel, so a fully-masked
    partial contributes ``exp(−huge) == 0`` exactly and the accumulator
    can never emit NaN.  The next block's ``ppermute`` is issued before
    the current block's kernel — the sends are data-independent, so the
    scheduler double-buffers the wire behind the MXU (the same contract
    as ``expert_alltoall_ffn``; on the synchronous CPU twin this pins
    structure, the overlap itself is a TPU quantity).

    Causal masking compares GLOBAL positions that travel around the
    ring with their blocks, so it composes with the ``zigzag`` layout;
    a causal ring step whose visiting block is entirely in the future
    skips its kernel launch via ``lax.cond`` (identity carry — the
    schedule is in :func:`ring_step_schedule`).

    Differentiable via ``custom_vjp``: FA2's blockwise backward is
    exact given the GLOBAL logsumexp and delta, so the backward replays
    the ring with each block's dK/dV accumulator traveling WITH the
    block — after ``world`` hops every accumulator is home and
    complete.

    Raises for shards off the flash tiling contract (unequal q/k/v
    shapes, non-tiling ``seq_local``, odd ``seq_local`` under zigzag)
    — the dispatch in ``parallel/ring_attention.py`` checks first and
    keeps the jnp formulation for those.
    """
    from jax import lax

    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"ring_flash_attention needs equal q/k/v shard shapes, got "
            f"{q.shape}/{k.shape}/{v.shape}")
    b, t, h, d = q.shape
    world = int(lax.axis_size(axis_name))
    scale = d ** -0.5 if scale is None else scale
    bq = fit_flash_block(t, block_q)
    bk = fit_flash_block(t, block_k)
    if bq is None or bk is None:
        raise ValueError(
            f"seq_local {t} does not fit the flash tiling contract; "
            f"use the jnp ring (parallel.ring_attention) instead")
    # validates layout, and zigzag's even-seq requirement (rank 0 is
    # representative; the traced per-rank positions are rebuilt inside
    # the vjp halves so no tracer is closed over across them)
    ring_layout_positions(0, world, t, layout)
    _count_fused_launch("ring_flash_attention")
    perm = [(i, (i + 1) % world) for i in range(world)]
    bh = b * h

    def _positions():
        me = lax.axis_index(axis_name)
        qpos = ring_layout_positions(me, world, t, layout)
        return qpos, jnp.max(qpos)

    def _to_o(w_row):
        # (bh, t) row weight -> broadcastable over (b, t, h, d)
        return w_row.reshape(b, h, t).transpose(0, 2, 1)[..., None]

    def _merge(out_acc, lse_acc, out_b, lse_b):
        # log-space merge of normalized flash partials.  All-finite by
        # construction: the sentinel is finite, logaddexp of finite
        # inputs is finite, and exp(_NEG_INF − anything) == 0 exactly.
        lse_new = jnp.logaddexp(lse_acc, lse_b)
        out_new = (out_acc * _to_o(jnp.exp(lse_acc - lse_new)) +
                   out_b.astype(jnp.float32) *
                   _to_o(jnp.exp(lse_b - lse_new)))
        return out_new, lse_new

    def _fwd_ring(q, k, v):
        qpos, q_max = _positions()
        out_acc = jnp.zeros((b, t, h, d), jnp.float32)
        lse_acc = jnp.full((bh, t), _NEG_INF, jnp.float32)
        k_cur, v_cur, kpos_cur = k, v, qpos
        for s in range(world):
            nxt = None
            if s < world - 1:
                # double-buffer: the hop is data-independent of this
                # step's kernel, so the wire flies behind the MXU
                nxt = lax.ppermute((k_cur, v_cur, kpos_cur),
                                   axis_name, perm)

            def live(args):
                o_acc, l_acc, k_c, v_c, kp = args
                out_b, lse_b = _flash_fwd(
                    q, k_c, v_c, causal, scale, bq, bk, interpret,
                    qpos=qpos if causal else None,
                    kpos=kp if causal else None)
                return _merge(o_acc, l_acc, out_b, lse_b[:, 0, :])

            args = (out_acc, lse_acc, k_cur, v_cur, kpos_cur)
            if causal:
                # a block entirely in the future launches no kernel;
                # the identity carry doubles as the lse=-inf NaN guard
                out_acc, lse_acc = lax.cond(
                    q_max < jnp.min(kpos_cur),
                    lambda a: (a[0], a[1]), live, args)
            else:
                out_acc, lse_acc = live(args)
            if nxt is not None:
                k_cur, v_cur, kpos_cur = nxt
        return out_acc.astype(q.dtype), lse_acc

    def _bwd_ring(res, g):
        q, k, v, out, lse_g = res
        qpos, q_max = _positions()
        gb = g.transpose(0, 2, 1, 3).reshape(bh, t, d).astype(jnp.float32)
        ob = out.transpose(0, 2, 1, 3).reshape(bh, t, d) \
            .astype(jnp.float32)
        delta = (gb * ob).sum(-1)                       # (bh, t) global
        lse8 = jnp.broadcast_to(lse_g[:, None, :], (bh, 8, t))
        dq_acc = jnp.zeros((b, t, h, d), jnp.float32)
        # the visiting block's dK/dV accumulate where the block IS and
        # travel with it: after `world` hops each is home, complete
        dk_cur = jnp.zeros((b, t, h, d), jnp.float32)
        dv_cur = jnp.zeros((b, t, h, d), jnp.float32)
        k_cur, v_cur, kpos_cur = k, v, qpos
        for s in range(world):
            nxt = None
            if s < world - 1:
                nxt = lax.ppermute((k_cur, v_cur, kpos_cur),
                                   axis_name, perm)

            def live(args):
                dq_a, dk_c, dv_c, k_c, v_c, kp = args
                dq_b, dk_b, dv_b = _flash_bwd(
                    q, k_c, v_c, out, lse8, g, causal, scale, bq, bk,
                    interpret, qpos=qpos if causal else None,
                    kpos=kp if causal else None, delta=delta)
                return (dq_a + dq_b.astype(jnp.float32),
                        dk_c + dk_b.astype(jnp.float32),
                        dv_c + dv_b.astype(jnp.float32))

            args = (dq_acc, dk_cur, dv_cur, k_cur, v_cur, kpos_cur)
            if causal:
                dq_acc, dk_cur, dv_cur = lax.cond(
                    q_max < jnp.min(kpos_cur),
                    lambda a: (a[0], a[1], a[2]), live, args)
            else:
                dq_acc, dk_cur, dv_cur = live(args)
            # the accumulators hop with their block every step — the
            # world-th hop is the homecoming
            dk_cur, dv_cur = lax.ppermute((dk_cur, dv_cur),
                                          axis_name, perm)
            if nxt is not None:
                k_cur, v_cur, kpos_cur = nxt
        return (dq_acc.astype(q.dtype), dk_cur.astype(k.dtype),
                dv_cur.astype(v.dtype))

    @jax.custom_vjp
    def _attn(q, k, v):
        out, _ = _fwd_ring(q, k, v)
        return out

    def _fwd(q, k, v):
        out, lse_g = _fwd_ring(q, k, v)
        return out, (q, k, v, out, lse_g)

    _attn.defvjp(_fwd, _bwd_ring)
    return _attn(q, k, v)
