"""Eager host-level collectives with Horovod's API shape.

The reference's user surface (``horovod/torch/mpi_ops.py``,
``tensorflow/mpi_ops.py``) is *eager per-tensor*: each call enqueues one
named tensor into the C++ background loop which negotiates, fuses and
executes (``operations.cc:840-1068``).  The TPU replacement keeps the
call shape — ``allreduce``/``allreduce_async``/``synchronize``/``poll``,
named tensors, pre/postscale, Average/Sum/Adasum — but the machinery
underneath is re-rooted:

* *world* = JAX processes (one multi-chip host process each).  Tensors are
  lifted into a global array sharded over a one-device-per-process "proc"
  mesh and reduced by a jitted SPMD computation; XLA runs the collective
  over ICI/DCN.  With a single process the ops reduce to local scaling.
* *async* = JAX's dispatch-and-return execution: a handle wraps the
  not-yet-materialized output array — the role the reference's handle
  manager plays for torch (``torch/handle_manager.{h,cc}``,
  ``mpi_ops.py:590-627 poll/synchronize``).
* *fusion* = the :class:`~horovod_tpu.ops.bucketing.Bucketer`: async
  submissions accumulate and flush as one grouped collective per dtype
  (see ``bucketing.py`` for the fusion-buffer mapping).

In-jit training code should use ``horovod_tpu.ops.collectives`` directly;
this module is for host-side orchestration (metric averaging, parameter
broadcast, object exchange) and API familiarity.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.ops.collectives import Adasum, Average, ReduceOp, Sum
from horovod_tpu.ops import adasum as adasum_mod
from horovod_tpu.runtime import state
from horovod_tpu.utils import logging as hvd_logging
from horovod_tpu.utils import timeline as tl

# Reference error text: common.h:163 DUPLICATE_NAME_ERROR
_DUPLICATE_NAME_ERROR = (
    "Requested to collect a tensor with the same name as another tensor "
    "that is currently being processed. If you want to request another "
    "tensor, use a different tensor name.")


# Collective failures raise HorovodInternalError; elastic mode catches it
# and restores state (reference ``common/exceptions.py:18``).
from horovod_tpu.exceptions import HorovodInternalError  # noqa: E402


_lock = threading.Lock()
_in_flight: dict = {}
_name_counter = 0
_proc_mesh: Optional[Mesh] = None
# Global negotiation-cycle counter.  Every eager collective performs exactly
# one `_negotiate` round, and negotiation rounds are themselves collectives,
# so the counter advances in lock-step on every process — it is the global
# "tick" the reference's background loop provides implicitly.  join() records
# the tick at which each process joined; the max identifies the exact last
# joiner (the reference controller knows this from request arrival order).
_cycle = 0


def _next_name(prefix: str) -> str:
    global _name_counter
    with _lock:
        _name_counter += 1
        return f"{prefix}.noname.{_name_counter}"


def process_mesh() -> Mesh:
    """One-device-per-process mesh: the eager ops' communicator.

    The analogue of the reference's GLOBAL communicator over worker
    processes (``common.h:113``)."""
    global _proc_mesh
    if _proc_mesh is None:
        by_proc: dict = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = [by_proc[p] for p in sorted(by_proc)]
        _proc_mesh = Mesh(np.array(devs), ("proc",))
    return _proc_mesh


def _reset_mesh_cache() -> None:
    """Drop every cache that captures the proc mesh — called on elastic
    world resize; stale jitted fns would pin the old world's devices."""
    global _proc_mesh, _cycle
    _proc_mesh = None
    _cycle = 0
    _validated_signatures.clear()
    _desc_cache.clear()
    _reducer_cache.clear()
    _motion_cache.clear()
    from horovod_tpu.ops import op_manager

    # HOST-plane KV keys carry a per-call counter that must restart in
    # lock-step with the new world (a fresh process starts at zero)
    op_manager.reset_host_plane()
    # timeline-aggregation upload keys carry the same kind of SPMD-
    # ordered counter: surviving processes must restart it so it stays
    # aligned with freshly-joined workers (which start at zero)
    from horovod_tpu.utils import timeline as _tl

    _tl._aggregate_seq = 0


_validated_signatures: set = set()
# digest → descriptor, populated at validation time on every process so a
# later join() can replay previously-seen collectives without re-paying
# the payload exchange (see _negotiate)
_desc_cache: dict = {}

# Reference join-incompatibility error texts (``controller.cc:487-497,569``).
_JOIN_UNSUPPORTED = {
    "allgather": "Allgather is not supported with Join at this time. "
                 "Specify sparse_as_dense=True if using DistributedOptimizer",
    "alltoall": "Alltoall is not supported with Join at this time.",
    "broadcast": "Broadcast is not supported with Join at this time.",
}
# Allreduce sub-ops a joined rank can zero-fill.  Zeros are the identity for
# SUM; AVERAGE is sum + postscale 1/world_size in the reference
# (``operations.cc:851-854``) so joined zeros lower the mean exactly as they
# do there; Adasum's pairwise combine is zero-safe (coefficients fall back to
# 1 on zero norms, ``adasum.py:_combine``).  MIN/MAX/PRODUCT have no zero
# identity — mirroring the reference's op whitelist they error under join.
_JOIN_ZERO_OPS = (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM)


def _join_bad_op_error(op_name: str) -> str:
    """One shared message for active and joined ranks — the error-cycle
    contract is that every rank raises the identical error."""
    return (f"Allreduce op {op_name} is not supported with Join: zero "
            f"contributions from joined ranks have no identity under "
            f"{op_name}.")


class _Negotiation:
    """Outcome of one controller cycle."""

    __slots__ = ("all_joined", "last_rank", "joined", "desc")

    def __init__(self, all_joined, last_rank, joined, desc):
        self.all_joined = all_joined
        self.last_rank = last_rank
        self.joined = joined      # process indices currently in join()
        self.desc = desc          # agreed collective descriptor (dict)


def _negotiate(desc: Optional[dict], join_cycle: int = -1) -> _Negotiation:
    """One negotiation cycle — controller-lite with Join support.

    The reference's coordinator gathers per-rank Requests each cycle,
    validates dtype/shape/op agreement, counts JOIN requests, and turns
    mismatches into descriptive error responses delivered on every rank
    (``ComputeResponseList`` ``controller.cc:63``, ``ConstructResponse``
    ``controller.cc:380``, JOIN counting ``controller.cc:220-223``).  The
    SPMD replacement is a fixed-shape host-metadata allgather per cycle:

      ``[is_join, join_cycle, payload_len, sha256(payload) as 4 words]``

    * all processes joined → everyone leaves join(); the exact last rank
      is the one with the highest join tick (ties → highest rank), the
      same answer the reference reads off request arrival order.
    * a mix of joined and active processes → one extra variable-size
      payload exchange so joined ranks learn the collective's descriptor
      and can contribute zero tensors (``tensor_queue.cc``
      ``GetTensorEntriesFromResponse`` synthesizes zero entries;
      ``controller.cc:263-274``).  Only allreduce-family ops support
      this; others raise the reference's error text
      (``controller.cc:487-497,569``).
    * digest mismatch among active processes → HorovodInternalError on
      all of them, naming the divergent processes.

    The fixed head exchange runs unconditionally — a joined process
    blocked in its service loop must observe every cycle, so there is no
    skip-the-wire fast path (the reference pays the same: its cache-hit
    path still does 2 bitwise-AND + 1 bitwise-OR cross-rank syncs,
    ``controller.cc:133-164``).

    On cache invalidation (deliberate design difference): the reference
    stall inspector invalidates cached responses of stalled tensors so
    they renegotiate (``stall_inspector.h:73-81`` +
    ``response_cache.cc``).  Here the caches are *cross-process wire
    state* — ``need_payload`` is computed from cache membership on every
    process independently, which is only sound because all processes
    mutate the caches at identical cycles.  A stall-triggered,
    one-sided invalidation would desynchronize that decision and
    misalign the payload exchange (deadlock), so stalls are surfaced
    through the stall inspector's warnings/shutdown and the timeline's
    NEGOTIATE events instead of cache eviction; the only evictions are
    the deterministic size-bound clear below and the world-reset clear
    in ``_reset_mesh_cache``.
    """
    global _cycle
    mesh = process_mesh()
    nproc = mesh.devices.size
    _cycle += 1
    import hashlib
    import pickle

    # Bounded caches.  The length is identical on every process at any
    # aligned cycle (all processes run identical collective sequences),
    # so the clear fires at the same cycle everywhere — a prerequisite
    # for using cache membership in wire-shape decisions below.
    if len(_validated_signatures) > 8192:
        _validated_signatures.clear()
        _desc_cache.clear()

    if desc is None:
        payload = b""
        head = np.zeros((7,), np.int64)
        head[0], head[1] = 1, join_cycle
    else:
        payload = pickle.dumps(desc, protocol=4)
        digest = hashlib.sha256(payload).digest()
        head = np.empty((7,), np.int64)
        head[0], head[1], head[2] = 0, -1, len(payload)
        head[3:] = np.frombuffer(digest, np.int64)[:4]

    heads = _allgather_host_metadata(head)  # (nproc, 7)
    joined = [p for p in range(nproc) if heads[p, 0]]
    active = [p for p in range(nproc) if not heads[p, 0]]

    if not active:
        ticks = heads[:, 1]
        last = max(range(nproc), key=lambda p: (int(ticks[p]), p))
        return _Negotiation(True, int(last), joined, None)

    ref = active[0]
    ref_digest = heads[ref, 3:].tobytes()
    seen = ref_digest in _validated_signatures

    # Payload exchange only when a joined rank may be missing the
    # descriptor.  Every process — active or joined — records
    # digest→descriptor at validation time, and all processes execute
    # identical collective sequences, so the caches are identical and
    # the skip decision is computable everywhere from shared data (no
    # collective misalignment).  A previously-validated descriptor thus
    # costs only the fixed head exchange even mid-join.
    need_payload = bool(joined) and not seen
    shared_desc = desc
    if need_payload:
        maxlen = int(heads[:, 2].max())
        wire_len = ((maxlen + 7) // 8) * 8
        raw = np.zeros((wire_len,), np.uint8)
        raw[:len(payload)] = np.frombuffer(payload, np.uint8)
        allp = _allgather_host_metadata(raw.view(np.int64))
        if desc is None:
            shared_desc = pickle.loads(
                allp[ref].tobytes()[:int(heads[ref, 2])])
    elif desc is None:
        shared_desc = _desc_cache.get(ref_digest)
        if shared_desc is None:  # pragma: no cover - invariant violation
            raise HorovodInternalError(
                "internal: joined process has no cached descriptor for a "
                "previously-validated collective — negotiation caches "
                "desynchronized across processes.")

    bad = [p for p in active
           if not (heads[p, 2:] == heads[ref, 2:]).all()]
    if desc is None:
        # Joined rank: when active ranks disagree they all raise and
        # stop issuing collectives — re-entering the head exchange would
        # block forever.  The mismatch is computable right here from the
        # gathered heads (the same data the active ranks used), so raise
        # the error on this rank too: the reference controller delivers
        # the error response on every rank (``controller.cc:380``).
        if bad:
            raise HorovodInternalError(
                f"Mismatched collective across processes while this "
                f"process (rank {jax.process_index()}) was in join(): "
                f"process(es) {bad} disagree with process {ref} on the "
                f"name/dtype/shape/op for this collective slot. All "
                f"processes must issue identical collectives in "
                f"identical order.")
        if not seen:
            _validated_signatures.add(ref_digest)
            _desc_cache[ref_digest] = shared_desc
        return _Negotiation(False, -1, joined, shared_desc)
    if bad:
        raise HorovodInternalError(
            f"Mismatched {desc.get('kind')} across processes: process "
            f"{jax.process_index()} submitted [{desc.get('sig')}] but "
            f"process(es) {bad} disagree with process {ref} on the "
            f"name/dtype/shape/op for this collective slot. All processes "
            f"must issue identical collectives in identical order.")

    if not seen:
        _validated_signatures.add(ref_digest)
        _desc_cache[ref_digest] = desc
    st = state.global_state() if state.is_initialized() else None
    if st:
        st.cache_stats["hits" if seen else "misses"] += 1
        # negotiation-phase observability: the reference timeline records
        # NEGOTIATE_* phases per tensor (controller.cc:845-857); here one
        # instant per cycle carrying the cache outcome and join count
        if st.timeline is not None:
            st.timeline.instant(
                tl.NEGOTIATE, {"kind": desc.get("kind"),
                               "cache": "hit" if seen else "miss",
                               "cycle": _cycle, "joined": len(joined)})

    if joined:
        kind = desc.get("kind")
        if kind in _JOIN_UNSUPPORTED:
            raise HorovodInternalError(_JOIN_UNSUPPORTED[kind])
        if kind == "allreduce" and \
                ReduceOp[desc["op"]] not in _JOIN_ZERO_OPS:
            raise HorovodInternalError(_join_bad_op_error(desc["op"]))
    return _Negotiation(False, -1, joined, shared_desc)


def _localize(tensor) -> jax.Array:
    """Intake normalization: a previous eager collective returns an array
    replicated over the *global* proc mesh; feeding it straight into the
    next collective (the natural training loop: ``w -= lr *
    allreduce(grad(w))``) must work.  Such arrays span non-addressable
    devices, which ``device_put``/``np.asarray`` reject — take the local
    replica.  Only *replicated* arrays get this shortcut: truncating a
    genuinely sharded array to its shard 0 would silently reduce a
    fragment."""
    if isinstance(tensor, jax.Array) and \
            len(tensor.sharding.device_set) > 1:
        if tensor.sharding.is_fully_replicated:
            return jnp.asarray(tensor.addressable_data(0))
        if not tensor.is_fully_addressable:
            raise HorovodInternalError(
                "eager collectives take per-process local tensors (or "
                "replicated results of previous eager collectives); got "
                "a globally-sharded array — gather it first, or use the "
                "in-jit horovod_tpu.ops.collectives inside your step.")
        # fully-addressable sharded input: jnp.asarray gathers it
    return jnp.asarray(tensor)


def _lift(tensor: jax.Array) -> jax.Array:
    """Lift this process's tensor into a (nproc, ...) global array sharded
    one-row-per-process."""
    mesh = process_mesh()
    nproc = mesh.devices.size
    local = jnp.asarray(tensor)[None]
    sharding = NamedSharding(mesh, P("proc", *([None] * tensor.ndim)))
    if nproc == 1:
        return jax.device_put(local, sharding)
    my_dev = mesh.devices.flat[jax.process_index()]
    return jax.make_array_from_single_device_arrays(
        (nproc,) + tuple(tensor.shape), sharding,
        [jax.device_put(local, my_dev)])


def _replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


_reducer_cache: dict = {}


def _reduce_global(garr, op: ReduceOp, prescale, postscale, nproc: int,
                   segments: tuple = ()):
    """jit-compiled reduction over the proc mesh with replicated output;
    compiled once per (op, scales, segments) — jax.jit memoizes per
    shape/dtype (the response-cache analogue, ``response_cache.{h,cc}``).

    ``segments`` (tuple of flat lengths) marks per-tensor boundaries inside
    a fused buffer; only Adasum consumes it — its dot/norm coefficients are
    per layer, never over the whole fusion buffer (reference
    ``ComputeDotAndNormSqrds`` walks the tensor table per entry).
    """
    mesh = process_mesh()
    key = (id(mesh), op, prescale, postscale, nproc, segments)
    fn = _reducer_cache.get(key)
    st = state.global_state() if state.is_initialized() else None
    if fn is None:
        fn = jax.jit(
            partial(_reduce_impl, op=op, prescale=prescale,
                    postscale=postscale, nproc=nproc, segments=segments),
            out_shardings=_replicated(mesh))
        _reducer_cache[key] = fn
        if st:
            st.cache_stats["misses"] += 1
    elif st:
        st.cache_stats["hits"] += 1
    return fn(garr)


def _adasum_tree(rows: list, xp=jnp):
    """Pairwise Adasum reduction tree; one combine formula for both data
    planes (``adasum_mod._combine`` is xp-generic)."""
    vals = list(rows)
    while len(vals) > 1:
        nxt = [adasum_mod._combine(vals[i], vals[i + 1], xp=xp)
               for i in range(0, len(vals) - 1, 2)]
        if len(vals) % 2:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def _reduce_stacked(x, *, op: ReduceOp, prescale, postscale, nproc: int,
                    segments: tuple = (), xp=jnp):
    """Reduce a stacked ``(nproc, n)`` array of per-process rows — the
    single source of truth for op/scale numerics, shared by the XLA
    plane (``xp=jnp``, under jit) and the HOST plane (``xp=np``) so the
    two planes cannot drift."""
    # 0.0 is a legal scale factor (reference accepts arbitrary doubles), so
    # test against None, not truthiness
    scaled = prescale is not None or postscale is not None
    dtype = x.dtype
    if scaled and dtype.name in ("float16", "bfloat16"):
        x = x.astype(xp.float32)
    if prescale is not None:
        x = x * prescale
    if op == ReduceOp.ADASUM:
        if segments:
            outs, off = [], 0
            for seg in segments:
                rows = [x[i, off:off + seg] for i in range(nproc)]
                outs.append(_adasum_tree(rows, xp=xp))
                off += seg
            y = xp.concatenate(outs) if len(outs) > 1 else outs[0]
        else:
            y = _adasum_tree([x[i] for i in range(nproc)], xp=xp)
    elif op == ReduceOp.AVERAGE:
        y = xp.mean(x, axis=0)
    elif op == ReduceOp.SUM:
        y = xp.sum(x, axis=0)
    elif op == ReduceOp.MIN:
        y = xp.min(x, axis=0)
    elif op == ReduceOp.MAX:
        y = xp.max(x, axis=0)
    elif op == ReduceOp.PRODUCT:
        y = xp.prod(x, axis=0)
    else:
        raise ValueError(f"unsupported op {op}")
    if postscale is not None:
        y = y * postscale
    return y.astype(dtype)


def _reduce_impl(garr, *, op: ReduceOp, prescale, postscale, nproc: int,
                 segments: tuple = ()):
    return _reduce_stacked(garr, op=op, prescale=prescale,
                           postscale=postscale, nproc=nproc,
                           segments=segments, xp=jnp)


class Handle:
    """Async collective handle (reference torch handle model:
    ``allreduce_async_`` returns an int handle resolved by
    ``synchronize()``, ``torch/mpi_ops.py:606``)."""

    def __init__(self, name: str):
        self.name = name
        self._result = None
        self._done = threading.Event()
        self._error: Optional[Exception] = None

    def _fulfill(self, result) -> None:
        self._result = result
        self._done.set()
        st = state.global_state() if state.is_initialized() else None
        if st and st.stall_inspector:
            st.stall_inspector.record_complete(self.name)
        with _lock:
            _in_flight.pop(self.name, None)

    def _fail(self, err: Exception) -> None:
        self._error = err
        self._done.set()
        st = state.global_state() if state.is_initialized() else None
        if st and st.stall_inspector:
            st.stall_inspector.record_complete(self.name)
        with _lock:
            _in_flight.pop(self.name, None)


def _register(name: str, handle: Handle) -> None:
    with _lock:
        if name in _in_flight:
            raise HorovodInternalError(_DUPLICATE_NAME_ERROR + f" (name={name})")
        _in_flight[name] = handle
    st = state.global_state() if state.is_initialized() else None
    if st and st.stall_inspector:
        st.stall_inspector.record_dispatch(name)


def _timeline():
    st = state.global_state() if state.is_initialized() else None
    return st.timeline if st else None


# ---------------------------------------------------------------------------
# public eager ops
# ---------------------------------------------------------------------------

def allreduce(tensor, average: Optional[bool] = None, name: Optional[str] = None,
              op: Optional[ReduceOp] = None,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              compression=None):
    """Synchronous allreduce across worker processes (reference
    ``horovod/torch/mpi_ops.py:allreduce`` / ``tensorflow/__init__.py:52``)."""
    h = allreduce_async(tensor, average=average, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        compression=compression)
    return synchronize(h)


def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    prescale_factor: Optional[float] = None,
                    postscale_factor: Optional[float] = None,
                    compression=None) -> Handle:
    from horovod_tpu.ops.bucketing import global_bucketer

    if op is None:
        op = Average if (average is None or average) else Sum
    if compression is not None and not hasattr(compression, "compress"):
        # validate before the handle registers / spans open: a rejected
        # call must leave no in-flight handle, stall record, or span
        raise ValueError(
            "Compression.int8 is an in-jit wire reduction (shard_map "
            "mode); the eager plane exchanges whole tensors — use "
            "Compression.fp16/bf16 here")
    name = name or _next_name("allreduce")
    handle = Handle(name)
    _register(name, handle)
    # per-tensor negotiation phase (reference timeline.h:77-131: every
    # tensor walks NEGOTIATING → TOP_LEVEL; NegotiateStart fires when the
    # request enters the system).  The span opens here at enqueue and
    # closes in _dispatch_group once the cycle's negotiation agrees.
    tlobj = _timeline()
    if tlobj is not None:
        tlobj.start_activity(name, tl.NEGOTIATE)
    # remember which timeline (if any) holds the open NEGOTIATE span so the
    # flush-time close pairs B/E on the same file even if the timeline is
    # started/stopped between enqueue and flush
    handle._tl_neg = tlobj
    tensor = _localize(tensor)
    ctx = None
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    handle._decompress = (compression, ctx)
    global_bucketer().add(name, tensor, op, prescale_factor,
                          postscale_factor, handle)
    return handle


def _dispatch_group(entries) -> None:
    """Flush callback from the bucketer: one fused collective per flush.

    This is ``PerformOperation`` (``operations.cc:253``) re-rooted: instead
    of memcpy-into-fusion-buffer + NCCL, we concatenate flat tensors and
    run one jitted reduction over the proc mesh.
    """
    nproc = process_mesh().devices.size
    tlobj = _timeline()

    def _end_negotiate():
        # close each entry's NEGOTIATE span on the timeline it was opened
        # on at enqueue (None if the timeline was off then)
        for e in entries:
            t = getattr(e.handle, "_tl_neg", None)
            if t is not None:
                t.end_activity(e.name)
                e.handle._tl_neg = None

    xla_open = False
    try:
        e0 = entries[0]
        segments = tuple(int(e.tensor.size) for e in entries) \
            if e0.op == ReduceOp.ADASUM else ()
        total = int(sum(e.tensor.size for e in entries))
        if nproc > 1:
            # Descriptor carries exactly what a joined rank needs to
            # issue the identical jitted reduction with zero inputs:
            # flat length, dtype, op, scales, segments.  ``sig`` is the
            # human-readable slot signature for mismatch errors.
            _negotiate({
                "kind": "allreduce",
                "n": total,
                "dtype": str(e0.tensor.dtype),
                "op": e0.op.name,
                "pre": e0.prescale,
                "post": e0.postscale,
                "segments": segments,
                "sig": "; ".join(
                    f"{e.name}:{e.tensor.dtype}:{tuple(e.tensor.shape)}:"
                    f"{e.op.name}:{e.prescale}:{e.postscale}"
                    for e in entries),
            })
        # negotiation agreed: close each tensor's NEGOTIATE span and open
        # its dispatch span (reference NEGOTIATING → TOP_LEVEL → ACTIVITY
        # transition, timeline.h:77-131 + controller.cc:845-857)
        _end_negotiate()
        if tlobj is not None:
            for e in entries:
                tlobj.start_activity(e.name, tl.XLA_ALLREDUCE)
            xla_open = True
        # Always reduce the flattened concatenation — a single entry
        # too — so the compiled program depends only on (n, dtype, op,
        # scales, segments) and joined ranks can replay it exactly.
        from horovod_tpu.ops import op_manager

        flat = jnp.concatenate(
            [jnp.ravel(e.tensor) for e in entries]) \
            if len(entries) > 1 else jnp.ravel(e0.tensor)
        red = op_manager.active_op().reduce_rows(
            flat, e0.op, e0.prescale, e0.postscale, segments,
            nproc, jax.process_index())
        red = jnp.asarray(red)
        off = 0
        for e in entries:
            n = e.tensor.size
            e.handle._fulfill(red[off:off + n].reshape(e.tensor.shape))
            off += n
        if xla_open:
            for e in entries:
                tlobj.end_activity(e.name)
            xla_open = False
    except Exception as err:  # surface as HorovodInternalError for elastic
        _end_negotiate()
        if xla_open:
            for e in entries:
                tlobj.end_activity(e.name)
        for e in entries:
            e.handle._fail(HorovodInternalError(str(err)))


def _fence(x):
    """Completion fence that survives remote-device tunnels.

    ``jax.block_until_ready`` can return before execution finishes when the
    device is driven through a remote PJRT tunnel; a host fetch cannot, so
    for non-empty arrays we pull one element (the tiny index program's
    completion implies the array's).  Returns ``x`` itself.
    """
    if getattr(x, "size", 0):
        np.asarray(jnp.ravel(x)[0])
        return x
    return jax.block_until_ready(x)


def synchronize(handle: Handle):
    """Block until the handle's collective completed and return the result
    (reference ``torch/mpi_ops.py:606``)."""
    from horovod_tpu.ops.bucketing import global_bucketer

    if not handle._done.is_set():
        global_bucketer().flush()
    handle._done.wait()
    if handle._error is not None:
        raise handle._error
    result = handle._result
    compression, ctx = getattr(handle, "_decompress", (None, None))
    if compression is not None:
        result = compression.decompress(result, ctx)
    return _fence(result)


def poll(handle: Handle) -> bool:
    """Non-blocking completion check (reference ``torch/mpi_ops.py:590``).

    Polling an undispatched handle drains the pending buckets first (the
    reference's background loop would have picked the tensor up within one
    cycle; with no background thread, the poll itself is the cycle edge —
    and a deterministic one, since it follows program order on every
    process)."""
    if not handle._done.is_set():
        from horovod_tpu.ops.bucketing import global_bucketer

        global_bucketer().flush()
    if not handle._done.is_set():
        return False
    r = handle._result
    try:
        return bool(r.is_ready()) if hasattr(r, "is_ready") else True
    except Exception:
        return True


_motion_cache: dict = {}


def _allgather_rows(garr):
    """O(data) data plane for eager allgather.

    ``lax.all_gather`` inside a shard_map over the proc mesh: each process
    wires out its own row once and receives the other ``nproc-1`` rows —
    total bytes on the wire per process = size of the gathered result, the
    same cost contract as the reference's ``MPI_Allgatherv``
    (``mpi_operations.cc:96``).  (A replicated ``out_shardings`` identity
    jit happens to lower to the same collective, but only by optimizer
    grace; this shape is the explicit, guaranteed form.)
    """
    mesh = process_mesh()
    key = ("ag", id(mesh))
    fn = _motion_cache.get(key)
    if fn is None:
        def ag(x):          # local block: (1, rows, ...)
            return jax.lax.all_gather(x, "proc", axis=0, tiled=True)

        fn = jax.jit(jax.shard_map(
            ag, mesh=mesh, in_specs=P("proc"), out_specs=P(),
            check_vma=False))
        _motion_cache[key] = fn
    return fn(garr)


def _alltoall_rows(garr):
    """O(data) data plane for eager alltoall.

    ``lax.all_to_all`` inside a shard_map over the proc mesh.  Input is the
    slot-packed global array ``(nproc_sender, nproc_dest, max_rows, ...)``
    sharded by sender; the collective routes slot ``d`` of each sender to
    process ``d``.  Wire cost per process: send ``(nproc-1) × max_rows``
    rows, receive the same — O(data), matching ``MPI_Alltoallv``
    (``mpi_operations.cc:392``).  The round-1 implementation replicated the
    whole slot tensor to every process (O(world²·max_rows) received per
    process); this is the fix for that scaling bug.

    Returns the global result ``(nproc_sender, nproc_dest, max_rows, ...)``
    sharded over the *destination* axis; callers read their own column via
    ``addressable_shards`` — no further cross-process movement.
    """
    mesh = process_mesh()
    key = ("a2a", id(mesh))
    fn = _motion_cache.get(key)
    if fn is None:
        def a2a(x):         # local block: (1, nproc, max_rows, ...)
            return jax.lax.all_to_all(x, "proc", split_axis=1,
                                      concat_axis=0)

        fn = jax.jit(jax.shard_map(
            a2a, mesh=mesh, in_specs=P("proc"),
            out_specs=P(None, "proc"), check_vma=False))
        _motion_cache[key] = fn
    return fn(garr)


def _fulfilled(name: str, value) -> Handle:
    """A pre-completed handle (the nproc==1 short-circuit of the async
    variants keeps the handle API shape)."""
    h = Handle(name)
    h._result = value
    h._done.set()
    return h


def allgather(tensor, name: Optional[str] = None):
    """Gather tensors from all processes, concatenated on dim 0; first dims
    may differ per process (reference ``EnqueueTensorAllgather``
    ``operations.cc:903``, recvcounts in ``mpi_operations.cc:96``)."""
    out, _ = allgather_with_sizes(tensor, name=name)
    return out


def allgather_async(tensor, name: Optional[str] = None) -> Handle:
    """Async ``allgather`` (reference ``allgather_async``,
    ``torch/mpi_ops.py:692``): the negotiation head runs inline — eager
    collectives must hit the wire in program order on every process —
    but the device computation and result fetch stay asynchronous until
    ``synchronize``."""
    handle, _ = _allgather_submit(tensor, name)
    return handle


def allgather_with_sizes(tensor, name: Optional[str] = None):
    """``allgather`` that also returns the negotiated per-process first-dim
    sizes as a host ``np.ndarray`` — callers exchanging variable payloads
    (``allgather_object``) reuse them instead of a second collective."""
    handle, sizes = _allgather_submit(tensor, name)
    return synchronize(handle), sizes


def _allgather_submit(tensor, name: Optional[str] = None):
    name = name or _next_name("allgather")
    tensor = _localize(tensor)
    mesh = process_mesh()
    nproc = mesh.devices.size
    if nproc == 1:
        return (_fulfilled(name, tensor),
                np.asarray([tensor.shape[0]], np.int64))
    handle = Handle(name)
    _register(name, handle)
    sizes = None
    try:
        # sequential NEGOTIATE -> XLA_* spans (docs/timeline.md contract;
        # matches _dispatch_group's transition) so the dispatch span never
        # absorbs negotiation wait
        with tl.activity(name, tl.NEGOTIATE):
            # first dims may differ per process; everything else must agree
            _negotiate({
                "kind": "allgather",
                "sig": f"{name}:{tensor.dtype}:{tuple(tensor.shape[1:])}",
            })
            # negotiate first-dim sizes (the controller's recvcount exchange)
            sizes = _allgather_host_metadata(
                np.asarray([tensor.shape[0]], np.int64)).reshape(nproc)
        with tl.activity(name, tl.XLA_ALLGATHER):
            max_rows = int(sizes.max())
            from horovod_tpu.ops import op_manager

            pad = jnp.zeros((max_rows,) + tensor.shape[1:], tensor.dtype)
            pad = pad.at[:tensor.shape[0]].set(tensor)
            rows = op_manager.active_op().allgather_padded(
                pad, nproc, jax.process_index())
            out = jnp.concatenate(
                [jnp.asarray(rows[p])[:int(sizes[p])]
                 for p in range(nproc)], axis=0)
            handle._fulfill(out)
    except Exception as err:
        handle._fail(HorovodInternalError(str(err)))
    return handle, sizes


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Broadcast from ``root_rank`` process to all (reference
    ``EnqueueTensorBroadcast``, ``operations.cc:928``)."""
    return synchronize(broadcast_async(tensor, root_rank, name=name))


def broadcast_async(tensor, root_rank: int,
                    name: Optional[str] = None) -> Handle:
    """Async ``broadcast`` (reference ``broadcast_async``,
    ``torch/mpi_ops.py:755``); negotiation inline for program-order
    alignment, device work asynchronous until ``synchronize``."""
    name = name or _next_name("broadcast")
    tensor = _localize(tensor)
    mesh = process_mesh()
    nproc = mesh.devices.size
    if nproc == 1:
        return _fulfilled(name, tensor)
    handle = Handle(name)
    _register(name, handle)
    try:
        with tl.activity(name, tl.NEGOTIATE):
            _negotiate({
                "kind": "broadcast",
                "sig": f"{name}:{tensor.dtype}:{tuple(tensor.shape)}:"
                       f"{root_rank}",
            })
        with tl.activity(name, tl.XLA_BROADCAST):
            from horovod_tpu.ops import op_manager

            out = op_manager.active_op().bcast(
                tensor, root_rank, nproc, jax.process_index())
            handle._fulfill(jnp.asarray(out))
    except Exception as err:
        handle._fail(HorovodInternalError(str(err)))
    return handle


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Distribute slices of dim 0 to all processes (reference
    ``EnqueueTensorAlltoall``, ``operations.cc:979``).  ``splits[i]`` rows go
    to process i; uniform split when ``splits`` is None.  Returns the
    concatenation of slices received from every process."""
    return synchronize(alltoall_async(tensor, splits, name=name))


def alltoall_async(tensor, splits=None,
                   name: Optional[str] = None) -> Handle:
    """Async ``alltoall`` (reference ``alltoall_async``,
    ``torch/mpi_ops.py:812``); negotiation inline for program-order
    alignment, device work asynchronous until ``synchronize``."""
    name = name or _next_name("alltoall")
    tensor = _localize(tensor)
    mesh = process_mesh()
    nproc = mesh.devices.size
    if splits is None:
        if tensor.shape[0] % nproc != 0:
            raise ValueError(
                "tensor dim 0 not divisible by world size; pass splits")
        splits = np.full((nproc,), tensor.shape[0] // nproc, np.int64)
    splits = np.asarray(splits, np.int64)
    if splits.sum() != tensor.shape[0]:
        raise ValueError("splits must sum to tensor.shape[0]")
    if nproc == 1:
        return _fulfilled(name, tensor)
    handle = Handle(name)
    _register(name, handle)
    try:
        with tl.activity(name, tl.NEGOTIATE):
            _negotiate({
                "kind": "alltoall",
                "sig": f"{name}:{tensor.dtype}:{tuple(tensor.shape[1:])}",
            })
            all_splits = _allgather_host_metadata(splits)  # (nproc, nproc)
            all_splits = all_splits.reshape(nproc, nproc)
        with tl.activity(name, tl.XLA_ALLTOALL):
            max_rows = int(all_splits.max())
            me = jax.process_index()
            from horovod_tpu.ops import op_manager

            # slot-pack: slot d holds rows destined to process d
            slots = jnp.zeros((nproc, max_rows) + tensor.shape[1:],
                              tensor.dtype)
            off = 0
            for d in range(nproc):
                cnt = int(splits[d])
                if cnt:
                    slots = slots.at[d, :cnt].set(tensor[off:off + cnt])
                off += cnt
            cols = op_manager.active_op().alltoall_slots(slots, nproc, me)
            out = jnp.concatenate(
                [jnp.asarray(cols[src])[:int(all_splits[src, me])]
                 for src in range(nproc)], axis=0)
            handle._fulfill(out)
    except Exception as err:
        handle._fail(HorovodInternalError(str(err)))
    return handle


def _allgather_host_metadata(arr: np.ndarray) -> np.ndarray:
    """Tiny fixed-shape host metadata allgather over processes — the
    control-plane exchange (recvcounts / splits negotiation,
    ``mpi_controller.cc:164-231``).

    int64 payloads are exchanged as int32 word pairs: without
    ``jax_enable_x64`` jnp silently truncates int64 to int32, which would
    corrupt any value ≥ 2^31 (e.g. nanosecond timestamps)."""
    arr = np.ascontiguousarray(arr)
    mesh = process_mesh()
    nproc = mesh.devices.size
    if nproc == 1:
        return arr[None]
    from horovod_tpu.ops import op_manager

    return op_manager.active_op().metadata_allgather(
        arr, nproc, jax.process_index())


def _xla_metadata_allgather(arr: np.ndarray) -> np.ndarray:
    """XLA-plane implementation of the metadata allgather (called via
    ``op_manager.XlaOps``): replicated identity jit over the lifted
    array.  int64 payloads are exchanged as int32 word pairs — without
    ``jax_enable_x64`` jnp silently truncates int64 to int32, which
    would corrupt any value ≥ 2^31 (e.g. microsecond timestamps)."""
    mesh = process_mesh()
    nproc = mesh.devices.size
    is64 = arr.dtype == np.int64
    wire = arr.view(np.int32) if is64 else arr
    garr = _lift(jnp.asarray(wire))
    rep = jax.jit(lambda g: g, out_shardings=_replicated(mesh))(garr)
    out = np.ascontiguousarray(np.asarray(rep))
    if is64:
        out = out.view(np.int64)
    return out.reshape((nproc,) + arr.shape)


def barrier(name: Optional[str] = None) -> None:
    """Block until all processes arrive (reference
    ``MPIController::Barrier``, ``mpi_controller.cc:225``).

    The negotiation head exchange IS the barrier; routing it through
    ``_negotiate`` (rather than a bare metadata allgather) keeps the wire
    aligned when some processes sit in a ``join()`` service loop — they
    observe a ``barrier`` descriptor, contribute nothing, and keep
    cycling."""
    mesh = process_mesh()
    if mesh.devices.size == 1:
        return
    _negotiate({"kind": "barrier", "sig": "barrier"})


def join() -> int:
    """Uneven-data termination: joined processes keep servicing other
    ranks' collectives with zero contributions until every process joins
    (reference ``EnqueueJoin`` ``operations.cc:1044``; zero synthesis
    ``controller.cc:263-274`` + ``tensor_queue.cc
    GetTensorEntriesFromResponse``).  Returns the exact rank of the last
    process to join, from the globally-consistent negotiation tick at
    which each process entered join (ties broken toward the higher rank)
    — the answer the reference controller reads off request arrival
    order.

    While a process sits in this loop, other ranks may continue issuing
    ``allreduce`` (SUM/AVERAGE/ADASUM — the joined process replays the
    identical jitted reduction with a zero input, so AVERAGE still
    divides by the full world size, exactly like the reference's
    postscale-1/size) and ``barrier``.  ``allgather``/``broadcast``/
    ``alltoall`` from non-joined ranks raise the reference's
    "not supported with Join" errors — on those ranks AND out of this
    loop (the reference delivers error responses on every rank,
    ``controller.cc:380``; a fatally-erroring peer must not leave
    joined processes blocking forever).  The error cycle completes its
    wire exchanges everywhere before anyone raises, so ranks that catch
    the error stay aligned and may re-enter ``join()``.  Ragged
    *per-step* participation
    inside a jitted train step is handled by zero-masking instead (see
    ``horovod_tpu.optim.join_step``).
    """
    from horovod_tpu.ops.bucketing import global_bucketer

    global_bucketer().flush()
    mesh = process_mesh()
    nproc = mesh.devices.size
    if nproc == 1:
        return 0
    my_tick = _cycle
    while True:
        neg = _negotiate(None, join_cycle=my_tick)
        if neg.all_joined:
            return neg.last_rank
        d = neg.desc
        if d is None:  # pragma: no cover - _negotiate raises on mismatch
            continue
        kind = d.get("kind")
        # Active ranks raise on join-unsupported collectives and then
        # stop issuing cycles; raise the identical error here instead of
        # blocking forever in the next head exchange (reference delivers
        # error responses on every rank, ``controller.cc:487-497,569``).
        if kind in _JOIN_UNSUPPORTED:
            raise HorovodInternalError(_JOIN_UNSUPPORTED[kind])
        if kind == "allreduce":
            op = ReduceOp[d["op"]]
            if op not in _JOIN_ZERO_OPS:
                raise HorovodInternalError(_join_bad_op_error(d["op"]))
            from horovod_tpu.ops import op_manager

            zeros = jnp.zeros((d["n"],), jnp.dtype(d["dtype"]))
            op_manager.active_op().reduce_rows(
                zeros, op, d["pre"], d["post"], tuple(d["segments"]),
                nproc, jax.process_index())
        elif d.get("kind") == "hostsync":
            # elastic host-update sync: participate in the fixed 3-word
            # exchange with zeros ("nothing to report")
            _allgather_host_metadata(np.zeros((3,), np.int64))
        # barrier: the head exchange was the whole contribution; loop
        # straight back into the next cycle.  (Unsupported kinds raised
        # above — they never reach this point.)
