"""Gradient compression for collectives.

Mirrors the reference's compression API (``horovod/torch/compression.py``,
``horovod/tensorflow/compression.py``): a ``Compressor`` with
``compress(tensor) -> (tensor, ctx)`` / ``decompress(tensor, ctx)``, and a
``Compression`` namespace with ``none`` and ``fp16``.  On TPU the natural
wire dtype is bfloat16 (no loss of exponent range), so a ``bf16``
compressor is added alongside the reference's fp16.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Compressor:
    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    """Identity (reference ``NoneCompressor``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 for the wire, back to original dtype after
    (reference ``FP16Compressor``)."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx \
            else tensor


class BF16Compressor(Compressor):
    """TPU-native halving: bfloat16 keeps fp32's exponent range and is the
    MXU's native input dtype — strictly better than fp16 on TPU."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if jnp.issubdtype(tensor.dtype, jnp.floating):
            tensor = tensor.astype(jnp.bfloat16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.astype(ctx) if ctx is not None and tensor.dtype != ctx \
            else tensor


class Int8WireReduction:
    """Marker selecting the int8-quantized *wire reduction* — not a
    ``Compressor``: the reduction runs *between* compress and decompress,
    and summing int8 payloads with per-shard scales would overflow and
    mis-scale.  Reduction layers that see this marker
    (``grouped_allreduce``/``distributed_gradients``/
    ``DistributedTrainStep``) route through
    :func:`horovod_tpu.ops.collectives.quantized_allreduce`, which agrees
    on a shared scale first (EQuARX-style): 1 byte/element on the wire
    for the main reduction vs 4 for fp32, one absmax-scaled rounding of
    accuracy cost, identical on every shard."""

    wire_reduce_bits = 8


class Compression:
    """Namespace matching the reference's ``Compression`` selector."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8WireReduction
