"""Collective operations: SPMD primitives, eager API, fusion, compression.

Layer map (vs reference ``horovod/common/ops/``):

* :mod:`~horovod_tpu.ops.collectives` — in-mesh XLA collectives (the
  NCCL/MPI op implementations' replacement).
* :mod:`~horovod_tpu.ops.eager` — host-level named-tensor API with async
  handles (the enqueue API + framework-binding replacement).
* :mod:`~horovod_tpu.ops.bucketing` — tensor fusion for eager submissions.
* :mod:`~horovod_tpu.ops.adasum` — adaptive-summation reduction.
* :mod:`~horovod_tpu.ops.compression` — fp16/bf16 wire compression.
"""

from horovod_tpu.ops.collectives import (
    Adasum,
    Average,
    ReduceOp,
    Sum,
)
from horovod_tpu.ops.compression import Compression
from horovod_tpu.ops.eager import (
    Handle,
    HorovodInternalError,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    join,
    poll,
    synchronize,
)

__all__ = [
    "Adasum", "Average", "ReduceOp", "Sum", "Compression",
    "Handle", "HorovodInternalError",
    "allreduce", "allreduce_async", "allgather", "allgather_async",
    "alltoall", "alltoall_async", "broadcast_async", "barrier",
    "broadcast", "join", "poll", "synchronize",
]
