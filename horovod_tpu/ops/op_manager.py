"""Operation manager: the data-plane priority chain, TPU edition.

The reference dispatches every collective through an ``OperationManager``
holding per-type op lists tried in priority order — first ``Enabled()``
wins (``ops/operation_manager.cc:40-98``), with the order set by build
flags and env knobs (``HOROVOD_CPU_OPERATIONS=MPI|GLOO|CCL``,
``HOROVOD_GPU_ALLREDUCE=NCCL|MPI|DDL``, chain built in
``CreateOperationManager`` ``operations.cc:142-249``).

The TPU runtime has two genuinely distinct eager data planes, each a
plane object implementing the same five primitives so dispatch in
``ops.eager`` is a method call, not a special case:

* :class:`XlaOps` (default): tensors are lifted onto the proc mesh and
  the collective compiles to XLA collectives over ICI/DCN — the NCCL
  analogue, and the only plane the in-jit training path ever uses.
* :class:`HostOps`: tensors move as raw bytes through the coordination
  service's key-value store and reduce in numpy on the host — the
  Gloo-on-CPU analogue.  No device compile; useful for debugging
  transport vs. compiler issues and for tiny control payloads.

``HOROVOD_TPU_OPERATIONS=XLA|HOST`` (flag ``--tpu-operations``) orders
the chain, mirroring the reference knob's semantics: the requested plane
goes first, the other remains as fallback; per-call dispatch takes the
first enabled plane.

Plane primitive interface (all collective — every process must call in
the same order; ``rank``/``nproc`` are process-level):

* ``metadata_allgather(arr, nproc, rank) -> (nproc, *arr.shape) ndarray``
* ``reduce_rows(flat, op, pre, post, segments, nproc, rank) -> flat``
* ``allgather_padded(padded, nproc, rank) -> list of per-process rows``
* ``bcast(tensor, root, nproc, rank) -> tensor``
* ``alltoall_slots(slots, nproc, rank) -> list indexed by source``
  (``slots[d]`` = rows this process sends to process ``d``; returns the
  rows each source sent to *this* process)
"""

from __future__ import annotations

import collections
import threading
from typing import List, Optional

import numpy as np

from horovod_tpu.utils import logging as hvd_logging


class XlaOps:
    """XLA data plane — delegates to the jitted/shard_map implementations
    in ``ops.eager`` (lazy import; eager imports this module back)."""

    name = "XLA"

    def enabled(self) -> bool:
        return True

    def metadata_allgather(self, arr: np.ndarray, nproc: int,
                           rank: int) -> np.ndarray:
        from horovod_tpu.ops import eager

        return eager._xla_metadata_allgather(arr)

    def reduce_rows(self, flat, op, prescale, postscale, segments,
                    nproc: int, rank: int):
        import jax.numpy as jnp

        from horovod_tpu.ops import eager

        garr = eager._lift(jnp.asarray(flat))
        return eager._reduce_global(garr, op, prescale, postscale, nproc,
                                    tuple(segments))

    def allgather_padded(self, padded, nproc: int, rank: int) -> list:
        from horovod_tpu.ops import eager

        rep = eager._allgather_rows(eager._lift(padded))
        return [rep[p] for p in range(nproc)]

    def bcast(self, tensor, root_rank: int, nproc: int, rank: int):
        import jax

        from horovod_tpu.ops import eager

        mesh = eager.process_mesh()
        garr = eager._lift(tensor)
        return jax.jit(lambda g: g[root_rank],
                       out_shardings=eager._replicated(mesh))(garr)

    def alltoall_slots(self, slots, nproc: int, rank: int) -> list:
        from horovod_tpu.ops import eager

        routed = eager._alltoall_rows(eager._lift(slots))
        # my column lives in my local shard: (nproc_sender, 1, ...) —
        # already a single-device jax.Array; slice on device
        local = routed.addressable_shards[0].data
        return [local[src, 0] for src in range(nproc)]


class HostOps:
    """Host data plane over the coordination-service KV store.

    Keys carry a monotonically increasing call counter that is identical
    on every process (calls are collective and SPMD-ordered; the counter
    resets with the world, see :func:`reset_host_plane`).  Each call
    records the keys it wrote; keys from call N-2 are deleted at call N:
    a process entering call N has completed call N-1, which implies
    every process wrote its N-1 keys, which implies every process
    finished reading call N-2 — the deletion can never race a reader.
    """

    name = "HOST"
    TIMEOUT_MS = 120_000

    def __init__(self):
        self._counter = 0
        self._written: "collections.deque" = collections.deque()
        self._lock = threading.Lock()
        self._pool = None   # lazy, reused across calls (thread churn)

    def reset(self) -> None:
        """Forget counter + pending GC — the elastic world reset.  Every
        surviving process resets in lock-step (``_reset_mesh_cache``) and
        new processes start at zero, so counters stay aligned; the new
        generation also gets a fresh coordination service, so stale keys
        from the old world are unreachable anyway."""
        with self._lock:
            self._counter = 0
            self._written.clear()
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _client(self):
        from jax._src import distributed as dist

        return dist.global_state.client

    def enabled(self) -> bool:
        import jax

        if jax.process_count() == 1:
            return True
        return self._client() is not None

    # -- keyed transport core ----------------------------------------------

    def _next_call(self) -> int:
        with self._lock:
            self._counter += 1
            return self._counter

    def _gc_and_record(self, client, call: int, keys: list) -> None:
        with self._lock:
            self._written.append((call, keys))
            stale = []
            while self._written and self._written[0][0] <= call - 2:
                stale.extend(self._written.popleft()[1])
        for k in stale:
            try:
                client.key_value_delete(k)
            except Exception:  # pragma: no cover - best-effort GC
                pass

    def _exchange(self, sends: dict, recv_keys: list) -> List[bytes]:
        """Write ``sends`` {key: bytes}, blocking-read ``recv_keys``.

        Reads are issued concurrently so a collective costs one
        round-trip of latency, not ``nproc`` sequential round trips —
        the flat-latency property the reference's Gloo ring has
        (``ops/gloo_operations.cc:119``).

        GC safety requires every call to read at least one key written
        by *every other* process: observing process p's call-K key
        proves p entered call K, hence finished all call K-1 reads,
        hence no reader can still be inside call K-1 when this process
        reaches call K+1 and deletes K-1 keys (see class docstring).
        Callers must pass ``recv_keys`` covering all peers.
        """
        client = self._client()
        call = self._next_call()
        written = []
        for k, v in sends.items():
            client.key_value_set_bytes(f"hvdhost/{call}/{k}", v)
            written.append(f"hvdhost/{call}/{k}")
        get = lambda k: client.blocking_key_value_get_bytes(  # noqa: E731
            f"hvdhost/{call}/{k}", self.TIMEOUT_MS)
        if len(recv_keys) <= 1:
            out = [get(k) for k in recv_keys]
        else:
            out = self._pool_map(get, recv_keys)
        self._gc_and_record(client, call, written)
        return out

    def _pool_map(self, fn, keys: list) -> list:
        """Concurrent map on the cached pool; a concurrent ``reset()``
        may shut the pool down between acquisition and map — retry with
        a fresh pool, falling back to serial reads rather than leaking a
        RuntimeError the recovery path doesn't treat as recoverable."""
        for _ in range(2):
            with self._lock:
                if self._pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._pool = ThreadPoolExecutor(
                        max_workers=32,
                        thread_name_prefix="hvd_tpu_host_plane")
                pool = self._pool
            try:
                return list(pool.map(fn, keys))
            except RuntimeError:
                with self._lock:
                    if self._pool is pool:
                        self._pool = None
                continue
        return [fn(k) for k in keys]

    @staticmethod
    def _decode(raw: bytes, like: np.ndarray) -> np.ndarray:
        return np.frombuffer(raw, like.dtype).reshape(like.shape)

    # -- plane primitives ---------------------------------------------------

    def metadata_allgather(self, arr: np.ndarray, nproc: int,
                           rank: int) -> np.ndarray:
        arr = np.ascontiguousarray(arr)
        if nproc == 1:
            return arr[None]
        rows = self._exchange({str(rank): arr.tobytes()},
                              [str(p) for p in range(nproc)])
        return np.stack([self._decode(r, arr) for r in rows])

    def reduce_rows(self, flat, op, prescale, postscale, segments,
                    nproc: int, rank: int):
        from horovod_tpu.ops import eager

        flat = np.ascontiguousarray(np.asarray(flat))
        rows = self.metadata_allgather(flat, nproc, rank)
        return eager._reduce_stacked(
            rows, op=op, prescale=prescale, postscale=postscale,
            nproc=nproc, segments=tuple(segments), xp=np)

    def allgather_padded(self, padded, nproc: int, rank: int) -> list:
        padded = np.ascontiguousarray(np.asarray(padded))
        if nproc == 1:
            return [padded]
        rows = self._exchange({str(rank): padded.tobytes()},
                              [str(p) for p in range(nproc)])
        return [self._decode(r, padded) for r in rows]

    def bcast(self, tensor, root_rank: int, nproc: int, rank: int):
        tensor = np.ascontiguousarray(np.asarray(tensor))
        if nproc == 1:
            return tensor
        # O(data): only the root uploads a payload; non-roots publish an
        # empty marker.  Every process reads every peer's key (payload
        # from root, markers from the rest) — the marker reads are what
        # keep the GC invariant (see _exchange): without them a fast
        # root could finish, advance two calls, and delete keys a slow
        # peer is still blocking on.
        sends = {str(rank): tensor.tobytes() if rank == root_rank else b""}
        rows = self._exchange(sends, [str(p) for p in range(nproc)])
        return self._decode(rows[root_rank], tensor)

    def alltoall_slots(self, slots, nproc: int, rank: int) -> list:
        slots = np.ascontiguousarray(np.asarray(slots))
        if nproc == 1:
            return [slots[0]]
        # O(data) per process: one key per destination, read own column —
        # not an allgather of the whole (nproc, max_rows) slot matrix.
        sends = {f"{rank}.{d}": np.ascontiguousarray(slots[d]).tobytes()
                 for d in range(nproc)}
        rows = self._exchange(sends,
                              [f"{src}.{rank}" for src in range(nproc)])
        return [self._decode(r, slots[0]) for r in rows]


_XLA = XlaOps()
_HOST = HostOps()
_chain_cache: Optional[tuple] = None


def _requested() -> str:
    from horovod_tpu.runtime import state

    if state.is_initialized():
        return state.global_state().config.tpu_operations
    from horovod_tpu.runtime.config import Config

    return Config.from_env().tpu_operations


def chain() -> List:
    """Priority-ordered op list (reference ``CreateOperationManager``)."""
    global _chain_cache
    req = _requested()
    if _chain_cache is not None and _chain_cache[0] == req:
        return list(_chain_cache[1])
    if req == "HOST":
        ops = [_HOST, _XLA]
    else:
        if req not in ("XLA", ""):
            hvd_logging.warning(
                "HOROVOD_TPU_OPERATIONS=%s is not a known data plane "
                "(XLA, HOST); defaulting to XLA", req)
        ops = [_XLA, _HOST]
    _chain_cache = (req, tuple(ops))
    return ops


def active_op():
    """First enabled op in the chain — the reference's
    ``ExecuteOperation`` dispatch rule (``operation_manager.cc:100``)."""
    for op in chain():
        if op.enabled():
            return op
    return _XLA   # unreachable: XLA is always enabled


def current_operations() -> str:
    """Name of the data plane eager collectives will use (probe API —
    the analogue of ``horovod_nccl_built()``-style introspection,
    ``operations.cc:784``)."""
    return active_op().name


def reset_host_plane() -> None:
    """Reset HOST-plane counters on an elastic world change (called from
    ``eager._reset_mesh_cache``)."""
    _HOST.reset()


def _reset_for_tests() -> None:
    global _chain_cache
    _chain_cache = None
    _HOST.reset()
