"""Tensor fusion for the eager path — the fusion buffer, compiler-era.

The reference's headline optimization is Tensor Fusion: the background
loop packs every gradient that became ready within one cycle (default
5 ms) into a 64 MiB fusion buffer and runs a single collective
(``fusion_buffer_manager.{h,cc}``, threshold default
``operations.cc:432``, packing in ``controller.cc:686 FuseResponses``).

Eager async submissions here accumulate in per-(op, dtype, scale) buckets —
the same grouping key ``FuseResponses`` uses (response type, devices,
dtype, ``controller.cc:720-745``) — and flush as ONE concatenated
collective when any of the reference's triggers fires:

* accumulated bytes reach ``HOROVOD_FUSION_THRESHOLD`` (64 MiB default);
* a ``synchronize()``/``poll()`` needs a pending result (drain, like the
  reference's shutdown/stall drain paths).

Flush points deliberately depend ONLY on program order (submission
sequence, byte counts), never on wall-clock timers: every process must
fuse the *same* tensor set into the same collective or the global
computations diverge — the invariant the reference's controller
negotiation establishes with ``FuseResponses`` and that SPMD gets for
free as long as flush decisions are deterministic.  ``HOROVOD_CYCLE_TIME``
is therefore advisory on TPU (autotune may still tune it for telemetry
parity), not a flush trigger.

There is no double memcpy: concatenation happens on device inside the same
jitted program as the reduction, so XLA fuses pack + collective + unpack.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from horovod_tpu.runtime import state
from horovod_tpu.utils import timeline as tl


def plan_buckets(nbytes: Sequence[int],
                 bucket_bytes: Optional[int],
                 reverse: bool = True) -> List[List[int]]:
    """Partition leaf indices into byte-capped fusion buckets — the
    compiler-era form of the reference's fusion-buffer cycle, used by
    the in-graph sharded exchange
    (:func:`horovod_tpu.ops.collectives.grouped_reducescatter`).

    ``nbytes[i]`` is leaf ``i``'s payload.  Greedy, order-preserving
    packing: a bucket closes when adding the next leaf would exceed
    ``bucket_bytes`` (a single oversized leaf still gets its own
    bucket).  With ``reverse=True`` (default) leaves are walked from
    the END of the pytree: autodiff produces gradients in reverse
    layer order, so bucket 0 holds the *earliest-ready* gradients of
    the backward pass and its collective appears first in program
    order — the dependency structure that lets XLA's latency-hiding
    scheduler start the first reduce-scatter while earlier layers'
    backward is still computing (the role of the reference's
    ready-order background flushes, ``controller.cc:686``).

    ``bucket_bytes`` of ``None`` or ``<= 0`` disables splitting: one
    bucket with every index (still reverse-ordered), i.e. the
    monolithic exchange.

    Like the eager :class:`Bucketer`, the plan depends only on static
    shapes and the cap — never on timing — so every shard compiles the
    identical collective schedule.
    """
    order = range(len(nbytes) - 1, -1, -1) if reverse \
        else range(len(nbytes))
    if not bucket_bytes or bucket_bytes <= 0:
        ids = list(order)
        return [ids] if ids else []
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i in order:
        if cur and cur_bytes + nbytes[i] > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes[i]
    if cur:
        buckets.append(cur)
    return buckets


class _Entry:
    __slots__ = ("name", "tensor", "op", "prescale", "postscale", "handle",
                 "nbytes")

    def __init__(self, name, tensor, op, prescale, postscale, handle):
        self.name = name
        self.tensor = tensor
        self.op = op
        self.prescale = prescale
        self.postscale = postscale
        self.handle = handle
        self.nbytes = tensor.size * tensor.dtype.itemsize


class Bucketer:
    """Eager-plane fusion buckets.

    Submission order IS gradient-ready order (the framework hooks fire
    as autodiff produces each gradient, last layer first), so
    threshold-triggered dispatches leave in reverse-layer order — the
    eager twin of :func:`plan_buckets`' reverse walk for the compiled
    path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[tuple, List[_Entry]] = {}
        self._bytes: Dict[tuple, int] = {}

    def _config(self):
        if state.is_initialized():
            return state.global_state().config
        from horovod_tpu.runtime.config import Config

        return Config()

    def add(self, name, tensor, op, prescale, postscale, handle) -> None:
        from horovod_tpu.ops.eager import _dispatch_group

        cfg = self._config()
        e = _Entry(name, tensor, op, prescale, postscale, handle)
        key = (op, str(tensor.dtype), prescale, postscale)
        group = None
        with self._lock:
            self._buckets.setdefault(key, []).append(e)
            self._bytes[key] = self._bytes.get(key, 0) + e.nbytes
            # deterministic trigger only: byte threshold in submission order
            if self._bytes[key] >= max(cfg.fusion_threshold_bytes, 1):
                group = self._take(key)
        if group:
            self._mark_cycle()
            _dispatch_group(group)
            self._record_autotune(group)

    def _take(self, key) -> List[_Entry]:
        entries = self._buckets.pop(key, [])
        self._bytes.pop(key, None)
        return entries

    def flush(self) -> None:
        """Drain all pending buckets in insertion order
        (synchronize/poll/shutdown path) — insertion order is program
        order, so the drain is cross-process deterministic too."""
        from horovod_tpu.ops.eager import _dispatch_group

        with self._lock:
            groups = [self._take(k) for k in list(self._buckets)]
        for g in groups:
            if g:
                self._mark_cycle()
                _dispatch_group(g)
                self._record_autotune(g)

    def _mark_cycle(self) -> None:
        if state.is_initialized():
            tline = state.global_state().timeline
            if tline is not None:
                tline.mark_cycle_start()

    def _record_autotune(self, group) -> None:
        if state.is_initialized():
            pm = state.global_state().parameter_manager
            if pm is not None and pm.active:
                pm.record_bytes(sum(e.nbytes for e in group))


_bucketer: Optional[Bucketer] = None
_bucketer_lock = threading.Lock()


def global_bucketer() -> Bucketer:
    global _bucketer
    if _bucketer is None:
        with _bucketer_lock:
            if _bucketer is None:
                _bucketer = Bucketer()
    return _bucketer
