"""Tensor fusion for the eager path — the fusion buffer, compiler-era.

The reference's headline optimization is Tensor Fusion: the background
loop packs every gradient that became ready within one cycle (default
5 ms) into a 64 MiB fusion buffer and runs a single collective
(``fusion_buffer_manager.{h,cc}``, threshold default
``operations.cc:432``, packing in ``controller.cc:686 FuseResponses``).

Eager async submissions here accumulate in per-(op, dtype, scale) buckets —
the same grouping key ``FuseResponses`` uses (response type, devices,
dtype, ``controller.cc:720-745``) — and flush as ONE concatenated
collective when any of the reference's triggers fires:

* accumulated bytes reach ``HOROVOD_FUSION_THRESHOLD`` (64 MiB default);
* a ``synchronize()``/``poll()`` needs a pending result (drain, like the
  reference's shutdown/stall drain paths).

Flush points deliberately depend ONLY on program order (submission
sequence, byte counts), never on wall-clock timers: every process must
fuse the *same* tensor set into the same collective or the global
computations diverge — the invariant the reference's controller
negotiation establishes with ``FuseResponses`` and that SPMD gets for
free as long as flush decisions are deterministic.  ``HOROVOD_CYCLE_TIME``
is therefore advisory on TPU (autotune may still tune it for telemetry
parity), not a flush trigger.

There is no double memcpy: concatenation happens on device inside the same
jitted program as the reduction, so XLA fuses pack + collective + unpack.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from horovod_tpu.runtime import state
from horovod_tpu.utils import timeline as tl


class _Entry:
    __slots__ = ("name", "tensor", "op", "prescale", "postscale", "handle",
                 "nbytes")

    def __init__(self, name, tensor, op, prescale, postscale, handle):
        self.name = name
        self.tensor = tensor
        self.op = op
        self.prescale = prescale
        self.postscale = postscale
        self.handle = handle
        self.nbytes = tensor.size * tensor.dtype.itemsize


class Bucketer:
    def __init__(self):
        self._lock = threading.Lock()
        self._buckets: Dict[tuple, List[_Entry]] = {}
        self._bytes: Dict[tuple, int] = {}

    def _config(self):
        if state.is_initialized():
            return state.global_state().config
        from horovod_tpu.runtime.config import Config

        return Config()

    def add(self, name, tensor, op, prescale, postscale, handle) -> None:
        from horovod_tpu.ops.eager import _dispatch_group

        cfg = self._config()
        e = _Entry(name, tensor, op, prescale, postscale, handle)
        key = (op, str(tensor.dtype), prescale, postscale)
        group = None
        with self._lock:
            self._buckets.setdefault(key, []).append(e)
            self._bytes[key] = self._bytes.get(key, 0) + e.nbytes
            # deterministic trigger only: byte threshold in submission order
            if self._bytes[key] >= max(cfg.fusion_threshold_bytes, 1):
                group = self._take(key)
        if group:
            self._mark_cycle()
            _dispatch_group(group)
            self._record_autotune(group)

    def _take(self, key) -> List[_Entry]:
        entries = self._buckets.pop(key, [])
        self._bytes.pop(key, None)
        return entries

    def flush(self) -> None:
        """Drain all pending buckets in insertion order
        (synchronize/poll/shutdown path) — insertion order is program
        order, so the drain is cross-process deterministic too."""
        from horovod_tpu.ops.eager import _dispatch_group

        with self._lock:
            groups = [self._take(k) for k in list(self._buckets)]
        for g in groups:
            if g:
                self._mark_cycle()
                _dispatch_group(g)
                self._record_autotune(g)

    def _mark_cycle(self) -> None:
        if state.is_initialized():
            tline = state.global_state().timeline
            if tline is not None:
                tline.mark_cycle_start()

    def _record_autotune(self, group) -> None:
        if state.is_initialized():
            pm = state.global_state().parameter_manager
            if pm is not None and pm.active:
                pm.record_bytes(sum(e.nbytes for e in group))


_bucketer: Optional[Bucketer] = None
_bucketer_lock = threading.Lock()


def global_bucketer() -> Bucketer:
    global _bucketer
    if _bucketer is None:
        with _bucketer_lock:
            if _bucketer is None:
                _bucketer = Bucketer()
    return _bucketer
