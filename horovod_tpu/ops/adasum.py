"""Adasum: adaptive-summation gradient reduction, TPU formulation.

The reference implements Adasum (arXiv 2006.02924) as a templated C++
recursive-halving allreduce (``horovod/common/ops/adasum/adasum.h`` —
``FusedAllreduce`` with per-layer ``ComputeDotAndNormSqrds``; MPI variant
``adasum_mpi.{h,cc}`` builds log2(N) nested reduction communicators; GPU
variant ``adasum_gpu_operations.cc:38`` does NCCL reduce-scatter inside the
node, Adasum-MPI across nodes, NCCL allgather back).

The pairwise rule, per layer: given gradients ``a``, ``b``,

    a' = (1 - a.b / (2|a|^2)) * a  +  (1 - a.b / (2|b|^2)) * b

which is ``a+b`` for orthogonal gradients and the average for parallel
ones — summation that adapts to gradient correlation.

TPU formulation: recursive *doubling* over a mesh axis with
``lax.ppermute`` (XOR-partner exchange, log2(N) rounds).  Each round
exchanges the full vector and both partners apply the symmetric rule, so
all shards converge to the identical result — no separate allgather-back
phase.  Dots/norms are elementwise-multiply + psum-free local reductions
(vectors are full after exchange), computed in fp32 regardless of input
dtype (the reference's fp16 path does the same accumulation widening,
``adasum.h:107``).

Hierarchy: for the (dcn, ici) runtime mesh we mirror the reference GPU
dispatch — plain *average* inside the ici axis (postscale ``1/local_size``,
``operations.cc:859-866``), Adasum across the dcn axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.runtime.topology import AXIS_DCN, AXIS_ICI, GLOBAL_AXES

AxisSpec = Union[str, Sequence[str]]


def _combine(a: jax.Array, b: jax.Array, xp=jnp) -> jax.Array:
    """One pairwise Adasum combine (reference ``adasum.h`` coefficient
    computation inside ``FusedAllreduce``).  ``xp``-generic (jnp or
    numpy) so the eager HOST data plane shares these exact numerics."""
    af = a.astype(xp.float32)
    bf = b.astype(xp.float32)
    dot = xp.vdot(af, bf)
    anormsq = xp.vdot(af, af)
    bnormsq = xp.vdot(bf, bf)
    acoeff = xp.where(anormsq >= 1e-30, 1.0 - dot / (2.0 * anormsq + 1e-30), 1.0)
    bcoeff = xp.where(bnormsq >= 1e-30, 1.0 - dot / (2.0 * bnormsq + 1e-30), 1.0)
    return (acoeff * af + bcoeff * bf).astype(a.dtype)


def _combine_many(azs: list, bzs: list) -> list:
    """Per-tensor (per-layer) combine for fused calls — each tensor gets its
    own dot/norm, matching the per-layer semantics of
    ``ComputeDotAndNormSqrds`` over the fusion buffer's tensor table."""
    return [_combine(a, b) for a, b in zip(azs, bzs)]


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _adasum_axis(xs: list, axis: str) -> list:
    """Adasum over one named mesh axis for a list of tensors."""
    n = lax.axis_size(axis)
    if n == 1:
        return xs
    if _is_pow2(n):
        rounds = n.bit_length() - 1
        for r in range(rounds):
            dist = 1 << r
            perm = [(i, i ^ dist) for i in range(n)]
            partners = [lax.ppermute(x, axis, perm=perm) for x in xs]
            xs = _combine_many(xs, partners)
        return xs
    # Non-power-of-two fallback: gather everything and run the identical
    # binary-tree reduction on every shard (replicated compute, one
    # all_gather of bandwidth — acceptable for the uncommon world sizes the
    # reference also special-cases).
    out = []
    for x in xs:
        stacked = lax.all_gather(x, axis, tiled=False)  # (n, ...)
        vals = [stacked[i] for i in range(n)]
        while len(vals) > 1:
            nxt = []
            for i in range(0, len(vals) - 1, 2):
                nxt.append(_combine(vals[i], vals[i + 1]))
            if len(vals) % 2:
                nxt.append(vals[-1])
            vals = nxt
        out.append(vals[0])
    return out


def adasum_grouped_allreduce(xs: Sequence[jax.Array],
                             axis: AxisSpec = GLOBAL_AXES) -> list:
    """Adasum-reduce a group of tensors with per-tensor coefficients.

    Multi-axis (dcn, ici) dispatch mirrors ``AdasumGpuAllreduceOp::Execute``
    (``adasum_gpu_operations.cc:38``): average within ici, Adasum across dcn.
    """
    xs = list(xs)
    if isinstance(axis, str):
        return _adasum_axis(xs, axis)
    axes = tuple(axis)
    if len(axes) == 1:
        return _adasum_axis(xs, axes[0])
    if axes != GLOBAL_AXES and set(axes) != set(GLOBAL_AXES):
        raise ValueError(f"adasum over unsupported axis tuple {axes}")
    local_n = lax.axis_size(AXIS_ICI)
    xs = [lax.psum(x, AXIS_ICI) / local_n for x in xs]
    return _adasum_axis(xs, AXIS_DCN)


def adasum_allreduce(x: jax.Array, axis: AxisSpec = GLOBAL_AXES) -> jax.Array:
    """Single-tensor Adasum allreduce (request type ADASUM,
    ``message.h:51``; dispatched from :func:`horovod_tpu.ops.collectives.allreduce`)."""
    return adasum_grouped_allreduce([x], axis=axis)[0]
