"""Version shims for the jax pinned in this image.

The repo is written against the modern ``jax.shard_map`` entry point,
whose replication-checking kwarg is ``check_vma``; the image pins
jax 0.4.37, where shard_map still lives at
``jax.experimental.shard_map.shard_map`` and the kwarg is spelled
``check_rep``.  Rather than fork every call site (and every test) on a
version check, importing :mod:`horovod_tpu` installs one alias:
``jax.shard_map`` that accepts either spelling and forwards to
whichever implementation the installed jax provides.

The same goes for ``jax.lax.axis_size``: 0.4.37 predates it, but
``jax.core.axis_frame(name)`` already returns the bound axis size as a
plain int, which is exactly the static value the collectives layer
needs for shard-shape arithmetic.  And for
``jax._src.distributed._jax`` (the coordination-service bindings):
0.4.37 ships the same factories on ``xla_extension`` under the older
keyword spelling, adapted below.

The shims are additive only — on a jax that already ships the modern
names nothing is touched, so upgrading the image drops them to no-ops
instead of shadowing the real APIs.
"""

from __future__ import annotations

import functools

import jax


def _install_shard_map() -> None:
    if getattr(jax, "shard_map", None) is not None:
        return

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, **kwargs):
        if check_rep is None:
            check_rep = True if check_vma is None else bool(check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep,
                          **kwargs)

    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if getattr(jax.lax, "axis_size", None) is not None:
        return

    def axis_size(axis_name):
        """Static size of a bound mesh axis (modern ``lax.axis_size``)."""
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for a in axis_name:
                n *= axis_size(a)
            return n
        return jax.core.axis_frame(axis_name)

    jax.lax.axis_size = axis_size


def _install_distributed_runtime() -> None:
    """``jax._src.distributed._jax`` — the coordination-service bindings
    :mod:`horovod_tpu.runtime.distributed` drives.  Modern jax re-exports
    the jaxlib module there; 0.4.37 exposes the same factories on
    ``xla_extension`` with the older knob spelling (``heartbeat_interval``
    × ``max_missing_heartbeats`` instead of one ``heartbeat_timeout``, a
    one-arg missed-heartbeat callback, no ``recoverable``).  The adapter
    translates the modern call the repo makes into the pinned API."""
    from jax._src import distributed as dist

    if getattr(dist, "_jax", None) is not None:
        return

    from jax._src.lib import xla_extension as xe

    _MISSABLE = 5   # timeout = interval x missable, matching new-API feel

    def _hb(heartbeat_timeout):
        if heartbeat_timeout is None:
            return {}
        return {"heartbeat_interval":
                max(1, int(heartbeat_timeout) // _MISSABLE),
                "max_missing_heartbeats": _MISSABLE}

    class _Adapter:
        @staticmethod
        def get_distributed_runtime_service(address, num_nodes,
                                            heartbeat_timeout=None, **kw):
            return xe.get_distributed_runtime_service(
                address, num_nodes, **_hb(heartbeat_timeout), **kw)

        @staticmethod
        def get_distributed_runtime_client(address, node_id,
                                           heartbeat_timeout=None,
                                           recoverable=None,
                                           missed_heartbeat_callback=None,
                                           **kw):
            del recoverable     # 0.4.37 clients predate the knob
            kwargs = dict(_hb(heartbeat_timeout), **kw)
            if missed_heartbeat_callback is not None:
                # old callback passes status only; the modern signature
                # adds coordinator_reported_failure — unknowable here
                kwargs["missed_heartbeat_callback"] = \
                    lambda status: missed_heartbeat_callback(status, False)
            return xe.get_distributed_runtime_client(address, node_id,
                                                     **kwargs)

    dist._jax = _Adapter()


def install() -> None:
    """Idempotently install every missing-API alias."""
    _install_shard_map()
    _install_axis_size()
    _install_distributed_runtime()


install()
