"""Checkpoint/resume: rank-0-save + broadcast-restore, off the train clock.

Reference (SURVEY §5.4): Horovod ships no checkpoint format; its
examples save on rank 0 only and restore with
``broadcast_variables``/``broadcast_optimizer_state``
(``examples/tensorflow2_keras_mnist.py``, ``tensorflow/functions.py:47``,
``torch/functions.py:30,62``).  This module packages that pattern with
an orbax backend (the TPU-native checkpoint store) and a msgpack/numpy
fallback — and, since the warm-start PR, takes serialization off the
training clock:

**Async snapshotting** (default): ``save()`` blocks only for the
device→host copy — the consistent cut; the arrays the train loop will
donate next step are copied out before ``save()`` returns — then
pickling, fsync and retention run on a background writer thread.
``wait()`` is the barrier: it re-raises writer errors, and ``save()``
calls it first so two writes never interleave (at steady state the
previous write has long finished and the barrier is free).

**Crash consistency**: a checkpoint file becomes visible only via
atomic ``os.replace`` after its bytes are fsynced, and the directory
entry is fsynced after the rename; a crash mid-write leaves only
``*.tmp*`` files, which every reader ignores and the next writer
removes.  The previous checkpoint is never touched until the new one
is durable (retention runs after the rename).

**Sharded (ZeRO) optimizer state** (PR 1 ``shard_optimizer_states``):
each rank owns 1/N of the flat fused state, so the rank-0-only rule
doesn't apply — :meth:`save_sharded` has every rank write its own
shard file and :meth:`restore_sharded` reassembles the full flat
buffer and re-slices it for the restoring world size, which may
differ (elastic resize).  The zero-padding the fusion spec adds is
preserved by construction (padded gradient tails are zero, so padded
state tails stay zero), so trimming/re-padding at a new world size is
exact.  See docs/warmstart.md.

::

    ckpt = hvd.checkpoint.Checkpointer("/tmp/run1")
    ckpt.save(step, {"params": params, "opt_state": opt_state})   # rank 0
    ckpt.wait()                                                   # barrier
    state = ckpt.restore_and_broadcast({"params": params, ...})   # all
"""

from __future__ import annotations

import os
import pickle
import threading
import time
from typing import Any, Optional

import jax
import numpy as np

from horovod_tpu import faults, telemetry
from horovod_tpu import functions as F
from horovod_tpu.utils import logging as hvd_logging

# save-plane telemetry (docs/metrics.md): dispatch counts, the blocking
# D2H-cut stall, the background write duration, and sticky writer errors
_TEL_SAVES = telemetry.counter(
    "hvd_checkpoint_saves_total", "checkpoint saves dispatched")
_TEL_STALL = telemetry.histogram(
    "hvd_checkpoint_stall_seconds",
    "train-loop blocking time of a save (the D2H consistent cut)")
_TEL_WRITE = telemetry.histogram(
    "hvd_checkpoint_write_seconds",
    "end-to-end background write duration (pickle+fsync+rename)")
_TEL_ERRORS = telemetry.counter(
    "hvd_checkpoint_writer_errors_total",
    "checkpoint writer-thread failures (sticky until clear_error)")


def _is_root() -> bool:
    return jax.process_index() == 0


def _host_copy(state: Any) -> Any:
    """The consistent cut: synchronous copy of every array leaf into
    host memory the snapshot OWNS.  After this returns, the snapshot is
    immune — the caller may overwrite its device buffers *and* its host
    arrays in place while the background writer pickles."""

    def _leaf(x):
        if isinstance(x, np.ndarray):
            # np.asarray would be a zero-copy alias here, breaking the
            # immune-after-return contract for host-resident state
            return x.copy()
        if hasattr(x, "shape"):
            a = np.asarray(x)
            # __array__ can be zero-copy too (CPU-backed jax arrays):
            # keep only memory we own
            return a if a.base is None and a.flags.owndata else a.copy()
        return x

    return jax.tree_util.tree_map(_leaf, state)


def _io_retry():
    """Writer-thread retry policy for transient storage errors (NFS
    hiccups, momentary ENOSPC): short exponential backoff under the
    unified ``HOROVOD_RETRY_*`` knobs, OSError only — a pickling error
    is a bug and must surface on the first attempt."""
    from horovod_tpu.runtime.retry import RetryPolicy

    return RetryPolicy(retry_on=(OSError,), name="checkpoint-io")


def _atomic_write(path: str, payload: Any) -> None:
    """Pickle ``payload`` to ``path`` durably: tmp file → fsync →
    atomic rename → fsync of the directory entry."""
    d = os.path.dirname(path)
    tmp = os.path.join(d, f".tmp.{os.path.basename(path)}.{os.getpid()}")
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dirfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass


class Checkpointer:
    """Directory-per-step checkpoints with an async writer thread.

    Replicated state is written by rank 0 only (the reference's
    "checkpoint on rank 0" rule); sharded state is written by every
    rank through :meth:`save_sharded`.  Uses orbax when available
    (``use_orbax=None`` autodetects); the fallback serializes the
    pytree's numpy leaves with pickle — same layout, no extra deps.

    ``async_save=False`` restores the old fully-synchronous behavior
    (save returns only when bytes are durable) — what the bench's
    ``checkpoint_sync_s`` reference number measures.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: Optional[bool] = None,
                 async_save: bool = True):
        self._dir = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        self._async = async_save
        self._writer: Optional[threading.Thread] = None
        # guards _writer_error: written by the writer thread, consumed
        # by wait()/clear_error() on the caller's thread.  wait()'s
        # join() already orders the steady-state handoff, but
        # clear_error() has no such edge — without the lock it can race
        # a writer failing mid-flight and acknowledge an error it never
        # returned to the caller.
        self._error_lock = threading.Lock()
        self._writer_error: Optional[BaseException] = None
        # steps pinned against retention (guardian "last-good" rollback
        # targets, docs/guardian.md).  Written by the caller thread,
        # read by _gc() on the writer thread — lock-guarded.
        self._pin_lock = threading.Lock()
        self._pins: set = set()
        # observability for the bench probe: the train-loop blocking
        # time of the last save (D2H cut only, async) and the last
        # end-to-end write duration (background, after wait())
        self.last_stall_s: Optional[float] = None
        self.last_write_s: Optional[float] = None
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                # orbax's CheckpointManager is collective: __init__ and
                # save() run global barriers over jax.distributed, which
                # deadlocks against this class's rank-0-only contract
                # (the reference's "checkpoint on rank 0" rule).  Use
                # orbax single-process; the pickle layout multi-process.
                use_orbax = jax.process_count() == 1
            except ImportError:
                use_orbax = False
        elif use_orbax and jax.process_count() > 1:
            raise ValueError(
                "use_orbax=True is not supported in multi-process runs: "
                "orbax's CheckpointManager is collective (global barriers "
                "in __init__/save) and this Checkpointer writes on rank 0 "
                "only — the job would deadlock at the first save. Leave "
                "use_orbax unset (the pickle layout is chosen "
                "automatically; reads remain layout-agnostic).")
        self._use_orbax = use_orbax
        self._manager = None
        os.makedirs(self._dir, exist_ok=True)
        if use_orbax and _is_root():
            import orbax.checkpoint as ocp

            self._manager = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))

    # -- async writer machinery ---------------------------------------------

    def wait(self) -> None:
        """Barrier: block until the pending background write (if any)
        is durable; re-raise any error it hit.  ``save()`` runs this
        first, so callers that never touch ``wait()`` still get the
        one-outstanding-write guarantee.

        A writer error is STICKY: every subsequent ``save()``/
        ``wait()``/``close()`` re-raises it until :meth:`clear_error`
        acknowledges it — a lost checkpoint must not be discoverable
        only by the one caller that happened to hit the barrier first
        (and silently absorbed by everyone after)."""
        w = self._writer
        if w is not None:
            w.join()
            self._writer = None
        with self._error_lock:
            err = self._writer_error
        if err is not None:
            raise err

    def clear_error(self) -> Optional[BaseException]:
        """Acknowledge (and return) the sticky writer error, unblocking
        further saves — the caller has decided how to proceed (retry
        the save, fail over to another directory, abort)."""
        with self._error_lock:
            err, self._writer_error = self._writer_error, None
        return err

    def close(self) -> None:
        """Final barrier: join any pending write and surface its error.
        A process that saves last and exits without ``wait()`` would
        otherwise swallow a failed final checkpoint (the non-daemon
        writer thread completes at interpreter shutdown, but nobody
        reads its error)."""
        self.wait()

    def _dispatch(self, fn) -> None:
        """Run ``fn`` on the writer thread (async) or inline (sync)."""

        def run():
            t0 = time.perf_counter()
            try:
                faults.inject("checkpoint.write")   # chaos hook
                fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at wait()
                _TEL_ERRORS.inc()
                with self._error_lock:
                    self._writer_error = e
            finally:
                self.last_write_s = time.perf_counter() - t0
                _TEL_WRITE.observe(self.last_write_s)

        if not self._async:
            run()
            with self._error_lock:
                # synchronous surfacing: the caller sees the error right
                # here, so it is consumed rather than left sticky
                err, self._writer_error = self._writer_error, None
            if err is not None:
                raise err
            return
        # non-daemon: a process exiting right after save() (last epoch,
        # worker retirement) joins the writer at interpreter shutdown
        # instead of truncating the write — durability over exit speed
        self._writer = threading.Thread(
            target=run, daemon=False, name="hvd_tpu_ckpt_writer")
        self._writer.start()

    # -- write --------------------------------------------------------------

    def save(self, step: int, state: Any) -> bool:
        """Write a checkpoint on rank 0; no-op elsewhere (the reference's
        "checkpoint on rank 0 only" rule).  Blocks only for the D2H
        copy when ``async_save`` (the default); durability is reached
        in the background and checkable via :meth:`wait`."""
        if not _is_root():
            return False
        self.wait()                       # one outstanding write, ever
        t0 = time.perf_counter()
        host_state = _host_copy(state)    # the consistent cut
        self.last_stall_s = time.perf_counter() - t0
        _TEL_SAVES.inc()
        _TEL_STALL.observe(self.last_stall_s)

        if self._manager is not None:
            def write():
                import orbax.checkpoint as ocp

                self._manager.save(step,
                                   args=ocp.args.StandardSave(host_state))
                self._manager.wait_until_finished()
                hvd_logging.info("checkpoint: saved step %d to %s",
                                 step, self._dir)
        else:
            def write():
                path = os.path.join(self._dir, f"step_{step}")
                os.makedirs(path, exist_ok=True)
                _io_retry().call(_atomic_write,
                                 os.path.join(path, "state.pkl"),
                                 host_state)
                self._gc()
                hvd_logging.info("checkpoint: saved step %d to %s",
                                 step, self._dir)

        self._dispatch(write)
        return True

    def save_sharded(self, step: int, shard_state: Any,
                     shard_rank: int, shard_count: int,
                     plan: Any = None) -> bool:
        """Write THIS rank's 1/N shard of a sharded (ZeRO) state tree.

        Every rank calls this with its own ``shard_state`` — the
        per-rank optimizer state of ``shard_optimizer_states=True``
        (flat ``(shard,)`` leaves keyed by fusion group).  Same async
        contract as :meth:`save`: blocks for the D2H copy only.  The
        step is complete once all ``shard_count`` files exist —
        :meth:`restore_sharded` verifies that.

        ``plan`` (a :class:`~horovod_tpu.parallel.plan.ShardingPlan` or
        grammar string) stamps the parallelism plan the state was
        trained under into every shard payload, letting
        :meth:`restore_sharded` reshard across *plan* changes — the
        data extent (dp×fsdp) may change freely, and so may ``sp``:
        sequence parallelism shards *activations*, not parameters, so
        for the saved state sp is data-free and the flat-buffer reshard
        covers it.  A changed model-parallel factorization (pp/ep/tp)
        is refused there instead of silently mis-slicing
        (docs/parallelism.md)."""
        if not 0 <= shard_rank < shard_count:
            raise ValueError(
                f"shard_rank {shard_rank} out of range for "
                f"shard_count {shard_count}")
        plan_str = _canonical_plan(plan, shard_count)
        self.wait()
        t0 = time.perf_counter()
        host_state = _host_copy(shard_state)
        self.last_stall_s = time.perf_counter() - t0
        _TEL_SAVES.inc()
        _TEL_STALL.observe(self.last_stall_s)

        def write():
            path = os.path.join(self._dir, f"step_{step}")
            os.makedirs(path, exist_ok=True)
            payload = {"shard_rank": shard_rank,
                       "shard_count": shard_count,
                       "state": host_state}
            if plan_str is not None:
                payload["plan"] = plan_str
            _io_retry().call(
                _atomic_write,
                os.path.join(path, _shard_name(shard_rank, shard_count)),
                payload)
            hvd_logging.info(
                "checkpoint: saved shard %d/%d of step %d to %s",
                shard_rank, shard_count, step, self._dir)

        self._dispatch(write)
        return True

    def pin(self, step: int) -> None:
        """Exempt ``step`` from retention until :meth:`unpin`.

        The guardian's rollback contract (docs/guardian.md): between
        anomaly detection and restore, further saves may push the
        last-good step past ``max_to_keep`` — a pinned step can never be
        reaped in that window.  Pins cover the pickle layout (the
        multi-process production writer); the orbax manager owns its own
        retention."""
        with self._pin_lock:
            self._pins.add(int(step))

    def unpin(self, step: int) -> None:
        """Release a :meth:`pin`; the step rejoins normal retention on
        the next save's GC pass."""
        with self._pin_lock:
            self._pins.discard(int(step))

    def pinned_steps(self) -> list:
        with self._pin_lock:
            return sorted(self._pins)

    def _gc(self) -> None:
        # rank retention over the pickle layout only — mixing in orbax
        # step numbers could delete a just-written pickle step while
        # never pruning the (manager-owned) orbax dirs
        steps = sorted(self._pickle_steps())
        with self._pin_lock:
            pins = set(self._pins)
        for s in steps[:-self._max_to_keep]:
            if s in pins:     # a rollback target is never reaped
                continue
            import shutil

            shutil.rmtree(os.path.join(self._dir, f"step_{s}"),
                          ignore_errors=True)

    # -- read ---------------------------------------------------------------

    def all_steps(self) -> list:
        """Steps present on disk, in EITHER layout.  The write format
        depends on availability and process count, but a run resumed or
        evaluated with a different process count must still find its
        existing checkpoints — reads are layout-agnostic.  Only steps
        with at least one finalized (non-tmp) payload file count, so a
        crash mid-first-write never surfaces an empty step."""
        self.wait()   # read-your-writes: surface our own pending save
        if not os.path.isdir(self._dir):
            return []
        steps = set(self._pickle_steps())
        if self._manager is not None:
            steps.update(int(s) for s in self._manager.all_steps())
        else:
            # Non-root ranks / pickle writers still list orbax-finalized
            # steps (checkpoint_steps only reports finalized ones, so a
            # reader can never pick a step rank 0 is mid-writing).
            try:
                from orbax.checkpoint import utils as ocp_utils

                # only steps living in orbax's plain-digit layout: the
                # pickle layout's step_N dirs must not round-trip through
                # orbax's scanner, which would resurface an incomplete
                # (crash-torso) pickle step _pickle_steps just filtered
                steps.update(
                    int(s) for s in ocp_utils.checkpoint_steps(self._dir)
                    if os.path.isdir(os.path.join(self._dir, str(int(s)))))
            except ImportError:
                pass
        return sorted(steps)

    def _pickle_steps(self) -> list:
        out = []
        for d in os.listdir(self._dir):
            if not (d.startswith("step_") and d.split("_", 1)[1].isdigit()):
                continue
            full = os.path.join(self._dir, d)
            try:
                final = [n for n in os.listdir(full)
                         if n.endswith(".pkl") and not n.startswith(".tmp")]
            except NotADirectoryError:
                continue
            if final:
                out.append(int(d.split("_", 1)[1]))
        return out

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Load a checkpoint on this process (every rank reads — use
        :meth:`restore_and_broadcast` for the read-once pattern)."""
        self.wait()
        if step is None:
            step = self._resolve_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        # layout-agnostic: read whichever format holds this step
        step_dir = os.path.join(self._dir, f"step_{step}")
        pkl = os.path.join(step_dir, "state.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                return pickle.load(f)
        if os.path.isdir(step_dir) and any(
                n.startswith("shard_") and n.endswith(".pkl")
                for n in os.listdir(step_dir)):
            # don't fall through to orbax: the step exists but holds
            # per-rank shard files, which only restore_sharded can read
            raise ValueError(
                f"step {step} in {self._dir} was written by "
                f"save_sharded() (per-rank shard files, no replicated "
                f"state.pkl) — use restore_sharded(target, shard_rank, "
                f"shard_count) to read it")
        if step not in self.all_steps():
            raise FileNotFoundError(
                f"no checkpoint for step {step} in {self._dir} "
                f"(available: {self.all_steps()})")
        import orbax.checkpoint as ocp

        host_target = _host_copy(target)
        if self._manager is not None and \
                step in set(self._manager.all_steps()):
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(host_target))
        # Non-root / cross-layout: plain per-host read of the shared
        # directory; no cross-host coordination needed for a restore.
        # Layout is the manager's: <dir>/<step>/default.
        return ocp.StandardCheckpointer().restore(
            os.path.join(self._dir, str(step), "default"), host_target)

    def saved_plan(self, step: Optional[int] = None) -> Optional[str]:
        """The parallelism plan stamped into ``step``'s sharded
        checkpoint (``save_sharded`` ``plan=``), or None when the step
        holds no shard files or an unstamped legacy one.  The degrade
        resolver reads this before a transition: the restoring plan's
        model extent must match the stamp or ``restore_sharded`` will
        refuse (elastic/degrade.py, docs/elastic.md)."""
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self._dir, f"step_{step}")
        try:
            shards = _load_shards(path)
        except (FileNotFoundError, ValueError):
            return None
        return shards[0].get("plan")

    def restore_sharded(self, target: Any, shard_rank: int,
                        shard_count: int,
                        step: Optional[int] = None,
                        plan: Any = None) -> Any:
        """Rebuild THIS rank's shard of a sharded state saved at any
        world size — or under any *plan* with the same model-parallel
        factorization.

        The saved shards concatenate back into the full flat buffer
        (padded to the *saving* world's multiple); ``target``'s leaf
        shapes define the *restoring* world's shard sizes, so the
        buffer is re-padded (or pad-trimmed — the tail is zeros by the
        fusion-spec invariant) to ``shard * shard_count`` and re-sliced
        at ``shard_rank``.  Scalar leaves (optimizer step counters) are
        replicated across shards; the saving rank 0's value wins.

        ``plan`` names the *restoring* run's plan.  When the checkpoint
        carries a saved plan (:meth:`save_sharded` ``plan=``), the
        model-parallel extents (pp/ep/tp) must match — those change
        the parameter tensors themselves, which no flat-buffer reshard
        can fix — while the data extent (dp×fsdp) *and* the sp extent
        reshard exactly like a world-size change: sp shards the
        sequence (activations), so every sp rank holds the same
        parameter/optimizer values and the exchange treats sp as one
        more data axis (docs/parallelism.md)."""
        self.wait()
        if step is None:
            step = self._resolve_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        path = os.path.join(self._dir, f"step_{step}")
        shards = _load_shards(path)
        plan_str = _canonical_plan(plan, shard_count)
        saved_plan = shards[0].get("plan")
        if saved_plan is not None and plan_str is not None:
            _check_plan_reshard(saved_plan, plan_str, path)
        saved_trees = [s["state"] for s in shards]
        t_leaves, treedef = jax.tree_util.tree_flatten(target)
        shard_leaves = [jax.tree_util.tree_flatten(t)[0]
                        for t in saved_trees]
        if any(len(sl) != len(t_leaves) for sl in shard_leaves):
            raise ValueError(
                f"sharded checkpoint at {path} has a different tree "
                f"structure than the restore target")
        out = []
        for i, t in enumerate(t_leaves):
            saved = [sl[i] for sl in shard_leaves]
            out.append(_reshard_leaf(t, saved, shard_rank, shard_count))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _resolve_step(self) -> Optional[int]:
        """Pick the latest step, agreed across ranks.

        Collective when multi-process (every rank must call it): root's
        directory listing is broadcast, because per-rank listings can lag
        on shared filesystems and ranks silently restoring different steps
        is worse than any error.
        """
        if jax.process_count() == 1:
            return self.latest_step()
        from horovod_tpu.ops import eager

        mine = self.latest_step() if _is_root() else None
        step = int(eager.broadcast(
            np.asarray([-1 if mine is None else mine], np.int32),
            root_rank=0, name="ckpt_latest_step")[0])
        return None if step < 0 else step

    def restore_and_broadcast(self, target: Any,
                              step: Optional[int] = None,
                              root_rank: int = 0) -> Any:
        """Rank 0 reads from storage, everyone else receives via broadcast
        (reference restore + ``broadcast_variables`` recipe) — one storage
        read per job instead of N."""
        if jax.process_count() == 1:
            return self.restore(target, step)
        # resolve the step on ALL ranks first: restore() below runs on root
        # only, so its internal collective resolution must not trigger
        if step is None:
            step = self._resolve_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        if _is_root():
            state = self.restore(target, step)
        else:
            state = target
        return F.broadcast_variables(state, root_rank=root_rank,
                                     name="checkpoint_restore")


def _shard_name(rank: int, count: int) -> str:
    return f"shard_{rank}_of_{count}.pkl"


def _canonical_plan(plan: Any, shard_count: int) -> Optional[str]:
    """Canonical plan string for shard payloads, validated against the
    exchange width: the sharded state shards over the plan's data axes,
    so a plan whose dp×fsdp disagrees with ``shard_count`` would stamp
    a lie into the checkpoint."""
    if plan is None:
        return None
    from horovod_tpu.parallel.plan import as_plan

    p = as_plan(plan)
    if p.dp is not None:
        # sp counts: sequence parallelism shards activations, not
        # parameters, so the sharded state spreads over dp×fsdp×sp
        # ranks (sp joined the exchange scope in the train step)
        data_extent = p.dp * p.fsdp * p.sp
        if data_extent != shard_count:
            raise ValueError(
                f"plan {p.to_string()} shards the exchange over "
                f"dp*fsdp*sp={data_extent} ranks, but shard_count is "
                f"{shard_count}")
    return p.to_string(allow_unresolved=True)


def _check_plan_reshard(saved: str, restoring: str, path: str) -> None:
    """Refuse cross-plan restores that change the model-parallel
    factorization: pp/ep/tp extents reshape the parameter tensors
    themselves, so the flat-buffer reshard of :func:`_reshard_leaf`
    would slice garbage.  Data-extent (dp/fsdp), ``sp`` (sequence
    parallelism shards activations — parameters are identical on every
    sp rank, so for the saved state sp is just more data extent) and
    virtual-stage changes reshard fine."""
    from horovod_tpu.parallel.plan import ShardingPlan

    sp = ShardingPlan.from_string(saved.replace("dp=?", "dp=1")
                                  if "dp=?" in saved else saved)
    rp = ShardingPlan.from_string(restoring.replace("dp=?", "dp=1")
                                  if "dp=?" in restoring else restoring)
    model_axes = ("pp", "ep", "tp")
    mismatch = [ax for ax in model_axes
                if getattr(sp, ax) != getattr(rp, ax)]
    if mismatch:
        raise ValueError(
            f"sharded checkpoint in {path} was saved under plan "
            f"{saved!r} but the restore runs plan {restoring!r}: "
            f"model-parallel extents differ on {mismatch} — resharding "
            f"only covers data-extent (dp/fsdp/sp) changes; "
            f"re-partition the model to change pp/ep/tp")


def _load_shards(path: str) -> list:
    """All shard payloads of one step, ordered by shard rank; validates
    the set is complete and from one world size."""
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no checkpoint directory {path}")
    names = [n for n in os.listdir(path)
             if n.startswith("shard_") and n.endswith(".pkl")
             and not n.startswith(".tmp")]
    if not names:
        raise FileNotFoundError(f"no shard files in {path}")
    payloads = []
    for n in sorted(names):
        with open(os.path.join(path, n), "rb") as f:
            payloads.append(pickle.load(f))
    counts = {p["shard_count"] for p in payloads}
    if len(counts) != 1:
        raise ValueError(
            f"mixed shard_count values {sorted(counts)} in {path} — "
            f"partial overwrite from two world sizes?")
    count = counts.pop()
    ranks = sorted(p["shard_rank"] for p in payloads)
    if ranks != list(range(count)):
        missing = sorted(set(range(count)) - set(ranks))
        raise FileNotFoundError(
            f"incomplete sharded checkpoint in {path}: missing shard(s) "
            f"{missing} of {count}")
    payloads.sort(key=lambda p: p["shard_rank"])
    return payloads


def _reshard_leaf(target, saved: list, shard_rank: int, shard_count: int):
    """One leaf's re-shard: concat the saved per-rank pieces, fix the
    padded length to the restoring world's, slice this rank's piece."""
    if not hasattr(target, "shape") or np.ndim(target) == 0:
        # replicated scalar (e.g. optax count): saving rank 0's value
        return saved[0]
    t_shape = tuple(np.shape(target))
    s0 = np.asarray(saved[0])
    if tuple(s0.shape) == t_shape and len(saved) == shard_count:
        # same world size: this rank's own shard, no reassembly
        return saved[shard_rank]
    if s0.ndim != 1 or len(t_shape) != 1:
        raise ValueError(
            f"cannot re-shard a non-flat leaf of shape {s0.shape} to "
            f"{t_shape}: sharded state leaves are 1-D fusion-buffer "
            f"slices (shard_optimizer_states contract)")
    full = np.concatenate([np.asarray(s) for s in saved])
    new_padded = t_shape[0] * shard_count
    if new_padded < full.shape[0]:
        # the fusion spec pads with zeros and padded gradient tails are
        # zero, so state tails are zero — trimming drops only padding
        tail = full[new_padded:]
        if np.any(tail != 0):
            raise ValueError(
                "re-shard would trim non-zero state: the restore "
                f"target's padded length {new_padded} is shorter than "
                f"the saved buffer {full.shape[0]} and the excess is "
                "not fusion padding")
        full = full[:new_padded]
    elif new_padded > full.shape[0]:
        full = np.concatenate([
            full, np.zeros((new_padded - full.shape[0],), full.dtype)])
    shard = full.shape[0] // shard_count
    return full[shard_rank * shard:(shard_rank + 1) * shard]
