"""Checkpoint/resume: the rank-0-save + broadcast-restore pattern.

Reference (SURVEY §5.4): Horovod ships no checkpoint format; its
examples save on rank 0 only and restore with
``broadcast_variables``/``broadcast_optimizer_state``
(``examples/tensorflow2_keras_mnist.py``, ``tensorflow/functions.py:47``,
``torch/functions.py:30,62``).  This module packages that pattern with
an orbax backend (the TPU-native checkpoint store, async-capable) and a
msgpack/numpy fallback.

::

    ckpt = hvd.checkpoint.Checkpointer("/tmp/run1")
    ckpt.save(step, {"params": params, "opt_state": opt_state})   # rank 0
    state = ckpt.restore_and_broadcast({"params": params, ...})   # all
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

from horovod_tpu import functions as F
from horovod_tpu.utils import logging as hvd_logging


def _is_root() -> bool:
    return jax.process_index() == 0


class Checkpointer:
    """Directory-per-step checkpoints, written by rank 0 only.

    Uses orbax when available (``use_orbax=None`` autodetects); the
    fallback serializes the pytree's numpy leaves with pickle — same
    layout, no extra deps.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 use_orbax: Optional[bool] = None):
        self._dir = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        if use_orbax is None:
            try:
                import orbax.checkpoint  # noqa: F401

                # orbax's CheckpointManager is collective: __init__ and
                # save() run global barriers over jax.distributed, which
                # deadlocks against this class's rank-0-only contract
                # (the reference's "checkpoint on rank 0" rule).  Use
                # orbax single-process; the pickle layout multi-process.
                use_orbax = jax.process_count() == 1
            except ImportError:
                use_orbax = False
        elif use_orbax and jax.process_count() > 1:
            raise ValueError(
                "use_orbax=True is not supported in multi-process runs: "
                "orbax's CheckpointManager is collective (global barriers "
                "in __init__/save) and this Checkpointer writes on rank 0 "
                "only — the job would deadlock at the first save. Leave "
                "use_orbax unset (the pickle layout is chosen "
                "automatically; reads remain layout-agnostic).")
        self._use_orbax = use_orbax
        self._manager = None
        if _is_root():
            os.makedirs(self._dir, exist_ok=True)
        if use_orbax and _is_root():
            import orbax.checkpoint as ocp

            self._manager = ocp.CheckpointManager(
                self._dir,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, create=True))

    # -- write (rank 0) -----------------------------------------------------

    def save(self, step: int, state: Any) -> bool:
        """Write a checkpoint on rank 0; no-op elsewhere (the reference's
        "checkpoint on rank 0 only" rule)."""
        if not _is_root():
            return False
        host_state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x, state)
        if self._manager is not None:
            import orbax.checkpoint as ocp

            self._manager.save(step, args=ocp.args.StandardSave(host_state))
            self._manager.wait_until_finished()
        else:
            path = os.path.join(self._dir, f"step_{step}")
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "state.pkl"), "wb") as f:
                pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
            self._gc()
        hvd_logging.info("checkpoint: saved step %d to %s", step, self._dir)
        return True

    def _gc(self) -> None:
        # rank retention over the pickle layout only — mixing in orbax
        # step numbers could delete a just-written pickle step while
        # never pruning the (manager-owned) orbax dirs
        steps = sorted(self._pickle_steps())
        for s in steps[:-self._max_to_keep]:
            import shutil

            shutil.rmtree(os.path.join(self._dir, f"step_{s}"),
                          ignore_errors=True)

    # -- read ---------------------------------------------------------------

    def all_steps(self) -> list:
        """Steps present on disk, in EITHER layout.  The write format
        depends on availability and process count, but a run resumed or
        evaluated with a different process count must still find its
        existing checkpoints — reads are layout-agnostic."""
        if not os.path.isdir(self._dir):
            return []
        steps = set(self._pickle_steps())
        if self._manager is not None:
            steps.update(int(s) for s in self._manager.all_steps())
        else:
            # Non-root ranks / pickle writers still list orbax-finalized
            # steps (checkpoint_steps only reports finalized ones, so a
            # reader can never pick a step rank 0 is mid-writing).
            try:
                from orbax.checkpoint import utils as ocp_utils

                steps.update(int(s)
                             for s in ocp_utils.checkpoint_steps(self._dir))
            except ImportError:
                pass
        return sorted(steps)

    def _pickle_steps(self) -> list:
        return [int(d.split("_", 1)[1]) for d in os.listdir(self._dir)
                if d.startswith("step_") and d.split("_", 1)[1].isdigit()]

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return max(steps) if steps else None

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Load a checkpoint on this process (every rank reads — use
        :meth:`restore_and_broadcast` for the read-once pattern)."""
        if step is None:
            step = self._resolve_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        # layout-agnostic: read whichever format holds this step
        pkl = os.path.join(self._dir, f"step_{step}", "state.pkl")
        if os.path.exists(pkl):
            with open(pkl, "rb") as f:
                return pickle.load(f)
        if step not in self.all_steps():
            raise FileNotFoundError(
                f"no checkpoint for step {step} in {self._dir} "
                f"(available: {self.all_steps()})")
        import orbax.checkpoint as ocp

        host_target = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if hasattr(x, "shape") else x,
            target)
        if self._manager is not None and \
                step in set(self._manager.all_steps()):
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(host_target))
        # Non-root / cross-layout: plain per-host read of the shared
        # directory; no cross-host coordination needed for a restore.
        # Layout is the manager's: <dir>/<step>/default.
        return ocp.StandardCheckpointer().restore(
            os.path.join(self._dir, str(step), "default"), host_target)

    def _resolve_step(self) -> Optional[int]:
        """Pick the latest step, agreed across ranks.

        Collective when multi-process (every rank must call it): root's
        directory listing is broadcast, because per-rank listings can lag
        on shared filesystems and ranks silently restoring different steps
        is worse than any error.
        """
        if jax.process_count() == 1:
            return self.latest_step()
        from horovod_tpu.ops import eager

        mine = self.latest_step() if _is_root() else None
        step = int(eager.broadcast(
            np.asarray([-1 if mine is None else mine], np.int32),
            root_rank=0, name="ckpt_latest_step")[0])
        return None if step < 0 else step

    def restore_and_broadcast(self, target: Any,
                              step: Optional[int] = None,
                              root_rank: int = 0) -> Any:
        """Rank 0 reads from storage, everyone else receives via broadcast
        (reference restore + ``broadcast_variables`` recipe) — one storage
        read per job instead of N."""
        if jax.process_count() == 1:
            return self.restore(target, step)
        # resolve the step on ALL ranks first: restore() below runs on root
        # only, so its internal collective resolution must not trigger
        if step is None:
            step = self._resolve_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self._dir}")
        if _is_root():
            state = self.restore(target, step)
        else:
            state = target
        return F.broadcast_variables(state, root_rank=root_rank,
                                     name="checkpoint_restore")
