"""Deterministic fault injection for the elastic runtime (docs/faults.md).

The elastic plane's failure handling — heartbeat death detection, host
quarantine, checkpoint recovery, retry/backoff — is only trustworthy if
it can be *demonstrated*, repeatedly, without waiting for real hardware
to fail.  This package provides the chaos half of that contract: a
seeded :class:`FaultPlan` schedules named faults (crash at step k, hang,
transient ``OSError``, slow host, discovery flap) that fire through
explicit :func:`inject` hooks placed at the runtime's failure-relevant
sites — the elastic worker loop, the driver discovery loop, the
checkpoint writer thread, the data prefetch feeder, worker registration
and the coordinator connect path.

With no plan active every hook is a near-zero-cost no-op (one global
``None`` check), so production code paths carry no chaos overhead.

Sites currently instrumented (grep ``faults.inject`` for ground truth):

==========================  =================================================
``worker.commit``           elastic ``State.commit()`` — once per train step
``worker.register``         worker → driver registration/READY report
``worker.heartbeat``        each heartbeat send in the worker sender thread
``worker.rendezvous``       ``refresh_assignment_from_driver`` RPC
``coordinator.connect``     elastic coordination-service client connect
``driver.discovery``        each driver discovery-loop pass
``discovery.script``        each discovery-script execution
``checkpoint.write``        the checkpoint writer (thread) before the write
``data.feed``               prefetch feeder, once per source batch
``driver.health``           each health-monitor watch pass (driver thread)
``stall.watch``             each stall-inspector poll pass
``timeline.write``          timeline writer thread, once per event
``probe.connect``           NIC-probe task → driver connect scan
``telemetry.export``        metrics snapshot writer, once per export pass
``guard.params``            guardian replica-checksum pass — the ``corrupt``
                            action's SDC point (once per check interval)
``guard.check``             each guardian check pass (numerics + checksum)
``worker.preempt``          preemption handler drain → commit → notify path
``guard.repair``            peer state fetch in the guard repair path
``serve.batch``             replica batch execution — ``crash`` models a
                            replica dying mid-batch (lease re-enqueues)
``serve.feed``              each continuous-batcher engine step — ``hang``
                            models a wedged queue feeder
``serve.drain``             replica drain completion — ``raise``/``hang``
                            models a drain wedged past its grace window
``serve.tenant``            each weighted-fair scheduler pick over the
                            tenant queues (serve/tenancy.py)
``serve.refresh``           each live weight-flip attempt — ``corrupt``
                            tampers the staged tree and must be caught by
                            the fingerprint verify (rollback path)
``serve.scale``             each autoscale controller poll
                            (serve/autoscale.py)
``degrade.resolve``         each degraded-plan resolution verdict
                            (elastic/degrade.py)
``degrade.reshard``         degrade-transition reshard restore, before any
                            shard is read — the transition's fragile point
``elastic.promote``         plan promotion back toward the base plan when
                            capacity returns
``offload.d2h``             host-offload D2H copy (worker thread) — a fault
                            degrades to the retained device state
``offload.h2d``             host-offload H2D restore in ``fetch()`` — same
                            degrade contract (memory/offload.py)
==========================  =================================================

(Coverage is enforced statically: hvdlint rule HVD006 fails on any
thread run-loop or connect path without an ``inject`` site, so this
table can only grow with the runtime — see docs/analysis.md.)

Typical use::

    plan = FaultPlan(seed=42, sim=True).add("worker.commit", "crash", at=7)
    faults.set_plan(plan)

or, for a launched job::

    HOROVOD_FAULT_PLAN="seed=42;worker.commit@7:crash;data.feed@3:delay(0.5)"
"""

from horovod_tpu.faults.plan import (
    FaultPlan,
    FaultSpec,
    WorkerCrash,
    active_plan,
    clear_plan,
    inject,
    load_env_plan,
    set_plan,
)

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "WorkerCrash",
    "active_plan",
    "clear_plan",
    "inject",
    "load_env_plan",
    "set_plan",
]
