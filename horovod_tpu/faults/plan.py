"""Seeded fault plans and the ``inject()`` hook (docs/faults.md).

Grammar (``HOROVOD_FAULT_PLAN``, ``;``-separated clauses)::

    seed=SEED                      deterministic RNG seed for ?prob draws
    mode=sim                       crash raises WorkerCrash instead of
                                   os._exit (in-process chaos tests)
    SITE[@HIT][:ACTION[(ARG)]][xCOUNT][?PROB]

``SITE`` is a dotted site name (see package docstring for the
instrumented sites).  ``@HIT`` is the 1-based hit index at which the
fault first fires (default 1); ``xCOUNT`` fires it on that many
consecutive hits (``x*`` = every hit from ``@HIT`` on); ``?PROB``
makes each eligible hit fire with probability PROB, decided by an RNG
seeded from ``(seed, site, hit)`` so the outcome is a pure function of
the plan — independent of thread interleaving across sites.

Actions:

=================  ==========================================================
``crash[(code)]``  ``os._exit(code)`` (default 173), or raise
                   :class:`WorkerCrash` in ``sim`` mode — worker dies at
                   step k
``hang[(s)]``      block for ``s`` seconds (default 3600) — alive but
                   making no progress; interruptible via ``plan.cancel()``
``raise[(Exc)]``   raise the named exception (default ``RuntimeError``);
                   supported names: OSError, IOError, TimeoutError,
                   ConnectionRefusedError, ConnectionResetError,
                   RuntimeError, ValueError, CalledProcessError,
                   TimeoutExpired
``delay[(s)]``     sleep ``s`` seconds (default 1.0) then continue — the
                   slow-host fault
``value[(v)]``     return ``v`` from ``inject()`` — the call site defines
                   the semantics (e.g. a discovery flap)
``corrupt[(s)]``   return a deterministically perturbed copy of the value
                   passed to ``inject(site, value=...)`` — one element of
                   one array leaf gets ``+= s * (1 + |x|)`` (default
                   ``s=1.0``), with leaf and element chosen by an RNG
                   seeded from ``(seed, site, hit)``.  The silent-data-
                   corruption fault (docs/guardian.md); with no value
                   passed, returns ``s`` itself
=================  ==========================================================
"""

from __future__ import annotations

import os
import random
import subprocess
import threading
import time
from typing import Any, List, Optional, Tuple

from horovod_tpu.utils import logging as hvd_logging

_DEFAULT_CRASH_CODE = 173   # distinguishable from generic exit 1

_EXCEPTIONS = {
    "OSError": OSError,
    "IOError": OSError,
    "TimeoutError": TimeoutError,
    "ConnectionRefusedError": ConnectionRefusedError,
    "ConnectionResetError": ConnectionResetError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
}


class WorkerCrash(BaseException):
    """Simulated process death (``mode=sim`` crash action).

    Derives from ``BaseException`` so ordinary ``except Exception``
    recovery code cannot accidentally absorb a "process death" — only a
    chaos harness that asks for it catches it, matching how a real
    ``os._exit`` is invisible to in-process handlers."""

    def __init__(self, site: str, hit: int, code: int = _DEFAULT_CRASH_CODE):
        super().__init__(f"injected crash at {site} (hit {hit}), "
                         f"exit code {code}")
        self.site = site
        self.hit = hit
        self.code = code


class FaultSpec:
    """One scheduled fault: fire ``action`` at ``site`` on hits
    ``[at, at + count)`` (``count=-1`` = forever), each eligible hit
    firing with probability ``prob``."""

    __slots__ = ("site", "action", "arg", "at", "count", "prob")

    def __init__(self, site: str, action: str = "raise",
                 arg: Any = None, at: int = 1, count: int = 1,
                 prob: float = 1.0):
        if action not in ("crash", "hang", "raise", "delay", "value",
                          "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        if at < 1:
            raise ValueError(f"fault hit index must be >= 1, got {at}")
        self.site = site
        self.action = action
        self.arg = arg
        self.at = int(at)
        self.count = int(count)
        self.prob = float(prob)

    def covers(self, hit: int) -> bool:
        if hit < self.at:
            return False
        return self.count < 0 or hit < self.at + self.count

    def __repr__(self):
        return (f"FaultSpec({self.site}@{self.at}:{self.action}"
                f"({self.arg}) x{self.count} ?{self.prob})")


class FaultPlan:
    """A deterministic, seeded schedule of faults.

    ``sim=True`` turns the ``crash`` action into a raised
    :class:`WorkerCrash` instead of ``os._exit`` — the in-process chaos
    harness mode.  All counters are per-site hit counts; the plan keeps
    a ``fired`` audit log ``(site, hit, action)`` for tests and the
    bench ``--chaos`` probe."""

    def __init__(self, specs: Optional[List[FaultSpec]] = None,
                 seed: int = 0, sim: bool = False):
        self.seed = int(seed)
        self.sim = bool(sim)
        self._specs: List[FaultSpec] = list(specs or [])
        self._hits = {}
        self._fired: List[Tuple[str, int, str]] = []
        self._lock = threading.Lock()
        self._cancel = threading.Event()

    # -- construction -------------------------------------------------------

    def add(self, site: str, action: str = "raise", arg: Any = None,
            at: int = 1, count: int = 1, prob: float = 1.0) -> "FaultPlan":
        self._specs.append(FaultSpec(site, action, arg, at, count, prob))
        return self

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse the ``HOROVOD_FAULT_PLAN`` grammar (module docstring)."""
        plan = cls()
        for raw in text.split(";"):
            clause = raw.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                plan.seed = int(clause[5:])
                continue
            if clause.startswith("mode="):
                mode = clause[5:].strip().lower()
                if mode not in ("sim", "process"):
                    raise ValueError(f"fault plan mode must be sim or "
                                     f"process, got {mode!r}")
                plan.sim = mode == "sim"
                continue
            plan._specs.append(_parse_clause(clause))
        return plan

    # -- firing -------------------------------------------------------------

    @property
    def specs(self) -> List[FaultSpec]:
        return list(self._specs)

    @property
    def fired(self) -> List[Tuple[str, int, str]]:
        with self._lock:
            return list(self._fired)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def cancel(self) -> None:
        """Unblock any in-progress ``hang``/``delay`` waits (teardown)."""
        self._cancel.set()

    def inject(self, site: str, value: Any = None) -> Any:
        """One hit at ``site``: fire every matching spec.  Returns the
        ``value`` action's arg or the ``corrupt`` action's perturbed
        copy of ``value`` (last one wins) or None."""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            due = [s for s in self._specs
                   if s.site == site and s.covers(hit)]
            due = [s for s in due if self._drawn(s, site, hit)]
            for s in due:
                self._fired.append((site, hit, s.action))
        out = None
        for s in due:
            res = self._fire(s, site, hit, value)
            if s.action in ("value", "corrupt"):
                out = res
        return out

    def _drawn(self, spec: FaultSpec, site: str, hit: int) -> bool:
        if spec.prob >= 1.0:
            return True
        # seeded per (plan seed, site, hit): a pure function of the
        # plan, independent of cross-site call interleaving
        rng = random.Random(f"{self.seed}:{site}:{hit}")
        return rng.random() < spec.prob

    def _fire(self, spec: FaultSpec, site: str, hit: int,
              value: Any = None) -> Any:
        hvd_logging.warning("faults: firing %s at %s (hit %d)",
                            spec.action, site, hit)
        # telemetry is imported lazily: telemetry.export imports this
        # package for its chaos hook, and _fire only runs under an
        # active plan — never on the production no-op path
        from horovod_tpu import telemetry

        telemetry.counter(
            "hvd_faults_injected_total",
            "chaos faults fired by the active plan").inc(
                site=site, action=spec.action)
        if spec.action == "crash":
            code = int(spec.arg) if spec.arg is not None \
                else _DEFAULT_CRASH_CODE
            if self.sim:
                raise WorkerCrash(site, hit, code)
            os._exit(code)
        if spec.action == "hang":
            seconds = float(spec.arg) if spec.arg is not None else 3600.0
            self._cancel.wait(seconds)
            return None
        if spec.action == "delay":
            seconds = float(spec.arg) if spec.arg is not None else 1.0
            # short sleeps use time.sleep (the cancel event costs ~50 us
            # per wait); long delays stay interruptible
            if seconds > 5.0:
                self._cancel.wait(seconds)
            else:
                time.sleep(seconds)
            return None
        if spec.action == "raise":
            raise _make_exception(spec.arg, site, hit)
        if spec.action == "corrupt":
            scale = float(spec.arg) if spec.arg is not None else 1.0
            return _corrupt_value(value, scale, self.seed, site, hit)
        return spec.arg       # "value"


def _corrupt_value(value: Any, scale: float, seed: int, site: str,
                   hit: int) -> Any:
    """Deterministic single-element perturbation of an array pytree.

    Leaf and flat index are drawn from an RNG seeded on
    ``(seed, site, hit)`` — a pure function of the plan, so two runs of
    the same plan corrupt the same element by the same amount.  The
    perturbation ``x += scale * (1 + |x|)`` moves the element whether or
    not it is near zero, and preserves the leaf's dtype."""
    if value is None:
        return scale
    # lazy: only the corrupt action needs array machinery, and _fire
    # never runs on the production no-plan path
    import jax
    import numpy as np

    rng = random.Random(f"{seed}:{site}:{hit}:corrupt")
    leaves, treedef = jax.tree_util.tree_flatten(value)
    eligible = [i for i, leaf in enumerate(leaves)
                if hasattr(leaf, "shape") and getattr(leaf, "size", 0)]
    if not eligible:
        return value
    li = eligible[rng.randrange(len(eligible))]
    leaf = np.array(leaves[li])          # host copy, original untouched
    flat = leaf.reshape(-1)
    j = rng.randrange(flat.size)
    x = float(flat[j])
    flat[j] = np.asarray(x + scale * (1.0 + abs(x)), dtype=leaf.dtype)
    leaves[li] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _make_exception(name: Optional[str], site: str, hit: int) -> BaseException:
    msg = f"injected fault at {site} (hit {hit})"
    if name is None:
        return RuntimeError(msg)
    if name == "CalledProcessError":
        return subprocess.CalledProcessError(1, f"fault:{site}")
    if name == "TimeoutExpired":
        return subprocess.TimeoutExpired(f"fault:{site}", 1.0)
    try:
        return _EXCEPTIONS[name](msg)
    except KeyError:
        raise ValueError(f"unknown fault exception {name!r} (supported: "
                         f"{sorted(_EXCEPTIONS) + ['CalledProcessError', 'TimeoutExpired']})")


def _parse_clause(clause: str) -> FaultSpec:
    """``SITE[@HIT][:ACTION[(ARG)]][xCOUNT][?PROB]``"""
    work = clause
    prob = 1.0
    if "?" in work:
        work, _, p = work.rpartition("?")
        prob = float(p)
    count = 1
    action_part = None
    if ":" in work:
        work, _, action_part = work.partition(":")
        if "x" in action_part:
            # split the trailing xCOUNT, but not the x inside "(...)"
            base, _, tail = action_part.rpartition("x")
            if ")" not in tail and base:
                count = -1 if tail.strip() == "*" else int(tail)
                action_part = base
    at = 1
    if "@" in work:
        work, _, at_s = work.partition("@")
        at = int(at_s)
    site = work.strip()
    if not site:
        raise ValueError(f"fault clause has no site: {clause!r}")
    action, arg = "raise", None
    if action_part:
        action_part = action_part.strip()
        if "(" in action_part:
            action, _, rest = action_part.partition("(")
            arg = rest.rstrip(")").strip() or None
        else:
            action = action_part
    return FaultSpec(site, action, arg, at, count, prob)


# -- process-wide plan ------------------------------------------------------

_plan: Optional[FaultPlan] = None
_env_checked = False
_state_lock = threading.Lock()


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install ``plan`` as the process-wide active plan (None clears)."""
    global _plan, _env_checked
    with _state_lock:
        _plan = plan
        _env_checked = True    # an explicit plan overrides the env


def clear_plan() -> None:
    set_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _plan


def load_env_plan(force: bool = False) -> Optional[FaultPlan]:
    """Parse ``HOROVOD_FAULT_PLAN`` into the active plan (once; pass
    ``force=True`` to re-read after changing the env)."""
    global _plan, _env_checked
    with _state_lock:
        if _env_checked and not force:
            return _plan
        _env_checked = True
        text = os.environ.get("HOROVOD_FAULT_PLAN")
        if text:
            _plan = FaultPlan.parse(text)
            hvd_logging.warning(
                "faults: HOROVOD_FAULT_PLAN active — %d fault(s), seed %d%s",
                len(_plan.specs), _plan.seed,
                " (sim mode)" if _plan.sim else "")
        return _plan


def inject(site: str, value: Any = None) -> Any:
    """The chaos hook: one hit at ``site`` against the active plan.

    ``value`` is only consulted by the ``corrupt`` action, which returns
    a perturbed copy of it; other actions ignore it.

    No active plan → returns None after one global check (plus a
    one-time env parse on the first call in the process) — cheap enough
    for per-step and per-batch call sites."""
    if _plan is None:
        if _env_checked:
            return None
        if load_env_plan() is None:
            return None
    return _plan.inject(site, value)
