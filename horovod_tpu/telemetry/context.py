"""RunContext: the (run_id, generation, step) correlation triple.

Trace spans (``utils/timeline.py``), metric snapshots (the JSONL
exporter) and log lines (``utils/logging.py``) all stamp the same
triple, so an operator can pivot between the three planes of one run:
find the slow step in the timeline, read its metrics sample, grep its
log lines (docs/metrics.md "Correlating the three planes").

* ``run_id`` — one training invocation end-to-end, surviving elastic
  resets; from ``HOROVOD_RUN_ID`` when the launcher provides it
  (re-exported to workers), else derived once per process.
* ``generation`` — the elastic world generation
  (``HOROVOD_ELASTIC_GENERATION``); bumped through ``update()`` on
  reset.
* ``step`` — the training progress counter; advanced by
  ``DistributedTrainStep`` calls and elastic commits.

The singleton is process-wide and thread-safe; reads are lock-free
snapshots of immutable ints/strings (torn reads impossible — each field
is one reference swap).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional


class RunContext:
    def __init__(self, run_id: Optional[str] = None,
                 generation: int = 0, step: int = 0):
        self._lock = threading.Lock()
        self.run_id = run_id or _default_run_id()
        self.generation = int(generation)
        self.step = int(step)
        # whether anything explicitly set context — the signal the log
        # formatter uses to start stamping lines (a non-run process,
        # e.g. a unit test, keeps the historical log format)
        self.explicit = False

    def update(self, run_id: Optional[str] = None,
               generation: Optional[int] = None,
               step: Optional[int] = None) -> None:
        with self._lock:
            if run_id is not None:
                self.run_id = str(run_id)
            if generation is not None:
                self.generation = int(generation)
            if step is not None:
                self.step = int(step)
            self.explicit = True

    def advance(self, generation: Optional[int] = None,
                step: Optional[int] = None) -> None:
        """Update values WITHOUT marking the context explicit — for
        instrumentation that tracks progress (train step, elastic
        commits) and must not switch a process into correlated-log mode
        on its own; ``update()`` is the operator-facing setter."""
        with self._lock:
            if generation is not None:
                self.generation = int(generation)
            if step is not None:
                self.step = int(step)

    def advance_step(self, n: int = 1) -> int:
        with self._lock:
            self.step += int(n)
            return self.step

    def as_dict(self) -> Dict:
        with self._lock:
            return {"run_id": self.run_id, "generation": self.generation,
                    "step": self.step}

    def log_suffix(self) -> str:
        """``" gen G step S"`` once context is explicitly set, else
        ``""`` — appended inside the log prefix bracket."""
        if not self.explicit:
            return ""
        return f" gen {self.generation} step {self.step}"


def _default_run_id() -> str:
    env = os.environ.get("HOROVOD_RUN_ID")
    if env:
        return env
    return f"run-{os.getpid():x}-{int(time.time()) & 0xFFFFFF:x}"


_ctx: Optional[RunContext] = None
_ctx_lock = threading.Lock()


def run_context() -> RunContext:
    global _ctx
    if _ctx is None:
        with _ctx_lock:
            if _ctx is None:
                _ctx = RunContext()
    return _ctx
