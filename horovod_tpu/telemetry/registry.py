"""The metrics registry: labeled Counter/Gauge/Histogram (docs/metrics.md).

Design constraints, in order:

* **zero-cost when disabled** — every ``inc``/``set``/``observe`` is one
  attribute load plus a branch when the registry is disabled (the same
  contract as :func:`horovod_tpu.faults.inject`, pinned <5 µs/call by
  ``tests/test_telemetry.py``), so instrumentation can live on per-step
  and per-batch hot paths unconditionally;
* **lock-disciplined** (hvdlint HVD004-clean) — one lock per metric
  series guards its value, one registry lock guards creation; exact
  totals under the multi-thread hammer test, and no lock is ever held
  while calling into another subsystem (telemetry is a leaf: it never
  calls back into the runtime, so it cannot extend any lock-order
  cycle);
* **mergeable** — histograms use *fixed* bucket bounds chosen at
  creation, identical on every rank, so the driver can sum per-rank
  bucket counts sample-by-sample (the heartbeat aggregation path in
  :mod:`horovod_tpu.telemetry.export`).

There is exactly ONE process-wide registry (``default_registry()``),
created lazily and never replaced — call sites may cache metric handles
forever.  Tests zero it with :meth:`MetricsRegistry.reset_values`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Fixed mergeable bucket families (seconds / bytes).  All ranks share
# these bounds, which is what makes cross-rank histogram merges exact.
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0)
DEFAULT_SIZE_BUCKETS: Tuple[float, ...] = (
    1024.0, 16384.0, 262144.0, 1048576.0, 4194304.0, 16777216.0,
    67108864.0, 268435456.0, 1073741824.0)


def series_key(name: str, labels: Dict[str, str]) -> str:
    """Canonical series identity: ``name`` or ``name{k="v",...}`` with
    labels sorted — the key the JSONL snapshot, the Prometheus renderer
    and the cross-rank merge all agree on."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


class _Series:
    """One (metric, label-set) time series."""

    __slots__ = ("labels", "_lock")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._lock = threading.Lock()


class _CounterSeries(_Series):
    __slots__ = ("value",)

    def __init__(self, labels):
        super().__init__(labels)
        self.value = 0.0


class _GaugeSeries(_Series):
    __slots__ = ("value",)

    def __init__(self, labels):
        super().__init__(labels)
        self.value = 0.0


class _HistogramSeries(_Series):
    __slots__ = ("counts", "sum", "count")

    def __init__(self, labels, n_buckets: int):
        super().__init__(labels)
        self.counts = [0] * n_buckets      # one per bound + overflow
        self.sum = 0.0
        self.count = 0


class Metric:
    """A named metric family; label sets create child series lazily.

    Call the value methods either directly (unlabeled series) or on the
    object ``labels(...)`` returns.  Handles are stable for the process
    lifetime — cache them on hot paths.
    """

    kind = "untyped"

    def __init__(self, registry: "MetricsRegistry", name: str,
                 help: str = ""):
        self._registry = registry
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], _Series] = {}

    def _make_series(self, labels: Dict[str, str]) -> _Series:
        raise NotImplementedError

    def _get_series(self, labels: Dict[str, str]) -> _Series:
        key = tuple(sorted(labels.items()))
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._make_series(dict(labels))
                self._series[key] = s
            return s

    def labels(self, **labels: str) -> "_BoundMetric":
        """Bind a label set; the returned handle exposes the same value
        methods and is cheap to cache."""
        return _BoundMetric(self, self._get_series(
            {k: str(v) for k, v in labels.items()}))

    def series(self) -> List[_Series]:
        with self._lock:
            return list(self._series.values())

    def reset_values(self) -> None:
        for s in self.series():
            with s._lock:
                if isinstance(s, _HistogramSeries):
                    s.counts = [0] * len(s.counts)
                    s.sum = 0.0
                    s.count = 0
                else:
                    s.value = 0.0


class _BoundMetric:
    """A metric handle bound to one label set."""

    __slots__ = ("_metric", "_series")

    def __init__(self, metric: Metric, series: _Series):
        self._metric = metric
        self._series = series

    def inc(self, n: float = 1.0) -> None:
        if not self._metric._registry._enabled:
            return
        s = self._series
        with s._lock:
            s.value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def set(self, v: float) -> None:
        if not self._metric._registry._enabled:
            return
        s = self._series
        with s._lock:
            s.value = float(v)

    def observe(self, v: float) -> None:
        if not self._metric._registry._enabled:
            return
        m = self._metric
        s = self._series
        i = bisect.bisect_left(m.buckets, v)
        with s._lock:
            s.counts[i] += 1
            s.sum += v
            s.count += 1

    @property
    def value(self) -> float:
        s = self._series
        with s._lock:
            return s.value


class Counter(Metric):
    """Monotonically-increasing count (events, bytes, errors)."""

    kind = "counter"

    def _make_series(self, labels):
        return _CounterSeries(labels)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if not self._registry._enabled:
            return
        (self.labels(**labels) if labels else self._unlabeled()).inc(n)

    def _unlabeled(self) -> _BoundMetric:
        return _BoundMetric(self, self._get_series({}))

    @property
    def value(self) -> float:
        """Unlabeled series value (0.0 if never incremented)."""
        return self._unlabeled().value


class Gauge(Metric):
    """Point-in-time value (queue depth, heartbeat age, generation)."""

    kind = "gauge"

    def _make_series(self, labels):
        return _GaugeSeries(labels)

    def set(self, v: float, **labels: str) -> None:
        if not self._registry._enabled:
            return
        (self.labels(**labels) if labels else self._unlabeled()).set(v)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if not self._registry._enabled:
            return
        (self.labels(**labels) if labels else self._unlabeled()).inc(n)

    def dec(self, n: float = 1.0, **labels: str) -> None:
        self.inc(-n, **labels)

    def _unlabeled(self) -> _BoundMetric:
        return _BoundMetric(self, self._get_series({}))

    @property
    def value(self) -> float:
        return self._unlabeled().value


class Histogram(Metric):
    """Distribution over fixed, mergeable buckets.

    ``buckets`` are the upper bounds of the finite buckets; one
    overflow (+Inf) bucket is implicit.  Counts are per-bucket (NOT
    cumulative) internally; the Prometheus renderer emits the standard
    cumulative ``_bucket{le=...}`` view.
    """

    kind = "histogram"

    def __init__(self, registry, name, help="",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(registry, name, help)
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b)
                                                       for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r} needs >= 1 bucket bound")

    def _make_series(self, labels):
        return _HistogramSeries(labels, len(self.buckets) + 1)

    def observe(self, v: float, **labels: str) -> None:
        if not self._registry._enabled:
            return
        (self.labels(**labels) if labels else self._unlabeled()).observe(v)

    def _unlabeled(self) -> _BoundMetric:
        return _BoundMetric(self, self._get_series({}))


class MetricsRegistry:
    """Process-wide metric family registry.

    ``enabled=False`` (the production default without the
    ``HOROVOD_METRICS*`` knobs) turns every value mutation into a
    near-free branch; creation/lookup still works so call sites can
    cache handles before the enable decision is made.
    """

    def __init__(self, enabled: bool = True):
        self._enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    # -- lifecycle ----------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def reset_values(self) -> None:
        """Zero every series (keep families + cached handles valid) —
        the test/bench isolation hook."""
        for m in self.metrics():
            m.reset_values()

    # -- creation -----------------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(self, name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    # -- read side ----------------------------------------------------------

    def metrics(self) -> List[Metric]:
        with self._lock:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def value(self, name: str, **labels: str) -> float:
        """Current value of a counter/gauge series (0.0 when absent) —
        the read seam ``bench.py --chaos`` consumes."""
        m = self.get(name)
        if m is None or isinstance(m, Histogram):
            return 0.0
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with m._lock:
            s = m._series.get(key)
        if s is None:
            return 0.0
        with s._lock:
            return s.value

    def gauge_samples(self) -> List[Tuple[str, Dict[str, str], float]]:
        """``(name, labels, value)`` for every gauge series — the feed
        for the timeline's Chrome counter (``"ph":"C"``) events."""
        out = []
        for m in self.metrics():
            if not isinstance(m, Gauge):
                continue
            for s in m.series():
                with s._lock:
                    out.append((m.name, dict(s.labels), s.value))
        return out

    def snapshot(self) -> Dict:
        """JSON-able value snapshot: ``counters``/``gauges`` map series
        key → value; ``histograms`` map series key → bounds + per-bucket
        counts + sum/count.  Bounds ride every snapshot so merges can
        verify bucket compatibility."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        histograms: Dict[str, Dict] = {}
        for m in self.metrics():
            for s in m.series():
                key = series_key(m.name, s.labels)
                with s._lock:
                    if isinstance(m, Histogram):
                        histograms[key] = {
                            "bounds": list(m.buckets),
                            "counts": list(s.counts),
                            "sum": s.sum,
                            "count": s.count,
                        }
                    elif isinstance(m, Counter):
                        counters[key] = s.value
                    else:
                        gauges[key] = s.value
        return {"counters": counters, "gauges": gauges,
                "histograms": histograms}

    def counters_snapshot(self) -> Dict[str, float]:
        """Counters only — the compact payload piggybacked on elastic
        heartbeats for driver-side aggregation."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            if not isinstance(m, Counter):
                continue
            for s in m.series():
                with s._lock:
                    out[series_key(m.name, s.labels)] = s.value
        return out


def merge_counter_snapshots(snaps: Iterable[Dict[str, float]]
                            ) -> Dict[str, float]:
    """Sum per-rank counter snapshots series-by-series — exact because
    counters are monotone sums and series keys are canonical."""
    out: Dict[str, float] = {}
    for snap in snaps:
        for k, v in snap.items():
            out[k] = out.get(k, 0.0) + v
    return out
