"""hvdtel: the unified runtime telemetry plane (docs/metrics.md).

One process-wide :class:`~horovod_tpu.telemetry.registry.MetricsRegistry`
that every subsystem instruments unconditionally — train step, input
pipeline, checkpointer, elastic driver/health plane, retry, faults,
stall inspector — at zero cost until enabled (the ``faults.inject``
contract: one attribute load + branch per call, pinned <5 µs by
tier-1).  Enabled, it feeds:

* a per-worker **Prometheus** text endpoint (``HOROVOD_METRICS_PORT``,
  0 = off; worker *i* binds ``port + i``), the driver's additionally
  serving per-worker counters aggregated off the heartbeat RPC;
* a periodic **JSONL snapshot log** (``HOROVOD_METRICS_LOG``,
  ``HOROVOD_METRICS_INTERVAL_S``) that ``bench.py`` folds into BENCH
  JSON and ``python -m horovod_tpu.analysis metrics-check`` validates;
* the **timeline**: registered gauges render as Chrome counter rows
  (``"ph":"C"``) under the collective spans (docs/timeline.md).

A :class:`~horovod_tpu.telemetry.context.RunContext` (run_id,
generation, step) is stamped onto metric snapshots, trace events and
log lines so the three planes correlate.

Typical use — instrumentation (handles are cheap to cache)::

    from horovod_tpu import telemetry
    _BATCHES = telemetry.counter("hvd_input_batches_total", "batches fed")
    _BATCHES.inc()

and operation::

    HOROVOD_METRICS_PORT=9090 HOROVOD_METRICS_LOG=/tmp/run.metrics.jsonl \
        hvdrun -np 4 python train.py
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from horovod_tpu.telemetry.context import RunContext, run_context
from horovod_tpu.telemetry.export import (
    SCHEMA_VERSION,
    SNAPSHOT_KIND,
    MetricsSnapshotWriter,
    PrometheusExporter,
    WorkerMetricsStore,
    render_prometheus,
    snapshot_line,
)
from horovod_tpu.telemetry.registry import (
    DEFAULT_SIZE_BUCKETS,
    DEFAULT_TIME_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_counter_snapshots,
    series_key,
)

__all__ = [
    "SCHEMA_VERSION", "SNAPSHOT_KIND",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "MetricsSnapshotWriter", "PrometheusExporter", "RunContext",
    "TelemetryRuntime", "WorkerMetricsStore",
    "DEFAULT_SIZE_BUCKETS", "DEFAULT_TIME_BUCKETS",
    "counter", "gauge", "histogram", "default_registry", "enabled",
    "enable", "disable", "reset", "value", "snapshot",
    "counters_snapshot", "bench_metrics", "merge_counter_snapshots",
    "render_prometheus", "run_context", "series_key", "snapshot_line",
    "start_from_config", "worker_store",
]

_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()
_worker_store: Optional[WorkerMetricsStore] = None


def default_registry() -> MetricsRegistry:
    """THE process registry (created lazily, disabled by default, never
    replaced — cached metric handles stay valid forever)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = MetricsRegistry(enabled=False)
    return _registry


def worker_store() -> WorkerMetricsStore:
    """The process-wide per-worker aggregation store (driver side)."""
    global _worker_store
    if _worker_store is None:
        with _registry_lock:
            if _worker_store is None:
                _worker_store = WorkerMetricsStore()
    return _worker_store


def enabled() -> bool:
    return _registry is not None and _registry.enabled


def enable() -> MetricsRegistry:
    reg = default_registry()
    reg.enable()
    return reg


def disable() -> None:
    if _registry is not None:
        _registry.disable()


def reset() -> None:
    """Zero every series (handles stay valid) — test/bench isolation."""
    if _registry is not None:
        _registry.reset_values()


def counter(name: str, help: str = "") -> Counter:
    return default_registry().counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return default_registry().gauge(name, help)


def histogram(name: str, help: str = "",
              buckets=DEFAULT_TIME_BUCKETS) -> Histogram:
    return default_registry().histogram(name, help, buckets=buckets)


def value(name: str, **labels) -> float:
    return default_registry().value(name, **labels)


def snapshot() -> Dict:
    return default_registry().snapshot()


def counters_snapshot() -> Dict[str, float]:
    return default_registry().counters_snapshot()


def bench_metrics() -> Dict:
    """The block ``bench.py`` folds into BENCH JSON: schema stamp +
    final counters (the deterministic slice of the snapshot — gauges
    and duration histograms are run-dependent by nature)."""
    return {"schema_version": SCHEMA_VERSION,
            "counters": counters_snapshot()}


class TelemetryRuntime:
    """The exporters one ``init()`` started; ``shutdown()`` stops them
    (final JSONL snapshot included)."""

    def __init__(self, exporter: Optional[PrometheusExporter] = None,
                 writer: Optional[MetricsSnapshotWriter] = None):
        self.exporter = exporter
        self.writer = writer

    def shutdown(self) -> None:
        if self.writer is not None:
            self.writer.stop()
            self.writer = None
        if self.exporter is not None:
            self.exporter.stop()
            self.exporter = None


def start_from_config(config, process_rank: int = 0
                      ) -> Optional[TelemetryRuntime]:
    """Resolve the ``HOROVOD_METRICS*`` contract at ``init()`` time.

    Collection is enabled when ``HOROVOD_METRICS=1`` or when either
    exporter is configured (``HOROVOD_METRICS=0`` force-disables both
    collection and exporters).  Returns the running exporters, or None
    when telemetry stays off.
    """
    explicit = getattr(config, "metrics_enabled", None)
    port = int(getattr(config, "metrics_port", 0) or 0)
    log_path = getattr(config, "metrics_log", None)
    on = bool(port or log_path) if explicit is None else bool(explicit)
    if not on:
        return None
    reg = enable()
    run_context().update(
        run_id=getattr(config, "run_id", None),
        generation=int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "0")
                       or 0))
    exporter = None
    writer = None
    if port:
        # per-worker endpoint: worker i binds port + i so co-hosted
        # workers never collide; scrape targets enumerate the range
        exporter = PrometheusExporter(reg, port + int(process_rank),
                                      store=worker_store())
        exporter.start()
    if log_path:
        if process_rank:
            log_path = f"{log_path}.{process_rank}"
        writer = MetricsSnapshotWriter(
            reg, log_path,
            interval_s=float(getattr(config, "metrics_interval_s", 10.0)))
        writer.start()
    return TelemetryRuntime(exporter, writer)
