"""Metric exporters: Prometheus text endpoint + JSONL snapshot log.

Two consumers, two formats, one registry (docs/metrics.md):

* :class:`PrometheusExporter` — a stdlib-only (``http.server``) HTTP
  endpoint serving the text exposition format on
  ``HOROVOD_METRICS_PORT`` (0 = off; per-worker — worker *i* binds
  ``port + i`` so one host's workers never collide).  The driver's
  endpoint additionally serves every worker's counters with a
  ``worker="host:local_rank"`` label, aggregated from the heartbeat
  piggyback (:class:`WorkerMetricsStore`).
* :class:`MetricsSnapshotWriter` — a periodic, ``schema_version``-
  stamped JSONL snapshot appended to ``HOROVOD_METRICS_LOG``; the
  machine-readable artifact ``bench.py`` folds into BENCH JSON and
  ``python -m horovod_tpu.analysis metrics-check`` validates.

Export failure must never touch training: the writer loop carries the
``telemetry.export`` chaos site (docs/faults.md) and degrades by
dropping the sample — counted in ``hvd_telemetry_export_errors_total``
— never by raising into the runtime.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from horovod_tpu import faults
from horovod_tpu.telemetry import context as tel_context
from horovod_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_counter_snapshots,
    series_key,
)
from horovod_tpu.utils import logging as hvd_logging

SCHEMA_VERSION = 1
SNAPSHOT_KIND = "hvdtel_snapshot"


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(registry: MetricsRegistry,
                      store: Optional["WorkerMetricsStore"] = None) -> str:
    """Text exposition (version 0.0.4) of every registered series;
    histograms render the standard cumulative ``_bucket{le=}``/
    ``_sum``/``_count`` triple from the internal per-bucket counts."""
    lines = []
    for m in registry.metrics():
        lines.append(f"# HELP {m.name} {m.help or m.name}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for s in m.series():
            with s._lock:
                if isinstance(m, Histogram):
                    cum = 0
                    for bound, n in zip(m.buckets, s.counts):
                        cum += n
                        lines.append(_series_line(
                            m.name + "_bucket",
                            dict(s.labels, le=_fmt(bound)), cum))
                    cum += s.counts[-1]
                    lines.append(_series_line(
                        m.name + "_bucket", dict(s.labels, le="+Inf"), cum))
                    lines.append(_series_line(m.name + "_sum",
                                              s.labels, s.sum))
                    lines.append(_series_line(m.name + "_count",
                                              s.labels, s.count))
                else:
                    lines.append(_series_line(m.name, s.labels, s.value))
    if store is not None:
        lines.extend(store.render_lines())
    return "\n".join(lines) + "\n"


def _series_line(name: str, labels: Dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{_escape(str(labels[k]))}"'
                         for k in sorted(labels))
        return f"{name}{{{inner}}} {_fmt(float(value))}"
    return f"{name} {_fmt(float(value))}"


def snapshot_line(registry: MetricsRegistry) -> Dict:
    """One JSONL snapshot record: schema stamp + run-context triple +
    the full value snapshot.  ``ts_unix`` is the only
    non-deterministic field for a seeded workload — determinism claims
    (docs/metrics.md) are over ``counters``."""
    line = {"schema_version": SCHEMA_VERSION, "kind": SNAPSHOT_KIND,
            "ts_unix": round(time.time(), 3)}
    line.update(tel_context.run_context().as_dict())
    line.update(registry.snapshot())
    return line


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] not in ("/", "/metrics"):
            self.send_error(404)
            return
        body = render_prometheus(self.server.hvd_registry,
                                 self.server.hvd_store).encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


class PrometheusExporter:
    """Serve ``/metrics`` from a background thread; ``port=0`` binds an
    ephemeral port (tests), the runtime gate for "off" lives in
    :func:`horovod_tpu.telemetry.start_from_config`."""

    def __init__(self, registry: MetricsRegistry, port: int,
                 host: str = "0.0.0.0",
                 store: Optional["WorkerMetricsStore"] = None):
        self._server = ThreadingHTTPServer((host, int(port)),
                                           _MetricsHandler)
        self._server.daemon_threads = True
        self._server.hvd_registry = registry
        self._server.hvd_store = store
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="hvd_tpu_metrics_http")
        self._thread.start()
        hvd_logging.info("telemetry: Prometheus endpoint on :%d/metrics",
                         self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


class WorkerMetricsStore:
    """Driver-side per-worker counter snapshots, fed by the heartbeat
    piggyback (``HeartbeatRequest.metrics``) exactly the way the step
    counter rides ``report_step`` — no extra RPC, no extra thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snapshots: Dict[str, Dict[str, float]] = {}

    def update(self, worker: str, counters: Dict[str, float]) -> None:
        if not isinstance(counters, dict):
            return
        clean = {str(k): float(v) for k, v in counters.items()
                 if isinstance(v, (int, float))}
        with self._lock:
            self._snapshots[worker] = clean

    def purge(self, keep) -> None:
        """Drop workers no longer assigned (mirrors HealthMonitor.purge)."""
        keep = set(keep)
        with self._lock:
            self._snapshots = {w: s for w, s in self._snapshots.items()
                               if w in keep}

    def snapshots(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {w: dict(s) for w, s in self._snapshots.items()}

    def merged(self) -> Dict[str, float]:
        """Counters summed across workers (exact: canonical series keys,
        monotone sums)."""
        return merge_counter_snapshots(self.snapshots().values())

    def render_lines(self):
        """Per-worker series with a ``worker`` label appended — what the
        driver's Prometheus endpoint serves on top of its own registry."""
        lines = []
        for worker, snap in sorted(self.snapshots().items()):
            for key, value in sorted(snap.items()):
                if key.endswith("}"):
                    line = (f'{key[:-1]},worker="{_escape(worker)}"}} '
                            f"{_fmt(value)}")
                else:
                    line = f'{key}{{worker="{_escape(worker)}"}} ' \
                           f"{_fmt(value)}"
                lines.append(line)
        return lines


class MetricsSnapshotWriter:
    """Periodic JSONL snapshot appender (``HOROVOD_METRICS_LOG``).

    One daemon thread, one append + flush per interval; a final
    snapshot is written at :meth:`stop` so short runs always leave at
    least one complete record.  The file is append-only JSONL: a crash
    mid-write loses at most the last line, and every complete line is
    independently parseable (the schema contract
    ``analysis/metrics_schema.py`` validates)."""

    def __init__(self, registry: MetricsRegistry, path: str,
                 interval_s: float = 10.0):
        self._registry = registry
        self.path = path
        self.interval_s = max(float(interval_s), 0.05)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="hvd_tpu_metrics_writer")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.write_now()

    def write_now(self) -> Optional[Dict]:
        """One guarded export pass: build + append a snapshot line.
        Failures (including the ``telemetry.export`` chaos site) drop
        the sample, bump ``hvd_telemetry_export_errors_total`` and
        return None — the export plane degrades, training never sees
        it."""
        try:
            # chaos hook: a raise/delay models a failing metrics sink
            # (full disk, dead NFS) — export must degrade, not propagate
            faults.inject("telemetry.export")
            line = snapshot_line(self._registry)
            with open(self.path, "a") as f:
                f.write(json.dumps(line, sort_keys=True) + "\n")
                f.flush()
            return line
        except Exception as e:  # noqa: BLE001 — export is best-effort
            self._registry.counter(
                "hvd_telemetry_export_errors_total",
                "metrics snapshot export failures").inc()
            hvd_logging.warning("telemetry: snapshot export failed: %s", e)
            return None

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.write_now()      # final record: short runs still export
