"""Training-loop callbacks (the Keras-layer parity surface).

Reference: ``horovod/_keras/callbacks.py`` —
``BroadcastGlobalVariablesCallback``, ``MetricAverageCallback``,
``LearningRateWarmupCallback``, ``LearningRateScheduleCallback``
(:22-187) — and ``horovod/_keras/elastic.py`` (``CommitStateCallback``,
``UpdateBatchStateCallback``, ``UpdateEpochStateCallback``).

JAX has no Keras Model owning the loop, so callbacks here operate on a
duck-typed ``loop`` object (anything with ``params``/``opt_state``
attributes, e.g. a small dataclass around ``DistributedTrainStep``) and
a ``logs`` dict.  Learning-rate control is exposed two ways:

* **optax schedules** (:func:`warmup_schedule`) — the idiomatic TPU form:
  the schedule is part of the compiled optimizer, zero host round-trips;
* the callback classes — for Keras-style loops that mutate an
  ``optax.inject_hyperparams`` learning rate between steps.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional

import numpy as np
import optax

import horovod_tpu.functions as F
from horovod_tpu.ops import eager


def warmup_schedule(base_lr: float, warmup_epochs: int,
                    steps_per_epoch: int, size: Optional[int] = None,
                    initial_lr_scale: Optional[float] = None):
    """Gradual LR warmup for large-batch scaling (reference
    ``LearningRateWarmupCallback``; Goyal et al. 2017): ramp from
    ``base_lr`` (single-worker LR) to ``base_lr * size`` over
    ``warmup_epochs``.  Returns an optax schedule."""
    import horovod_tpu as hvd

    size = size if size is not None else hvd.size()
    init = base_lr * (initial_lr_scale if initial_lr_scale is not None
                      else 1.0)
    return optax.linear_schedule(
        init_value=init, end_value=base_lr * size,
        transition_steps=max(warmup_epochs * steps_per_epoch, 1))


class Callback:
    """Minimal lifecycle protocol (Keras callback shape)."""

    def on_train_begin(self, loop, logs: Optional[Dict] = None): ...
    def on_epoch_begin(self, epoch: int, loop, logs: Optional[Dict] = None): ...
    def on_batch_begin(self, batch: int, loop, logs: Optional[Dict] = None): ...
    def on_batch_end(self, batch: int, loop, logs: Optional[Dict] = None): ...
    def on_epoch_end(self, epoch: int, loop, logs: Optional[Dict] = None): ...
    def on_train_end(self, loop, logs: Optional[Dict] = None): ...


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self._callbacks = list(callbacks)

    def __getattr__(self, name):
        if not name.startswith("on_"):
            raise AttributeError(name)

        def fanout(*args, **kwargs):
            for cb in self._callbacks:
                getattr(cb, name)(*args, **kwargs)
        return fanout


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial model/optimizer state from ``root_rank`` at train
    start (reference ``callbacks.py:22``: the consistency step of the
    5-line recipe)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, loop, logs=None):
        loop.params = F.broadcast_variables(loop.params, self.root_rank)
        if getattr(loop, "opt_state", None) is not None:
            loop.opt_state = F.broadcast_variables(loop.opt_state,
                                                   self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics over all workers (reference
    ``callbacks.py:48-87``) so logged/checkpoint-selection metrics agree
    everywhere."""

    def on_epoch_end(self, epoch, loop, logs=None):
        if not logs:
            return
        import jax.numpy as jnp

        for k in sorted(logs):
            v = logs[k]
            if isinstance(v, (int, float, np.floating, np.integer)) or \
                    hasattr(v, "shape"):
                logs[k] = float(np.asarray(eager.allreduce(
                    jnp.asarray(v, jnp.float32),
                    name=f"metric.{k}", op=eager.Average)))


class _LrCallback(Callback):
    """Base for callbacks driving an ``optax.inject_hyperparams``
    learning rate (``loop.opt_state.hyperparams['learning_rate']``)."""

    @staticmethod
    def _set_lr(loop, lr: float) -> None:
        hp = getattr(loop.opt_state, "hyperparams", None)
        if hp is None or "learning_rate" not in hp:
            raise ValueError(
                "LR callbacks need an optimizer built with "
                "optax.inject_hyperparams(optax.sgd)(learning_rate=...) so "
                "the rate is mutable between steps")
        import jax.numpy as jnp

        hp["learning_rate"] = jnp.asarray(lr, jnp.float32)

    @staticmethod
    def _get_lr(loop) -> float:
        return float(loop.opt_state.hyperparams["learning_rate"])


class LearningRateWarmupCallback(_LrCallback):
    """Epoch-fraction warmup ``initial → base_lr*size`` (reference
    ``callbacks.py:104-187``)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 steps_per_epoch: Optional[int] = None, verbose: bool = False):
        import horovod_tpu as hvd

        self.initial_lr = initial_lr
        self.target_lr = initial_lr * hvd.size()
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._epoch = 0

    def on_epoch_begin(self, epoch, loop, logs=None):
        self._epoch = epoch

    def on_batch_begin(self, batch, loop, logs=None):
        if self._epoch >= self.warmup_epochs:
            return
        if not self.steps_per_epoch:
            raise ValueError("steps_per_epoch required for warmup")
        progress = (self._epoch * self.steps_per_epoch + batch + 1) / \
            (self.warmup_epochs * self.steps_per_epoch)
        lr = self.initial_lr + (self.target_lr - self.initial_lr) * \
            min(progress, 1.0)
        self._set_lr(loop, lr)

    def on_epoch_end(self, epoch, loop, logs=None):
        if epoch == self.warmup_epochs - 1 and self.verbose:
            print(f"Epoch {epoch}: finished gradual learning rate warmup "
                  f"to {self.target_lr}.")


class LearningRateScheduleCallback(_LrCallback):
    """Multiplier schedule against the (scaled) base LR (reference
    ``callbacks.py:104-160``): ``multiplier`` is a float or
    ``f(epoch) -> float``; with ``staircase`` the epoch is floored."""

    def __init__(self, initial_lr: float,
                 multiplier: Callable[[float], float],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 steps_per_epoch: Optional[int] = None):
        self.initial_lr = initial_lr
        self.multiplier = multiplier if callable(multiplier) \
            else (lambda _e, _m=multiplier: _m)
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self._epoch = 0

    def _adjust(self, loop, epoch: float) -> None:
        if epoch < self.start_epoch or \
                (self.end_epoch is not None and epoch >= self.end_epoch):
            return
        self._set_lr(loop, self.initial_lr * self.multiplier(epoch))

    def on_epoch_begin(self, epoch, loop, logs=None):
        self._epoch = epoch
        if self.staircase:
            self._adjust(loop, epoch)

    def on_batch_begin(self, batch, loop, logs=None):
        if not self.staircase:
            if not self.steps_per_epoch:
                raise ValueError("steps_per_epoch required for smooth "
                                 "schedules")
            self._adjust(loop, self._epoch + batch / self.steps_per_epoch)


# -- elastic callbacks (reference horovod/_keras/elastic.py) ----------------

class CommitStateCallback(Callback):
    """``state.commit()`` every ``batches_per_commit`` batches (reference
    ``CommitStateCallback``)."""

    def __init__(self, state, batches_per_commit: int = 1):
        self.state = state
        self.batches_per_commit = batches_per_commit

    def on_batch_end(self, batch, loop, logs=None):
        if (batch + 1) % self.batches_per_commit == 0:
            self.state.commit()


class UpdateBatchStateCallback(Callback):
    """Track ``state.batch``; resuming mid-epoch skips finished batches
    (reference ``UpdateBatchStateCallback``)."""

    def __init__(self, state):
        self.state = state

    def on_batch_end(self, batch, loop, logs=None):
        self.state.batch = batch + 1

    def on_epoch_end(self, epoch, loop, logs=None):
        self.state.batch = 0


class UpdateEpochStateCallback(Callback):
    """Track ``state.epoch`` across resets (reference
    ``UpdateEpochStateCallback``)."""

    def __init__(self, state):
        self.state = state

    def on_epoch_end(self, epoch, loop, logs=None):
        self.state.epoch = epoch + 1
