"""hvdmem — the memory-aware execution plane (docs/memory.md).

The memory twin of the parallelism-plan compiler (``parallel/plan.py``):
where the plan decides how work is *sharded*, this package decides how
it *fits* —

* ``memory/remat.py`` — the remat policy compiler: the per-block
  ``none | dots | full | offload`` tiers behind the models' ``remat``
  flag and the ``HOROVOD_REMAT_POLICY`` knob;
* ``memory/planner.py`` — HBM-budgeted search over
  (plan × remat × microbatch × offload), returning the *fastest
  feasible* config under ``HOROVOD_HBM_BUDGET_BYTES``;
* ``memory/offload.py`` — double-buffered async host offload of ZeRO
  optimizer-state shards (chaos sites ``offload.d2h``/``offload.h2d``);
* ``memory/smoke.py`` — the pure-sim planner scenario hvdci runs as
  gate 8.

``remat``/``planner``/``smoke`` import no JAX at module scope (the
analysis CLI stays runtime-free); ``offload`` needs a device runtime
and is therefore re-exported lazily.
"""

from horovod_tpu.memory.planner import (
    InfeasibleError,
    MemoryCandidate,
    search_memory_plans,
)
from horovod_tpu.memory.remat import (
    ENV_REMAT_POLICY,
    REMAT_POLICIES,
    remat_block,
    remat_fn,
    resolve_remat_policy,
)

__all__ = [
    "ENV_REMAT_POLICY",
    "HostOffloadEngine",
    "InfeasibleError",
    "MemoryCandidate",
    "REMAT_POLICIES",
    "remat_block",
    "remat_fn",
    "resolve_remat_policy",
    "search_memory_plans",
]


def __getattr__(name):
    if name == "HostOffloadEngine":     # lazy: offload.py imports JAX
        from horovod_tpu.memory.offload import HostOffloadEngine

        return HostOffloadEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
