"""Remat policy compiler — the per-block rematerialization tiers.

The boolean ``remat`` flag on the model configs (and on
``DistributedTrainStep``) collapses a whole design space into on/off:
*what* gets saved across the forward/backward boundary is exactly the
activation high-water the HBM planner (``memory/planner.py``,
docs/memory.md) trades against recompute time.  This module promotes
the flag into a small closed policy vocabulary:

====================  =====================================================
policy                what the backward pass may read without recompute
====================  =====================================================
``none``              everything — no remat, peak activations, no overhead
``dots``              matmul outputs only (``jax.checkpoint_policies.
                      dots_saveable``) — the classic "recompute the cheap
                      elementwise ops" middle tier
``full``              nothing — every block replays its forward
``offload``           matmul outputs, streamed to pinned host memory
                      (``offload_dot_with_no_batch_dims``) instead of HBM;
                      falls back to ``dots`` where the backend has no
                      pinned-host space (CPU XLA)
====================  =====================================================

Resolution precedence (:func:`resolve_remat_policy`): an explicit
policy string beats the ``HOROVOD_REMAT_POLICY`` env knob beats the
legacy boolean (``True`` → ``full``, the exact behavior the flag had)
beats ``none``.  The resolved policy is stamped into the AOT cache key
(``train_step._aot_extras``) so a warm start never serves an
executable compiled under a different remat variant.

JAX/flax are imported lazily so the policy *names* stay usable from
the stdlib-only analysis layer (``analysis/cost_model.py`` duplicates
the vocabulary by value, like ``PLAN_GRAMMAR_KEYS``).
"""

from __future__ import annotations

import os
from typing import Optional, Union

#: Closed policy vocabulary, cheapest-memory last.  Mirrored by value
#: in ``analysis/cost_model.REMAT_POLICIES`` (stdlib-only module).
REMAT_POLICIES = ("none", "dots", "full", "offload")

ENV_REMAT_POLICY = "HOROVOD_REMAT_POLICY"


def validate_policy(policy: str) -> str:
    if policy not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat policy {policy!r}: expected one of "
            f"{', '.join(REMAT_POLICIES)} (HOROVOD_REMAT_POLICY / "
            f"remat_policy; docs/memory.md)")
    return policy


def resolve_remat_policy(policy: Optional[str] = None,
                         remat: Union[bool, str, None] = None) -> str:
    """Resolve the effective policy for one model / train step.

    Precedence: explicit ``policy`` > ``HOROVOD_REMAT_POLICY`` env >
    the legacy boolean ``remat`` (``True`` → ``"full"``, preserving
    what the flag always meant) > ``"none"``.  A string passed through
    the legacy ``remat`` slot counts as explicit — it is how
    ``DistributedTrainStep(remat="dots")`` spells a policy.
    """
    if isinstance(remat, str) and policy is None:
        policy = remat
        remat = None
    if policy is not None:
        return validate_policy(policy)
    env = os.environ.get(ENV_REMAT_POLICY)
    if env:
        return validate_policy(env.strip())
    return "full" if remat else "none"


def checkpoint_policy(policy: str):
    """The ``jax.checkpoint_policies`` value for a tier, or None when
    the tier needs no policy argument (``none`` — no checkpointing at
    all — and ``full`` — save nothing, jax.checkpoint's default).

    ``offload`` asks for matmul outputs in pinned host memory; where
    the installed JAX lacks the factory (or the backend the pinned
    space — CPU XLA) the *compile-time* construction still succeeds
    and XLA's host-memory lowering decides, so construction failures
    here (old JAX) degrade to ``dots`` rather than erroring: the
    memory planner already prices ``offload`` ≈ ``dots`` + stream.
    """
    import jax

    validate_policy(policy)
    if policy in ("none", "full"):
        return None
    cp = jax.checkpoint_policies
    if policy == "offload":
        factory = getattr(cp, "offload_dot_with_no_batch_dims", None)
        if factory is not None:
            try:
                return factory("device", "pinned_host")
            except Exception:       # noqa: BLE001 — degrade, don't error
                pass
    return cp.dots_saveable


def remat_block(block_cls, policy: str):
    """Wrap a flax module class per policy — the drop-in replacement
    for the models' ``nn.remat(Block, static_argnums=())`` sites.
    ``none`` returns the class untouched."""
    import flax.linen as nn

    if validate_policy(policy) == "none":
        return block_cls
    cp = checkpoint_policy(policy)
    if cp is None:
        return nn.remat(block_cls, static_argnums=())
    return nn.remat(block_cls, static_argnums=(), policy=cp)


def remat_fn(fn, policy: str):
    """Wrap a plain function (the train step's ``loss_fn``) per
    policy — the drop-in replacement for ``jax.checkpoint(loss_fn) if
    remat else loss_fn``."""
    import jax

    if validate_policy(policy) == "none":
        return fn
    cp = checkpoint_policy(policy)
    if cp is None:
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=cp)
