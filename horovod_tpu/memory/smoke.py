"""Seeded memory-planner smoke for ``hvdci`` (analysis/ci.py gate 8).

A sub-second, pure-sim (no JAX, no devices) walk of the HBM-budgeted
planner: a synthetic 8-rank workload is searched unconstrained and
under a budget chosen to exclude the unconstrained winner, the
budgeted winner must actually fit and differ from the free one, an
everything-infeasible budget must raise :class:`~horovod_tpu.memory.
planner.InfeasibleError` naming the tightest axis, and the whole
scenario runs twice and must be bit-identical — planner determinism
itself is gated (the autotune acceptance criterion: same budget, same
config, every run).

Returns error strings (empty = pass) in the same idiom as
``parallel.smoke`` / ``guard.smoke`` so ci.py folds it straight into
its exit code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

from horovod_tpu.analysis import cost_model as CM
from horovod_tpu.memory.planner import (
    InfeasibleError,
    search_memory_plans,
)
from horovod_tpu.parallel.plan import candidate_plans

WORLD = 8
GB = 1e9
PARAM_BYTES = 8 * GB          # 2B-param model at fp32
ACTIVATION_BYTES = 24 * GB    # remat-none activations, one batch shard
BUDGET_BYTES = 6 * GB         # excludes the unconstrained winner
INFEASIBLE_BYTES = 0.1 * GB   # nothing fits
COMPUTE_S = 0.1


def _search(budget: float) -> Any:
    plans = [p.to_string() for p in candidate_plans(WORLD)]
    return search_memory_plans(
        plans, param_bytes=PARAM_BYTES,
        activation_bytes=ACTIVATION_BYTES, budget_bytes=budget,
        shard_optimizer_states=True, compute_s=COMPUTE_S,
        n_ici=WORLD)


def _scenario() -> Dict[str, Any]:
    free = _search(budget=1e15)
    tight = _search(budget=BUDGET_BYTES)
    try:
        _search(budget=INFEASIBLE_BYTES)
        infeasible = None
    except InfeasibleError as e:
        infeasible = {"axis": e.tightest_axis, "message": str(e)}
    return {
        "free": dataclasses.asdict(free),
        "tight": dataclasses.asdict(tight),
        "tight_total": tight.total_bytes,
        "tight_fits": CM.plan_fits(tight.predicted_bytes, BUDGET_BYTES),
        "free_fits": CM.plan_fits(free.predicted_bytes, BUDGET_BYTES),
        "infeasible": infeasible,
    }


def run_smoke() -> List[str]:
    """Run the seeded planner scenario twice; returns a list of error
    strings (empty = pass)."""
    errors: List[str] = []
    try:
        r1, r2 = _scenario(), _scenario()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        return [f"memory-smoke: scenario crashed: "
                f"{type(e).__name__}: {e}"]
    if r1["free_fits"]:
        errors.append(
            "memory-smoke: the unconstrained winner already fits the "
            f"{BUDGET_BYTES / GB:.0f} GB budget — the scenario no "
            "longer exercises the budget at all")
    if not r1["tight_fits"]:
        errors.append(
            f"memory-smoke: budgeted winner needs "
            f"{r1['tight_total'] / GB:.2f} GB, over the "
            f"{BUDGET_BYTES / GB:.0f} GB budget — plan_fits and the "
            "search disagree")
    if r1["free"] == r1["tight"]:
        errors.append(
            "memory-smoke: budget did not change the winning config")
    if r1["infeasible"] is None:
        errors.append(
            f"memory-smoke: {INFEASIBLE_BYTES / GB:.1f} GB budget did "
            "not raise InfeasibleError")
    elif r1["infeasible"]["axis"] not in (
            "params", "grads", "optimizer", "activations", "exchange"):
        errors.append(
            f"memory-smoke: InfeasibleError names unknown axis "
            f"{r1['infeasible']['axis']!r}")
    elif r1["infeasible"]["axis"] not in r1["infeasible"]["message"]:
        errors.append(
            "memory-smoke: InfeasibleError message does not name the "
            f"tightest axis {r1['infeasible']['axis']!r}")
    if r1 != r2:
        errors.append("memory-smoke: two seeded runs were not identical")
    return errors
