"""Async host offload of optimizer-state shards (docs/memory.md).

The ZeRO exchange (``optim/sharded_distributed_update``) already cuts
optimizer memory to ``1/N`` per rank; on HBM-starved configs even the
shard is too much.  :class:`HostOffloadEngine` streams a pytree —
typically the shard-sized optimizer state, optionally checkpointed
activations — to host RAM after the step that produced it and back
just before the step that needs it, on the PrefetchIterator
thread/queue pattern (``data/prefetch.py``): one worker issues the
D2H copies in the background, a bounded ring
(``HOROVOD_OFFLOAD_DEPTH``, default 2 — double buffering) applies
backpressure, and the H2D restore is a blocking ``fetch`` whose wait
time is the *stall* the telemetry histogram records — zero when the
transfer hid under compute.

Crash/consistency contract (the ``offload.*`` chaos sites pin it):

* the engine retains the **device** reference until the host copy has
  round-tripped; an injected or real transfer fault degrades to that
  retained reference — the caller gets its state back, bit-identical,
  and loses no step (``hvd_memory_offload_fallbacks_total`` counts);
* the round-trip itself is bit-exact: ``jax.device_get`` /
  ``jax.device_put`` move raw buffers, no dtype laundering;
* ``close()`` is idempotent, joins the worker, and leaves nothing
  running (the shutdown-without-leak discipline of the input
  pipeline).

Telemetry series (``analysis/metrics_schema.MEMORY_SERIES``):
``hvd_memory_offload_bytes_total{direction=d2h|h2d}``,
``hvd_memory_offload_stall_seconds``, ``hvd_memory_offload_inflight``,
``hvd_memory_offload_fallbacks_total``.
"""

from __future__ import annotations

import collections
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from horovod_tpu import faults, telemetry
from horovod_tpu.runtime.config import _env_int

_THREAD_PREFIX = "hvd-offload"
_DEFAULT_DEPTH = 2


def default_offload_depth() -> int:
    """HOROVOD_OFFLOAD_DEPTH — in-flight D2H transfers (2 = classic
    double buffering), resolved config-first like the prefetch knobs."""
    from horovod_tpu.runtime import state

    if state.is_initialized():
        return max(int(state.global_state().config.offload_depth), 1)
    return max(_env_int("HOROVOD_OFFLOAD_DEPTH", _DEFAULT_DEPTH), 1)


class HostOffloadEngine:
    """Double-buffered D2H/H2D streaming of pytrees.

    ::

        engine = HostOffloadEngine(name="optimizer")
        for step_i in range(steps):
            opt_state = engine.fetch(step_i - 1, opt_state)  # H2D (no-op
            params, opt_state, loss = step(params, opt_state, batch)
            engine.offload(step_i, opt_state)                # async D2H
        engine.close()

    ``offload(tag, tree)`` issues the background D2H copy and blocks
    only when ``depth`` copies are already in flight (backpressure).
    ``fetch(tag, fallback)`` joins the copy and restores to device,
    returning ``fallback`` untouched when the tag was never offloaded
    (the cold first step) or when the transfer faulted (the degrade
    path).  Tags are opaque; a step counter is the usual choice.
    """

    def __init__(self, name: str = "optimizer",
                 depth: Optional[int] = None):
        self.name = name
        self.depth = max(int(depth), 1) if depth is not None \
            else default_offload_depth()
        self._pending = collections.OrderedDict()   # tag -> (future, ref)
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"{_THREAD_PREFIX}-{name}")
        self._closed = False
        self.stall_s = 0.0
        self.fallbacks = 0
        self._tel_bytes = telemetry.counter(
            "hvd_memory_offload_bytes_total",
            "bytes streamed by the host-offload engine, per direction")
        self._tel_stall = telemetry.histogram(
            "hvd_memory_offload_stall_seconds",
            "seconds fetch() blocked on the host round-trip")
        self._tel_inflight = telemetry.gauge(
            "hvd_memory_offload_inflight",
            "offloaded pytrees currently parked on the host")
        self._tel_fallbacks = telemetry.counter(
            "hvd_memory_offload_fallbacks_total",
            "offload faults degraded to the retained device reference")

    # -- D2H ----------------------------------------------------------------

    def _d2h(self, tree):
        import jax

        faults.inject("offload.d2h")
        host = jax.device_get(tree)
        nbytes = sum(getattr(x, "nbytes", 0)
                     for x in jax.tree_util.tree_leaves(host))
        self._tel_bytes.labels(
            engine=self.name, direction="d2h").inc(nbytes)
        return host

    def offload(self, tag, tree) -> None:
        """Issue the async D2H copy of ``tree`` under ``tag``.

        Keeps the device reference alongside the future — the degrade
        contract — and applies backpressure at ``depth`` *in-flight*
        copies by joining the oldest unfinished one.  Completed copies
        stay in ``_pending`` until ``fetch`` (that's the contract), so
        only not-yet-done futures count toward the depth limit — a
        finished transfer costs host RAM, not D2H bandwidth.  A copy
        that *raised* counts as done too (no over-depth insert sneaks
        in behind it); the fault surfaces at its own ``fetch`` via the
        degrade path."""
        if self._closed:
            raise RuntimeError(f"offload engine {self.name!r} is closed")
        if tag in self._pending:
            raise ValueError(f"tag {tag!r} already offloaded — fetch it "
                             "before offloading it again")
        while True:
            in_flight = [f for f, _ in self._pending.values()
                         if not f.done()]
            if len(in_flight) < self.depth:
                break
            try:
                in_flight[0].result()
            except Exception:       # noqa: BLE001 — surfaced at fetch()
                pass
        self._pending[tag] = (self._executor.submit(self._d2h, tree),
                              tree)
        self._tel_inflight.labels(engine=self.name).set(
            len(self._pending))

    # -- H2D ----------------------------------------------------------------

    def fetch(self, tag, fallback):
        """Restore ``tag``'s pytree to device, or degrade.

        Blocks on the host copy (the measured stall), re-places it with
        ``jax.device_put`` and returns the restored tree.  Returns
        ``fallback`` as-is when ``tag`` was never offloaded, or when
        the D2H/H2D path faulted — the retained device state, so the
        training loop continues without losing the step."""
        import jax

        entry = self._pending.pop(tag, None)
        self._tel_inflight.labels(engine=self.name).set(
            len(self._pending))
        if entry is None:
            return fallback
        future, device_ref = entry
        t0 = time.perf_counter()
        try:
            host = future.result()
            faults.inject("offload.h2d")
            # restore to each leaf's ORIGINAL placement (the retained
            # ref's sharding), then detach with an on-device copy: a
            # compiled step consumes the restored state DONATED, and
            # device_put from numpy may hand back a zero-copy buffer
            # aliasing host memory the executable must not free
            import jax.numpy as jnp

            out = jax.tree_util.tree_map(
                lambda h, d: jnp.copy(jax.device_put(
                    h, getattr(d, "sharding", None))),
                host, device_ref)
            nbytes = sum(getattr(x, "nbytes", 0)
                         for x in jax.tree_util.tree_leaves(host))
            self._tel_bytes.labels(
                engine=self.name, direction="h2d").inc(nbytes)
        except Exception:           # noqa: BLE001 — the degrade path
            self.fallbacks += 1
            self._tel_fallbacks.labels(engine=self.name).inc()
            out = device_ref
        dt = time.perf_counter() - t0
        self.stall_s += dt
        self._tel_stall.labels(engine=self.name).observe(dt)
        return out

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Idempotent: drop pending copies, join the worker."""
        if self._closed:
            return
        self._closed = True
        for future, _ref in self._pending.values():
            future.cancel()
        self._pending.clear()
        self._executor.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
