"""HBM-budgeted plan search: the fastest *feasible* config.

The plan-space autotuner (PR 13) picks the fastest (plan, schedule)
point; nothing guaranteed the winner *fits*.  This module closes that
gap: :func:`search_memory_plans` walks the
(plan × remat policy × microbatch × offload) grid, prices each point
with the cost model's speed (:func:`~horovod_tpu.analysis.cost_model.
plan_cost_s` stretched by the policy's recompute overhead) and memory
(:func:`~horovod_tpu.analysis.cost_model.plan_memory_bytes`) twins,
and returns the fastest point whose predicted high-water fits the
``HOROVOD_HBM_BUDGET_BYTES`` budget.

Pure and deterministic — stdlib + the stdlib-only cost model, no JAX,
no clock, no randomness: the same inputs produce the same candidate
bit-for-bit (ties break on the candidate tuple itself), which is what
lets ``memory/smoke.py`` run the search twice under hvdci gate 8 and
require identical output.  When *nothing* fits,
:class:`InfeasibleError` names the tightest axis — the dominant
component of the closest candidate — so the operator knows which knob
(model shards, optimizer offload, remat, microbatches) actually moves
the wall.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple, Union

from horovod_tpu.analysis import cost_model as CM

#: Fractional step-time penalty charged to an offloaded optimizer
#: stream — the share of the D2H/H2D transfer the double buffer fails
#: to hide under compute.  Under the honest roofline
#: (``cost_model.OFFLOAD_RESIDENT_FRACTION`` = 1.0: the engine
#: restores the full shard before the step, so streaming buys no
#: step-window high-water) an offload=True point is strictly
#: dominated — same memory, this penalty slower — and the search
#: never returns one.  The axis stays in the grid for callers that
#: force ``offload=(True,)`` (host parking for reasons other than the
#: step high-water) and for a future bucketed engine whose residency
#: fraction drops below 1.  A winner with ``offload_optimizer=True``
#: only streams if the caller also sets HOROVOD_OFFLOAD_OPTIMIZER=1
#: and wires a :class:`~horovod_tpu.memory.offload.HostOffloadEngine`
#: into the loop — the plan does not enable the engine by itself.
OFFLOAD_STEP_PENALTY = 0.02

#: Default microbatch grid — powers of two up to the bench pipeline
#: probe's depth (``cost_model.PLAN_SCORE_MICROBATCHES``).
DEFAULT_MICROBATCHES = (1, 2, 4, 8)

#: Default policy grid: the non-offload remat tiers.  ``offload``
#: enters through the ``offload`` axis (optimizer-state streaming),
#: not the activation tier — activation offload needs a backend with
#: pinned-host space, which the pure-sim planner must not assume.
DEFAULT_REMAT_POLICIES = ("none", "dots", "full")


class InfeasibleError(ValueError):
    """No point of the search grid fits the budget.

    ``tightest_axis`` names the dominant memory component of the
    *closest* candidate (smallest predicted total) — the axis more
    budget, or a knob outside the searched grid, must address.
    """

    def __init__(self, message: str, tightest_axis: str,
                 closest: Optional["MemoryCandidate"] = None):
        super().__init__(message)
        self.tightest_axis = tightest_axis
        self.closest = closest


@dataclasses.dataclass(frozen=True)
class MemoryCandidate:
    """One scored point of the (plan × remat × microbatch × offload)
    grid."""

    plan: str
    remat_policy: str
    microbatches: int
    offload_optimizer: bool
    predicted_bytes: CM.MemoryBytes
    predicted_step_s: float

    @property
    def total_bytes(self) -> float:
        return self.predicted_bytes.total

    def summary(self) -> str:
        # an offload=on winner is only real once the caller enables the
        # streaming engine — say so wherever the candidate is printed
        off = "on [needs HOROVOD_OFFLOAD_OPTIMIZER=1]" \
            if self.offload_optimizer else "off"
        return (f"plan={self.plan} remat={self.remat_policy} "
                f"microbatches={self.microbatches} "
                f"offload={off} "
                f"-> {self.total_bytes / 1e9:.3f} GB, "
                f"{self.predicted_step_s * 1e3:.3f} ms/step")


def _plan_string(plan) -> str:
    if isinstance(plan, str):
        return plan
    if isinstance(plan, dict):
        ext = CM.parse_plan(plan)
        return ",".join(f"{k}={v}" for k, v in ext.items() if v > 1) \
            or "dp=1"
    to_string = getattr(plan, "to_string", None)
    if callable(to_string):        # parallel.plan.ShardingPlan
        return to_string()
    raise TypeError(f"plan must be a grammar string, extent dict or "
                    f"ShardingPlan, got {type(plan).__name__}")


def search_memory_plans(plans: Sequence[Union[str, Dict]], *,
                        param_bytes: float,
                        activation_bytes: float,
                        budget_bytes: Optional[float] = None,
                        hw: Optional[CM.HardwareModel] = None,
                        remat_policies: Sequence[str]
                        = DEFAULT_REMAT_POLICIES,
                        microbatches: Sequence[int]
                        = DEFAULT_MICROBATCHES,
                        offload: Sequence[bool] = (False, True),
                        optimizer_slots: int = 2,
                        shard_optimizer_states: bool = False,
                        exchange_bucket_bytes: Optional[float] = None,
                        compute_s: float = 0.0,
                        n_dcn: int = 1,
                        n_ici: int = 1
                        ) -> MemoryCandidate:
    """The fastest candidate whose predicted high-water fits.

    Speed: :func:`~horovod_tpu.analysis.cost_model.plan_cost_s`
    (compute stretched by the pipeline bubble + serial exchange wire)
    × (1 + the policy's recompute overhead) × (1 +
    :data:`OFFLOAD_STEP_PENALTY` when streaming).  Memory:
    :func:`~horovod_tpu.analysis.cost_model.plan_memory_bytes`.
    Gradients are the exchange payload, so ``param_bytes`` prices the
    wire too.

    Deterministic: candidates are scored in the caller's grid order
    and ties break on ``(step_s, plan, policy, microbatches,
    offload)`` — two runs over the same grid return the same object.
    Raises :class:`InfeasibleError` (naming the tightest axis) when
    nothing fits, and ``ValueError`` on an empty grid.

    A returned candidate with ``offload_optimizer=True`` describes a
    config that *assumes* optimizer-state streaming: applying it
    requires HOROVOD_OFFLOAD_OPTIMIZER=1 plus a
    :class:`~horovod_tpu.memory.offload.HostOffloadEngine` in the
    training loop (``summary()`` flags this).  The search itself never
    flips that knob.
    """
    if not plans:
        raise ValueError("search_memory_plans needs at least one plan")
    if hw is None:
        # calibration artifact > preset knob > v5e — the same measured
        # constants the cost model and perf gate price with
        # (docs/calibration.md "Precedence")
        hw = CM.resolve_hardware_model()
    scored = []
    for plan in plans:
        ps = _plan_string(plan)
        for policy in remat_policies:
            for m in microbatches:
                for off in offload:
                    mem = CM.plan_memory_bytes(
                        ps, param_bytes=param_bytes,
                        activation_bytes=activation_bytes,
                        remat_policy=policy, microbatches=m,
                        optimizer_slots=optimizer_slots,
                        shard_optimizer_states=shard_optimizer_states,
                        offload_optimizer=off,
                        exchange_bucket_bytes=exchange_bucket_bytes)
                    step_s = CM.plan_cost_s(
                        ps, param_bytes, n_dcn=n_dcn, n_ici=n_ici,
                        compute_s=compute_s, microbatches=m, hw=hw)
                    step_s *= 1.0 + CM.REMAT_RECOMPUTE_OVERHEAD[policy]
                    if off:
                        step_s *= 1.0 + OFFLOAD_STEP_PENALTY
                    scored.append(MemoryCandidate(
                        plan=ps, remat_policy=policy, microbatches=int(m),
                        offload_optimizer=bool(off), predicted_bytes=mem,
                        predicted_step_s=step_s))
    feasible = [c for c in scored
                if CM.plan_fits(c.predicted_bytes, budget_bytes, hw)]
    key = lambda c: (c.predicted_step_s, c.plan, c.remat_policy,  # noqa: E731
                     c.microbatches, c.offload_optimizer)
    if feasible:
        return min(feasible, key=key)
    closest = min(scored, key=lambda c: (c.total_bytes,) + key(c))
    cap = budget_bytes if budget_bytes is not None \
        else hw.hbm_capacity_bytes
    axis = closest.predicted_bytes.tightest
    raise InfeasibleError(
        f"no (plan x remat x microbatch x offload) point fits the "
        f"{float(cap) / 1e9:.3f} GB budget: the closest candidate "
        f"({closest.summary()}) is dominated by its {axis} component "
        f"— tightest axis: {axis}", tightest_axis=axis, closest=closest)
