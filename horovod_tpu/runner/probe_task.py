"""``python -m horovod_tpu.runner.probe_task <driver_addrs> <index>
[key]`` — the per-host NIC probe task (reference ``python -m
horovod.runner.task_fn``).  The HMAC key arrives as an argument because
ssh does not forward environment variables (the reference ships its
settings, key included, base64-encoded in the remote command); the env
var is the fallback for local spawns."""

import os
import sys

from horovod_tpu.runner.driver_service import run_probe_task


def main() -> None:
    driver_addrs, index = sys.argv[1], int(sys.argv[2])
    key = sys.argv[3] if len(sys.argv) > 3 else \
        os.environ.get("HOROVOD_SECRET_KEY")
    run_probe_task(driver_addrs, index, key)


if __name__ == "__main__":
    main()
