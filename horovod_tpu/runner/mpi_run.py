"""mpirun launch path.

Reference: ``horovod/runner/mpi_run.py`` — detect the MPI implementation
(``mpirun --version``), compose a single ``mpirun -np N -H host:slots,…
-bind-to none -map-by slot -x ENV… command`` line and exec it; mpirun
owns process placement.  The TPU edition keeps the command shape; the
MCA transport knobs that exist to steer Open MPI's BTLs stay
OpenMPI-conditional, and workers get their identity from the
OMPI/PMIx env (``cluster_env.jsm_identity``) plus the coordinator
address forwarded with ``-x``.
"""

from __future__ import annotations

import shutil
import subprocess
from typing import Dict, List, Optional

from horovod_tpu.runner.hosts import HostInfo

_MPI_NOT_FOUND_MSG = (
    "horovod_tpu does not find an installed MPI.\n\n"
    "Choose one of:\n"
    "1. Install Open MPI or another MPI implementation.\n"
    "2. Use the built-in launcher (drop --mpi).\n"
    "3. Use --jsrun on LSF clusters.")

# env vars forwarded to every rank (reference nccl_socket/path/pythonpath
# forwarding, mpi_run.py:185-199): true prefixes vs exact names kept
# separate so e.g. PATH_INFO is not swept up by a bare "PATH" prefix
_FORWARD_PREFIXES = ("HOROVOD_", "GLOO_", "JAX_", "TPU_", "XLA_")
_FORWARD_EXACT = ("PYTHONPATH", "PATH", "LD_LIBRARY_PATH")


def is_mpirun_installed() -> bool:
    return shutil.which("mpirun") is not None


def detect_mpi_implementation() -> str:
    """Identify the installed MPI from ``mpirun --version`` (reference
    ``_get_mpi_implementation``, ``mpi_run.py:113-130``): ``"openmpi"``,
    ``"spectrum"``, ``"mpich"``, or ``"unknown"``."""
    try:
        out = subprocess.run(["mpirun", "--version"],
                             capture_output=True, text=True,
                             timeout=10).stdout
    except (OSError, subprocess.TimeoutExpired):
        out = ""
    if "Open MPI" in out or "OpenRTE" in out:
        return "openmpi"
    if "Spectrum MPI" in out:
        return "spectrum"
    if "MPICH" in out or "HYDRA" in out:
        return "mpich"
    return "unknown"


def mpi_implementation_flags(env: Optional[Dict[str, str]] = None,
                             impl: Optional[str] = None) -> List[str]:
    """Implementation-specific placement flags (reference
    ``_get_mpi_implementation_flags`` composes per-implementation flag
    sets for OpenMPI/Spectrum/MPICH, ``mpi_run.py:112-119``).  MPICH's
    hydra understands ``-bind-to``/``-map-by`` but none of the OpenMPI
    MCA/``--tag-output`` spellings."""
    impl = impl or detect_mpi_implementation()
    if impl in ("openmpi", "spectrum"):
        return ["--allow-run-as-root", "--tag-output",
                "-bind-to", "none", "-map-by", "slot",
                "-mca", "pml", "ob1", "-mca", "btl", "^openib"]
    if impl == "mpich":
        return ["-bind-to", "none", "-map-by", "slot"]
    raise RuntimeError(
        "Unsupported MPI implementation for --mpi (need Open MPI, IBM "
        "Spectrum MPI, or MPICH — the launch relies on their env "
        "forwarding and per-rank identity env). Detected: " + impl)


def mpi_run_command(np: int, hosts: List[HostInfo], command: List[str],
                    env: Dict[str, str],
                    impl_flags: Optional[List[str]] = None,
                    nics: Optional[str] = None,
                    extra_mpi_args: Optional[str] = None,
                    ssh_port: Optional[int] = None,
                    ssh_identity_file: Optional[str] = None,
                    impl: Optional[str] = None) -> List[str]:
    """Compose the mpirun argv (reference ``mpi_run.py:122-218``).

    OpenMPI/Spectrum forward env with repeated ``-x VAR``; MPICH's hydra
    takes one ``-genvlist V1,V2,…`` and spells the NIC filter ``-iface``
    instead of an MCA knob.
    """
    import shlex

    impl = impl or detect_mpi_implementation()
    cmd = ["mpirun"]
    cmd += impl_flags if impl_flags is not None \
        else mpi_implementation_flags(env, impl=impl)
    cmd += ["-np", str(np),
            "-H", ",".join(f"{h.hostname}:{h.slots}" for h in hosts)]
    if nics:
        if impl == "mpich":
            cmd += ["-iface", nics.split(",")[0]]
        else:
            cmd += ["-mca", "btl_tcp_if_include", nics]
    if ssh_port or ssh_identity_file:
        if impl == "mpich":
            # hydra has no per-arg rsh passthrough; dialing default ssh
            # settings behind the user's back would connect differently
            # than requested
            raise ValueError(
                "ssh_port/ssh_identity_file cannot be forwarded to MPICH's "
                "hydra launcher; configure them in ~/.ssh/config for the "
                "target hosts instead")
        # mpirun's rsh agent must dial the same ssh settings the user
        # gave the launcher (reference forwards them via plm_rsh_args)
        rsh = []
        if ssh_port:
            rsh += ["-p", str(ssh_port)]
        if ssh_identity_file:
            rsh += ["-i", ssh_identity_file]
        cmd += ["-mca", "plm_rsh_args", " ".join(rsh)]
    fwd = [var for var in sorted(env)
           if var in _FORWARD_EXACT or var.startswith(_FORWARD_PREFIXES)]
    if impl == "mpich":
        if fwd:
            cmd += ["-genvlist", ",".join(fwd)]
    else:
        for var in fwd:
            cmd += ["-x", var]
    if extra_mpi_args:
        cmd += shlex.split(extra_mpi_args)
    cmd += list(command)
    return cmd


def mpi_run(args, hosts: List[HostInfo], env: Dict[str, str],
            stdout=None, stderr=None) -> int:
    import os

    from horovod_tpu.runner import safe_shell_exec

    if not is_mpirun_installed():
        raise RuntimeError(_MPI_NOT_FOUND_MSG)
    cmd = mpi_run_command(args.np, hosts, args.command, env,
                          nics=args.nics, extra_mpi_args=args.mpi_args,
                          ssh_port=args.ssh_port,
                          ssh_identity_file=args.ssh_identity_file)
    if args.verbose:
        import sys

        print("[launcher] " + " ".join(cmd), file=sys.stderr)
    opened = []
    if args.output_filename and stdout is None:
        # ranks' output is tagged by mpirun (--tag-output); capture the
        # combined streams under the requested directory like the other
        # launch paths do per rank
        os.makedirs(args.output_filename, exist_ok=True)
        stdout = open(os.path.join(args.output_filename, "mpirun.out"),
                      "wb")
        stderr = open(os.path.join(args.output_filename, "mpirun.err"),
                      "wb")
        opened = [stdout, stderr]
    try:
        return safe_shell_exec.execute(cmd, env=env, stdout=stdout,
                                       stderr=stderr)
    finally:
        for f in opened:
            f.close()
