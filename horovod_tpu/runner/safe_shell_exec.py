"""Process execution with whole-tree cleanup.

Reference: ``horovod/runner/common/util/safe_shell_exec.py`` — fork +
``setsid`` so the child owns a process group, SIGTERM the group on
termination with a grace period, then SIGKILL (``GRACEFUL_TERMINATION_TIME_S``).
The reference adds a middleman process to survive launcher death; here the
launcher is long-lived Python, so a killpg-on-exit registry is sufficient
and keeps worker teardown one signal away.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, IO, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0

_active_lock = threading.Lock()
_active: List[subprocess.Popen] = []


def _register(proc: subprocess.Popen) -> None:
    with _active_lock:
        _active.append(proc)


def _unregister(proc: subprocess.Popen) -> None:
    with _active_lock:
        if proc in _active:
            _active.remove(proc)


def terminate(proc: subprocess.Popen,
              grace_s: float = GRACEFUL_TERMINATION_TIME_S) -> None:
    """SIGTERM the child's process group, escalate to SIGKILL after the
    grace period (reference semantics)."""
    if proc.poll() is not None:
        return
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def terminate_all(grace_s: float = GRACEFUL_TERMINATION_TIME_S) -> None:
    with _active_lock:
        procs = list(_active)
    for p in procs:
        terminate(p, grace_s)


def launch(command: List[str], env: Optional[Dict[str, str]] = None,
           stdout: Optional[IO] = None,
           stderr: Optional[IO] = None) -> subprocess.Popen:
    """Start a command in its own process group (``setsid``), registered
    for cleanup via :func:`terminate_all`."""
    proc = subprocess.Popen(
        command, env=env,
        stdout=stdout if stdout is not None else sys.stdout,
        stderr=stderr if stderr is not None else sys.stderr,
        start_new_session=True)   # child leads its own process group
    _register(proc)
    return proc


def execute(command: List[str], env: Optional[Dict[str, str]] = None,
            stdout: Optional[IO] = None, stderr: Optional[IO] = None,
            events: Optional[list] = None) -> int:
    """Run to completion; on any event in ``events`` (``threading.Event``)
    terminate the whole tree.  Returns the exit code."""
    proc = launch(command, env=env, stdout=stdout, stderr=stderr)
    try:
        if not events:
            return proc.wait()
        while True:
            try:
                return proc.wait(timeout=0.25)
            except subprocess.TimeoutExpired:
                if any(e.is_set() for e in events):
                    terminate(proc)
                    return proc.wait()
    finally:
        _unregister(proc)
