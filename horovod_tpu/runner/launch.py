"""``hvdrun`` — the launcher CLI (reference ``horovodrun``).

Reference: ``horovod/runner/launch.py`` (``parse_args:212``,
``_run_static:484``, ``run_commandline:715``).  Maps the same surface
onto the TPU runtime: host/hostfile parsing, config-file → env plumbing,
per-slot env contract (``gloo_context.cc:47-55``), process fan-out with
fail-fast teardown, and the ``jax.distributed`` coordinator address in
place of the gloo rendezvous server.

Usage::

    python -m horovod_tpu.runner.launch -np 4 python train.py
    python -m horovod_tpu.runner.launch -np 4 -H h1:2,h2:2 python train.py
    python -m horovod_tpu.runner.launch -np 2 --min-np 2 --max-np 4 \
        --host-discovery-script ./discover.sh python train.py
"""

from __future__ import annotations

import argparse
import os
import shutil
import socket
import sys
import threading
from typing import Dict, List, Optional

from horovod_tpu.runner import config_parser, safe_shell_exec
from horovod_tpu.runner.hosts import (
    HostInfo,
    SlotInfo,
    get_host_assignments,
    parse_hostfile,
    parse_hosts,
)

_LOCAL_NAMES = ("localhost", "127.0.0.1", "::1")


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job.")
    p.add_argument("-v", "--version", action="store_true")
    p.add_argument("-np", "--num-proc", type=int, dest="np",
                   help="total number of worker processes")
    p.add_argument("-H", "--hosts", dest="hosts",
                   help='host list "h1:slots,h2:slots"; default localhost')
    p.add_argument("--hostfile", dest="hostfile",
                   help="file with one 'host slots=N' per line")
    p.add_argument("--ssh-port", type=int, default=None)
    p.add_argument("-i", "--ssh-identity-file", dest="ssh_identity_file",
                   help="ssh private key for remote worker launch")
    p.add_argument("--gloo", action="store_true", dest="use_gloo",
                   help="use the built-in launcher fan-out (the default; "
                        "accepted for reference CLI compatibility)")
    p.add_argument("--mpi", action="store_true", dest="use_mpi",
                   help="launch through mpirun (workers read identity "
                        "from the OMPI/PMIx env)")
    p.add_argument("--jsrun", action="store_true",
                   help="launch through jsrun with an ERF rankfile "
                        "(LSF clusters)")
    p.add_argument("--mpi-args", dest="mpi_args",
                   help="extra arguments appended to mpirun")
    p.add_argument("--network-interface", dest="nics",
                   help="comma-separated interfaces to restrict control "
                        "and data traffic to (narrows NIC discovery and "
                        "pins GLOO_SOCKET_IFNAME)")
    p.add_argument("--start-timeout", type=int, default=30)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--output-filename", dest="output_filename",
                   help="per-rank stdout/stderr directory")
    p.add_argument("--config-file", dest="config_file")
    p.add_argument("--check-build", action="store_true",
                   help="print capability report and exit")

    # elastic (reference --min-np/--max-np/--host-discovery-script)
    p.add_argument("--min-np", type=int, dest="min_np")
    p.add_argument("--max-np", type=int, dest="max_np")
    p.add_argument("--slots-per-host", type=int, dest="slots",
                   help="default slot count for discovered hosts")
    p.add_argument("--host-discovery-script", dest="host_discovery_script")
    p.add_argument("--elastic-timeout", type=int, default=600)
    p.add_argument("--reset-limit", type=int, dest="reset_limit",
                   help="stop after this many elastic resets (reference "
                        "--reset-limit)")

    # knobs → env (reference config_parser flag set)
    p.add_argument("--fusion-threshold-mb", type=int,
                   dest="fusion_threshold_mb")
    p.add_argument("--cycle-time-ms", type=float, dest="cycle_time_ms")
    p.add_argument("--cache-capacity", type=int, dest="cache_capacity")
    p.add_argument("--disable-cache", action="store_const", const=True,
                   dest="disable_cache",
                   help="re-run launch-time discovery (NIC ring probe) "
                        "instead of using cached results (reference "
                        "--disable-cache), and disable the "
                        "response-cache analogue "
                        "(sets HOROVOD_CACHE_CAPACITY=0)")
    p.add_argument("--autotune", action="store_const", const=True,
                   dest="autotune")
    p.add_argument("--autotune-log-file", dest="autotune_log_file")
    p.add_argument("--autotune-warmup-samples", type=int,
                   dest="autotune_warmup_samples")
    p.add_argument("--autotune-steps-per-sample", type=int,
                   dest="autotune_steps_per_sample")
    p.add_argument("--autotune-bayes-opt-max-samples", type=int,
                   dest="autotune_bayes_opt_max_samples")
    p.add_argument("--autotune-gaussian-process-noise", type=float,
                   dest="autotune_gaussian_process_noise")
    p.add_argument("--log-level", dest="log_level",
                   choices=["trace", "debug", "info", "warning", "error",
                            "fatal"])
    p.add_argument("--log-hide-timestamp", action="store_const", const=True,
                   dest="log_hide_timestamp")
    p.add_argument("--timeline-filename", dest="timeline_filename")
    p.add_argument("--timeline-mark-cycles", action="store_const", const=True,
                   dest="timeline_mark_cycles")
    p.add_argument("--no-stall-check", action="store_const", const=True,
                   dest="no_stall_check")
    p.add_argument("--stall-warning-time-seconds", type=float,
                   dest="stall_warning_time_seconds")
    p.add_argument("--stall-shutdown-time-seconds", type=float,
                   dest="stall_shutdown_time_seconds")
    p.add_argument("--mesh-shape", dest="mesh_shape",
                   help='TPU mesh override "dcn,ici"')
    p.add_argument("--tpu-operations", dest="tpu_operations")

    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command")
    return p.parse_args(argv)


def _resolve_hosts(args) -> List[HostInfo]:
    if args.hosts and args.hostfile:
        raise ValueError("specify --hosts or --hostfile, not both")
    if args.hostfile:
        return parse_hostfile(args.hostfile)
    if args.hosts:
        return parse_hosts(args.hosts)
    from horovod_tpu.runner.cluster_env import detect_cluster_hosts

    detected = detect_cluster_hosts()
    if detected:   # LSF / TPU pod: host list with zero flags
        return detected
    return [HostInfo("localhost", args.np)]


def _is_local(hostname: str) -> bool:
    return hostname in _LOCAL_NAMES or hostname == socket.gethostname()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _coordinator_addr(hosts: List[HostInfo]) -> str:
    """jax.distributed coordinator on rank 0's host (the rendezvous-server
    analogue, reference ``gloo_run.py:213``)."""
    head = hosts[0].hostname
    if _is_local(head):
        head = "127.0.0.1"
    return f"{head}:{_free_port()}"


def _discover_coordinator_addr(hosts: List[HostInfo], args) -> str:
    """Multi-host coordinator addressing via the NIC ring probe: start a
    probe task on every host (ssh for remote ones), compute the
    interfaces every consecutive pair can route over, and address the
    coordinator by rank-0's IP on a common interface (reference
    ``get_common_interfaces`` + driver/task services,
    ``driver_service.py:124-193``) — instead of hoping ``hosts[0]``'s
    name resolves identically from every worker."""
    import subprocess

    from horovod_tpu.runner.driver_service import probe_common_and_rank0
    from horovod_tpu.runner.network import make_secret_key

    hostnames = [h.hostname for h in hosts]
    if all(_is_local(h) for h in hostnames):
        return _coordinator_addr(hosts)
    key = make_secret_key()
    requested_nics = set(args.nics.split(",")) if args.nics else None
    procs = []

    def spawn(host: str, index: int, driver_addrs: str) -> None:
        # the key rides the command line, not the env — ssh does not
        # forward env vars (the reference ships settings incl. the key
        # base64-encoded in the remote command, driver_service.py:49-84)
        cmd = [sys.executable, "-m", "horovod_tpu.runner.probe_task",
               driver_addrs, str(index), key]
        slot = SlotInfo(hostname=host, rank=index, local_rank=0,
                        cross_rank=0, size=len(hostnames), local_size=1,
                        cross_size=len(hostnames))
        full = build_worker_command(slot, cmd, args.ssh_port,
                                    args.ssh_identity_file)
        procs.append(subprocess.Popen(full,
                                      stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL))

    try:
        # repeated launches against one host set skip the ssh+probe
        # round trip via the on-disk TTL cache (reference
        # runner/util/cache.py; --disable-cache forces a fresh probe)
        cache = None
        if not getattr(args, "disable_cache", None):
            from horovod_tpu.runner.cache import DiscoveryCache

            cache = DiscoveryCache()
        common, rank0_ips = probe_common_and_rank0(
            hostnames, spawn, key, cache=cache,
            validate_port=args.ssh_port or 22)
        if requested_nics is not None:
            # --network-interface: the user's list wins, but the probe
            # still supplies rank-0's IP on that interface (the launcher
            # cannot know it otherwise) and fails loudly if the requested
            # interface is not mutually routable
            narrowed = [i for i in common if i in requested_nics]
            if not narrowed:
                raise RuntimeError(
                    f"--network-interface {args.nics} matches none of "
                    f"the mutually-routable interfaces {common}")
            common = narrowed
        iface = next(i for i in common if i in rank0_ips)
        ip = rank0_ips[iface]
        if args.verbose:
            print(f"[launcher] common interfaces: {common}; coordinator "
                  f"on {ip}", file=sys.stderr)
        return f"{ip}:{_free_port()}"
    finally:
        # reap without masking the primary error: stragglers get
        # terminated, then killed — never re-raise from cleanup
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:
                p.terminate()
                try:
                    p.wait(timeout=2)
                except Exception:
                    p.kill()


def build_worker_command(slot: SlotInfo, command: List[str],
                         ssh_port: Optional[int] = None,
                         ssh_identity_file: Optional[str] = None
                         ) -> List[str]:
    """Local slots exec directly; remote slots go through ssh (reference
    ``gloo_run.py:113-180`` ssh/exec split).  Remote args are
    ``shlex.quote``d — naive single-quoting corrupts any argument that
    itself contains a quote."""
    import shlex

    if _is_local(slot.hostname):
        return list(command)
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    if ssh_port:
        # options must precede the destination — ssh stops parsing at it
        ssh += ["-p", str(ssh_port)]
    ssh.append(slot.hostname)
    return ssh + [" ".join(shlex.quote(c) for c in command)]


SSH_CHECK_TIMEOUT_S = 30


def check_all_hosts_ssh_successful(hostnames: List[str],
                                   ssh_port: Optional[int] = None,
                                   ssh_identity_file: Optional[str] = None,
                                   runner=None) -> None:
    """Verify every remote host is ssh-reachable before fan-out
    (reference ``_check_all_hosts_ssh_successful``, ``launch.py:55-104``)
    — one bad host should fail the launch immediately with a named
    culprit, not hang N-1 healthy workers.  ``runner`` is injectable for
    tests; defaults to running the composed ssh command."""
    import shlex
    import subprocess

    def default_runner(cmd: List[str]) -> int:
        try:
            return subprocess.run(
                cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=SSH_CHECK_TIMEOUT_S).returncode
        except subprocess.TimeoutExpired:
            return 255

    run = runner or default_runner
    remote = [h for h in hostnames if not _is_local(h)]
    results: Dict[str, int] = {}
    lock = threading.Lock()

    def check(host: str) -> None:
        cmd = ["ssh", "-o", "BatchMode=yes",
               "-o", "StrictHostKeyChecking=no"]
        if ssh_identity_file:
            cmd += ["-i", ssh_identity_file]
        if ssh_port:
            cmd += ["-p", str(ssh_port)]
        cmd += [host, shlex.quote("true")]
        rc = run(cmd)
        with lock:
            results[host] = rc

    threads = [threading.Thread(target=check, args=(h,), daemon=True)
               for h in remote]
    for t in threads:
        t.start()
    for t in threads:
        t.join(SSH_CHECK_TIMEOUT_S + 5)
    failed = sorted(h for h, rc in results.items() if rc != 0)
    failed += sorted(h for h in remote if h not in results)
    if failed:
        raise RuntimeError(
            "SSH was unable to connect to hosts: {}\n"
            "Check that every host is reachable, accepts passwordless "
            "ssh, and that --ssh-port matches.".format(", ".join(failed)))


def build_worker_env(slot: SlotInfo, base_env: Dict[str, str],
                     coordinator_addr: str) -> Dict[str, str]:
    env = dict(base_env)
    env.update(slot.to_env())
    env["HOROVOD_COORDINATOR_ADDR"] = coordinator_addr
    # HOROVOD_RANK/SIZE name the *process* world for jax.distributed
    env["HOROVOD_CONTROLLER"] = "jax"
    return env


def _run_jsrun(args, hosts: List[HostInfo]) -> int:
    """LSF/jsrun launch: one jsrun command with an ERF rankfile places
    every rank; workers read identity from the PMIx env (reference
    ``run_controller`` jsrun branch, ``launch.py:632`` + ``js_run.py``)."""
    from horovod_tpu.runner import js_run

    env = config_parser.set_env_from_args(dict(os.environ), args)
    env["HOROVOD_COORDINATOR_ADDR"] = _coordinator_addr(hosts)
    env["HOROVOD_SIZE"] = str(args.np)
    return js_run.js_run(args, hosts, env)


def _run_mpi(args, hosts: List[HostInfo]) -> int:
    """mpirun launch: mpirun places the ranks; workers read identity
    from the OMPI/PMIx env (reference ``mpi_run.py``)."""
    from horovod_tpu.runner import mpi_run

    env = config_parser.set_env_from_args(dict(os.environ), args)
    env["HOROVOD_COORDINATOR_ADDR"] = _coordinator_addr(hosts)
    env["HOROVOD_SIZE"] = str(args.np)
    return mpi_run.mpi_run(args, hosts, env)


def _run_static(args) -> int:
    hosts = _resolve_hosts(args)
    if args.jsrun:
        return _run_jsrun(args, hosts)
    if args.use_mpi:
        return _run_mpi(args, hosts)
    check_all_hosts_ssh_successful([h.hostname for h in hosts],
                                   args.ssh_port, args.ssh_identity_file)
    assignments = get_host_assignments(hosts, args.np, args.np)
    coordinator = _discover_coordinator_addr(hosts, args)
    base_env = config_parser.set_env_from_args(dict(os.environ), args)

    if args.verbose:
        for s in assignments:
            print(f"[launcher] rank {s.rank} -> {s.hostname} "
                  f"(local {s.local_rank}/{s.local_size})", file=sys.stderr)

    failures: List[int] = []
    abort = threading.Event()
    threads = []
    out_dir = args.output_filename
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)

    def run_slot(slot: SlotInfo):
        cmd = build_worker_command(slot, args.command, args.ssh_port,
                                   args.ssh_identity_file)
        env = build_worker_env(slot, base_env, coordinator)
        stdout = stderr = None
        if out_dir:
            stdout = open(os.path.join(out_dir, f"rank.{slot.rank}.out"), "wb")
            stderr = open(os.path.join(out_dir, f"rank.{slot.rank}.err"), "wb")
        try:
            rc = safe_shell_exec.execute(cmd, env=env, stdout=stdout,
                                         stderr=stderr, events=[abort])
        finally:
            for f in (stdout, stderr):
                if f:
                    f.close()
        if rc != 0:
            failures.append(rc)
            abort.set()   # fail fast: kill the whole job (reference
            #               gloo_run kills all on any failure)

    for slot in assignments:
        t = threading.Thread(target=run_slot, args=(slot,), daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join()
    return failures[0] if failures else 0


def _check_build() -> int:
    import horovod_tpu as hvd

    print("horovod_tpu v" + hvd.__version__)
    print("Available backends:")
    print(f"    [{'X' if hvd.xla_built() else ' '}] XLA")
    print(f"    [{'X' if hvd.tpu_available() else ' '}] TPU")
    print(f"    [{'X' if hvd.mpi_built() else ' '}] MPI")
    print(f"    [{'X' if hvd.gloo_built() else ' '}] Gloo")
    print(f"    [{'X' if hvd.nccl_built() else ' '}] NCCL")
    print(f"Eager data plane (HOROVOD_TPU_OPERATIONS): "
          f"{hvd.current_operations()}")
    return 0


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        import horovod_tpu as hvd

        print(hvd.__version__)
        return 0
    if args.check_build:
        return _check_build()
    if args.config_file:
        config_parser.apply_config_defaults(
            args, config_parser.load_config_file(args.config_file))
    if not args.command:
        raise SystemExit("no training command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.np is None and not args.host_discovery_script:
        raise SystemExit("-np is required")

    elastic = bool(args.host_discovery_script or args.min_np or args.max_np)
    if elastic:
        from horovod_tpu.elastic.launch import run_elastic

        return run_elastic(args)
    return _run_static(args)


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
