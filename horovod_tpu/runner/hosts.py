"""Host/slot parsing and rank assignment.

Reference: ``horovod/runner/common/util/hosts.py`` (``SlotInfo``,
``parse_hosts``, ``get_host_assignments:106`` — round-robin ranks over
hosts with local/cross rank computation) and ``--hostfile`` handling in
``runner/launch.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(host_string: str) -> "HostInfo":
        if ":" in host_string:
            hostname, slots = host_string.rsplit(":", 1)
            return HostInfo(hostname.strip(), int(slots))
        return HostInfo(host_string.strip(), 1)


@dataclasses.dataclass
class SlotInfo:
    """One worker process's identity (reference ``SlotInfo``): global,
    node-local and cross-node (one-per-host) ranks and sizes."""

    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int

    def to_env(self) -> Dict[str, str]:
        """The worker env contract (reference ``gloo_context.cc:47-55``)."""
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``"h1:2,h2:4"`` (reference ``parse_hosts``)."""
    return [HostInfo.from_string(s)
            for s in hosts_string.split(",") if s.strip()]


def parse_hostfile(path: str) -> List[HostInfo]:
    """Parse a hostfile with ``hostname slots=N`` or ``hostname:N`` lines
    (reference ``launch.py`` hostfile format)."""
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "slots=" in line:
                name, _, slots = line.partition("slots=")
                hosts.append(HostInfo(name.strip(), int(slots.strip())))
            else:
                hosts.append(HostInfo.from_string(line))
    return hosts


def get_host_assignments(hosts: List[HostInfo], min_np: int,
                         max_np: Optional[int] = None) -> List[SlotInfo]:
    """Assign ``rank/local_rank/cross_rank`` over hosts in order
    (reference ``get_host_assignments:106``): ranks fill each host's slots
    before moving on, so consecutive ranks share a host — the layout that
    keeps intra-node (ICI) neighbors adjacent.

    Raises when fewer than ``min_np`` slots exist; assigns at most
    ``max_np``.
    """
    total = sum(h.slots for h in hosts)
    if total < min_np:
        raise ValueError(
            f"requested {min_np} processes but hosts supply only {total} "
            f"slots: {', '.join(f'{h.hostname}:{h.slots}' for h in hosts)}")
    np_ = min(total, max_np) if max_np else min_np

    assignments: List[SlotInfo] = []
    local_sizes: Dict[str, int] = {}
    rank = 0
    for cross_rank, host in enumerate(hosts):
        if rank >= np_:
            break
        take = min(host.slots, np_ - rank)
        for local_rank in range(take):
            assignments.append(SlotInfo(
                hostname=host.hostname, rank=rank, local_rank=local_rank,
                cross_rank=cross_rank, size=0, local_size=take,
                cross_size=0))
            rank += 1
        local_sizes[host.hostname] = take
    n_hosts = len(local_sizes)
    for s in assignments:
        s.size = rank
        s.cross_size = n_hosts
    return assignments
