"""Cluster scheduler introspection: derive hosts from the environment.

Reference: ``horovod/runner/util/lsf.py`` (``LSFUtils`` reads
``LSB_MCPU_HOSTS``/``CSM_ALLOCATION_ID`` to build the host list for
jsrun/LSF clusters) and ``js_run.py``.  TPU-native addition: GKE/GCE TPU
pod environments publish ``TPU_WORKER_HOSTNAMES``/``TPU_WORKER_ID`` —
the same introspection gives `hvdrun` a host list with zero flags on a
pod.
"""

from __future__ import annotations

import os
from typing import List, Optional

from horovod_tpu.runner.hosts import HostInfo


class LSFUtils:
    """LSF batch-system introspection (reference ``LSFUtils``)."""

    @staticmethod
    def using_lsf() -> bool:
        return "LSB_JOBID" in os.environ

    @staticmethod
    def get_compute_hosts() -> List[HostInfo]:
        """Parse ``LSB_MCPU_HOSTS`` ("batch_host 1 host1 N host2 N ...");
        the first entry is the launch/batch node and carries no compute
        slots (reference ``lsf.py`` skips it)."""
        raw = os.environ.get("LSB_MCPU_HOSTS", "").split()
        pairs = list(zip(raw[0::2], raw[1::2]))
        return [HostInfo(h, int(s)) for h, s in pairs[1:]]

    @staticmethod
    def get_num_processes() -> int:
        return sum(h.slots for h in LSFUtils.get_compute_hosts())

    # Node-shape introspection for the jsrun ERF rankfile (reference
    # queries CSM allocation + remote lscpu, ``lsf.py:42-103``; here the
    # values come from the LSF/user env with local-machine fallbacks —
    # no CSM daemon on TPU clusters).
    @staticmethod
    def get_num_cores() -> int:
        v = os.environ.get("HOROVOD_LSF_CORES_PER_NODE")
        if v:
            return int(v)
        return os.cpu_count() or 1

    @staticmethod
    def get_num_threads() -> int:
        return int(os.environ.get("HOROVOD_LSF_THREADS_PER_CORE", "1"))

    @staticmethod
    def get_num_accelerators() -> int:
        """Accelerators (TPU chips / GPUs) per node — bounds the slot
        count a host may carry in the rankfile (reference
        ``get_num_gpus``)."""
        v = os.environ.get("HOROVOD_LSF_ACCELERATORS_PER_NODE")
        if v:
            return int(v)
        hosts = LSFUtils.get_compute_hosts()
        return max((h.slots for h in hosts), default=1)


class TpuPodUtils:
    """TPU pod slice introspection from the runtime-provided env."""

    @staticmethod
    def using_tpu_pod() -> bool:
        return "TPU_WORKER_HOSTNAMES" in os.environ

    @staticmethod
    def get_compute_hosts(slots_per_host: int = 1) -> List[HostInfo]:
        names = [h.strip() for h in
                 os.environ["TPU_WORKER_HOSTNAMES"].split(",") if h.strip()]
        return [HostInfo(h, slots_per_host) for h in names]

    @staticmethod
    def worker_id() -> Optional[int]:
        wid = os.environ.get("TPU_WORKER_ID")
        return int(wid) if wid is not None else None


def jsm_identity() -> Optional[dict]:
    """Per-process identity from the PMIx/JSM env that ``jsrun`` (and
    OpenMPI's mpirun) set on each spawned rank — the worker-side half of
    the jsrun launch path.  Returns ``{rank, size, local_rank,
    local_size}`` or None outside such a launcher."""
    for rank_var, size_var, lrank_var, lsize_var in (
            ("PMIX_RANK", "PMIX_SIZE", "PMIX_LOCAL_RANK", "PMIX_LOCAL_SIZE"),
            ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE",
             "OMPI_COMM_WORLD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_SIZE"),
            # MPICH hydra (reference supports MPICH, mpi_run.py:117)
            ("PMI_RANK", "PMI_SIZE",
             "MPI_LOCALRANKID", "MPI_LOCALNRANKS"),
    ):
        if rank_var in os.environ and size_var in os.environ:
            return {
                "rank": int(os.environ[rank_var]),
                "size": int(os.environ[size_var]),
                "local_rank": int(os.environ.get(lrank_var, "0")),
                "local_size": int(os.environ.get(lsize_var, "1")),
            }
    return None


def detect_cluster_hosts() -> Optional[List[HostInfo]]:
    """Host list from the ambient scheduler, or None outside any cluster
    (the ``hvdrun`` no-flags path on LSF and TPU pods)."""
    if LSFUtils.using_lsf():
        hosts = LSFUtils.get_compute_hosts()
        if hosts:
            return hosts
    if TpuPodUtils.using_tpu_pod():
        hosts = TpuPodUtils.get_compute_hosts()
        # single-host "pods" (e.g. a tunneled dev chip exporting
        # TPU_WORKER_HOSTNAMES=localhost) are not a cluster — let the
        # launcher's localhost default size the slot count from -np
        if len(hosts) > 1:
            return hosts
    return None
