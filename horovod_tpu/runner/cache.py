"""On-disk TTL cache for launch-time discovery results.

Reference: ``horovod/runner/util/cache.py`` — the launcher memoizes
expensive pre-flight discovery (NIC routability probes) in a JSON file
under the user's cache dir, keyed by the call parameters, with entries
expiring after a staleness threshold; ``--disable-cache`` bypasses it.
Repeated launches against the same host set then skip the multi-second
ssh + ring-probe round trip.
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Any, Optional

from horovod_tpu.utils import logging as hvd_logging

DEFAULT_TTL_S = 600.0
_TTL_ENV = "HOROVOD_TPU_DISCOVERY_CACHE_TTL"


def tcp_reachable(ip: str, port: int = 22, timeout_s: float = 1.0) -> bool:
    """Cheap liveness check for a cached rank-0 IP: one TCP connect.

    A completed handshake proves the host is up and routable; so does a
    REFUSED connect (the RST came *from that host* — nothing listening
    on ``port`` is fine, we only validate addressing).  Only a timeout
    or a routing error (host renumbered, NIC gone, network moved) marks
    the cached IP stale.  Well inside the TTL a host can re-IP — DHCP
    churn, pod rescheduling — and a launcher that trusts the entry then
    burns the full startup timeout; one connect costs ~an RTT."""
    try:
        with socket.create_connection((ip, port), timeout=timeout_s):
            return True
    except ConnectionRefusedError:
        return True
    except OSError:
        return False


def _default_path() -> str:
    root = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(root, "horovod_tpu", "discovery_cache.json")


class DiscoveryCache:
    """``{key: (timestamp, value)}`` in one JSON file.

    Keys are JSON-serialized (sorted) parameter dicts; values must be
    JSON-serializable.  The file is re-read on every ``get`` — launches
    are seconds apart, not microseconds, and rereads keep concurrent
    launchers coherent enough (last-writer-wins, same as the
    reference's fcntl-less fallback behavior)."""

    def __init__(self, path: Optional[str] = None,
                 ttl_s: Optional[float] = None):
        self._path = path or _default_path()
        self._ttl = ttl_s if ttl_s is not None else \
            float(os.environ.get(_TTL_ENV, DEFAULT_TTL_S))

    @staticmethod
    def _key(params: Any) -> str:
        return json.dumps(params, sort_keys=True)

    def _load(self) -> dict:
        try:
            with open(self._path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def get(self, params: Any):
        """The cached value for ``params``, or None when missing or
        older than the TTL."""
        entry = self._load().get(self._key(params))
        if not entry:
            return None
        ts, value = entry
        if time.time() - ts > self._ttl:
            return None
        return value

    def put(self, params: Any, value: Any) -> None:
        data = self._load()
        data[self._key(params)] = (time.time(), value)
        try:
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = self._path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._path)    # atomic vs concurrent readers
        except OSError as e:
            hvd_logging.debug("discovery cache write failed: %s", e)
