"""CLI args / YAML config → ``HOROVOD_*`` env plumbing.

Reference: ``horovod/runner/common/util/config_parser.py`` — a YAML
``--config-file`` populates defaults for CLI args, and resolved args are
exported as the env vars the core reads (three converging config layers,
SURVEY §5.6).  Same contract here; the knob names match
``runtime/config.py``.
"""

from __future__ import annotations

from typing import Any, Dict

# YAML section.key → (CLI arg attribute, env var)
_PARAMS = [
    ("fusion.threshold_mb", "fusion_threshold_mb", "HOROVOD_FUSION_THRESHOLD"),
    ("fusion.cycle_time_ms", "cycle_time_ms", "HOROVOD_CYCLE_TIME"),
    ("cache.capacity", "cache_capacity", "HOROVOD_CACHE_CAPACITY"),
    ("autotune.enabled", "autotune", "HOROVOD_AUTOTUNE"),
    ("autotune.log_file", "autotune_log_file", "HOROVOD_AUTOTUNE_LOG"),
    ("autotune.warmup_samples", "autotune_warmup_samples",
     "HOROVOD_AUTOTUNE_WARMUP_SAMPLES"),
    ("autotune.steps_per_sample", "autotune_steps_per_sample",
     "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE"),
    ("autotune.bayes_opt_max_samples", "autotune_bayes_opt_max_samples",
     "HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"),
    ("autotune.gaussian_process_noise", "autotune_gaussian_process_noise",
     "HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"),
    ("logging.level", "log_level", "HOROVOD_LOG_LEVEL"),
    ("logging.hide_timestamp", "log_hide_timestamp",
     "HOROVOD_LOG_HIDE_TIME"),
    ("timeline.filename", "timeline_filename", "HOROVOD_TIMELINE"),
    ("timeline.mark_cycles", "timeline_mark_cycles",
     "HOROVOD_TIMELINE_MARK_CYCLES"),
    ("stall_check.disable", "no_stall_check", "HOROVOD_STALL_CHECK_DISABLE"),
    ("stall_check.warning_time_seconds", "stall_warning_time_seconds",
     "HOROVOD_STALL_CHECK_TIME_SECONDS"),
    ("stall_check.shutdown_time_seconds", "stall_shutdown_time_seconds",
     "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"),
    ("library_options.mesh_shape", "mesh_shape", "HOROVOD_TPU_MESH_SHAPE"),
    ("library_options.tpu_operations", "tpu_operations",
     "HOROVOD_TPU_OPERATIONS"),
]


def load_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        return yaml.safe_load(f) or {}


def apply_config_defaults(args, config: Dict[str, Any]) -> None:
    """Fill unset CLI args from the YAML config (CLI wins — reference
    ``config_parser`` precedence)."""
    for dotted, attr, _ in _PARAMS:
        if getattr(args, attr, None) is not None:
            continue
        section, _, key = dotted.partition(".")
        value = (config.get(section) or {}).get(key)
        if value is not None:
            setattr(args, attr, value)


def set_env_from_args(env: Dict[str, str], args) -> Dict[str, str]:
    """Export resolved args as the worker env contract (reference
    ``set_env_from_args``)."""
    for _, attr, env_var in _PARAMS:
        value = getattr(args, attr, None)
        if value is None:
            continue
        if isinstance(value, bool):
            if value:
                env[env_var] = "1"
        elif attr == "fusion_threshold_mb":
            env[env_var] = str(int(value) * 1024 * 1024)
        else:
            env[env_var] = str(value)
    # --disable-cache is the reference's spelling for cache capacity 0
    if getattr(args, "disable_cache", None):
        env["HOROVOD_CACHE_CAPACITY"] = "0"
    # restrict gloo's CPU collectives to the requested interfaces
    if getattr(args, "nics", None):
        env["GLOO_SOCKET_IFNAME"] = args.nics
    return env
