"""``python -m horovod_tpu.runner`` == ``hvdrun``."""

from horovod_tpu.runner.launch import main

main()
