"""Programmatic launcher: ``horovod_tpu.runner.run(fn, ...)``.

Reference: ``horovod/runner/__init__.py:90`` — pickle ``fn`` with
cloudpickle, launch the distributed job, collect and return the per-rank
return values (tested by ``test/test_interactiverun.py``).  The function
travels and the results return over the launcher's HMAC-authenticated
:class:`~horovod_tpu.runner.network.BasicService` (the KVStoreServer
analogue, ``runner/http/http_server.py``).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable, List, Optional

from horovod_tpu.runner import launch as launch_mod
from horovod_tpu.runner.network import (
    AckResponse,
    BasicClient,
    BasicService,
    make_secret_key,
)


class GetFuncRequest:
    pass


class FuncResponse:
    def __init__(self, payload: bytes):
        self.payload = payload


class ResultRequest:
    def __init__(self, rank: int, payload: bytes):
        self.rank = rank
        self.payload = payload


def run(fn: Callable, args=(), kwargs=None, np: int = 1,
        hosts: Optional[str] = None, verbose: bool = False,
        extra_env: Optional[dict] = None) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` workers; returns the list of
    per-rank return values in rank order."""
    import cloudpickle

    payload = cloudpickle.dumps((fn, tuple(args), dict(kwargs or {})))
    key = make_secret_key()
    results: dict = {}
    done = threading.Event()

    def handler(req):
        if isinstance(req, GetFuncRequest):
            return FuncResponse(payload)
        if isinstance(req, ResultRequest):
            import pickle

            results[req.rank] = pickle.loads(req.payload)
            if len(results) == np:
                done.set()
            return AckResponse()
        raise ValueError(f"unexpected request {type(req).__name__}")

    service = BasicService("run_service", key, handler, host="127.0.0.1")
    service.start()
    try:
        host_addr = f"127.0.0.1:{service.port}"
        argv = ["-np", str(np)]
        if hosts:
            argv += ["-H", hosts]
        if verbose:
            argv += ["--verbose"]
        argv += ["--", sys.executable, "-m", "horovod_tpu.runner.run_task"]
        os.environ["HOROVOD_RUN_SERVICE_ADDR"] = host_addr
        os.environ["HOROVOD_RUN_SECRET"] = key
        for k, v in (extra_env or {}).items():
            os.environ[k] = v
        try:
            rc = launch_mod.run_commandline(argv)
        finally:
            os.environ.pop("HOROVOD_RUN_SERVICE_ADDR", None)
            os.environ.pop("HOROVOD_RUN_SECRET", None)
        if rc != 0:
            raise RuntimeError(f"horovod_tpu.runner.run failed with exit "
                               f"code {rc}")
        if not done.wait(timeout=30):
            missing = sorted(set(range(np)) - set(results))
            raise RuntimeError(f"no results from ranks {missing}")
        return [results[r] for r in range(np)]
    finally:
        service.shutdown()


def _task_main() -> None:
    """Worker entry (``python -m horovod_tpu.runner.run_task``): fetch the
    function, execute, report the result."""
    import pickle

    import cloudpickle

    addr = os.environ["HOROVOD_RUN_SERVICE_ADDR"]
    key = os.environ["HOROVOD_RUN_SECRET"]
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    host, port = addr.rsplit(":", 1)
    client = BasicClient((host, int(port)), key)
    fn, args, kwargs = cloudpickle.loads(
        client.request(GetFuncRequest()).payload)
    result = fn(*args, **kwargs)
    client.request(ResultRequest(rank, pickle.dumps(result)))
