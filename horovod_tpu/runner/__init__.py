"""Launcher: hvdrun CLI, host assignment, rendezvous, elastic driver plumbing.

Reference: ``horovod/runner/`` (launch.py CLI, gloo_run/mpi_run, driver and
task services, elastic driver).  Programmatic entry:
``horovod_tpu.runner.run(fn, np=4)`` (reference ``horovod.run``,
``runner/__init__.py:90``).
"""

from horovod_tpu.runner.api import run

__all__ = ["run"]
