"""Launcher: hvdrun CLI, host assignment, rendezvous, elastic driver plumbing.

Reference: ``horovod/runner/`` (launch.py CLI, gloo_run/mpi_run, driver and
task services, elastic driver).
"""
