"""Pre-launch NIC discovery: find interfaces every worker can route to.

Reference: ``horovod/runner/driver/driver_service.py:124-193`` — before
fanning out the real job, the launcher starts a tiny task server on each
host; every task registers its per-interface addresses with the driver,
then task *i* is asked to probe task *i+1*'s addresses ("the ring
trick": if every consecutive pair is mutually routable on an interface
set, the full mesh is, for any symmetric network).  The launcher then
restricts rendezvous/coordinator addressing to the common interfaces
instead of hoping ``hosts[0]`` resolves from everywhere.

TPU edition: the same ring probe over the existing ``BasicService``
control plane.  Task servers are started via the worker command path
(ssh for remote hosts, direct exec locally), so the machinery is fully
exercisable on localhost without ssh — the form the tests use.
"""

from __future__ import annotations

import array
import fcntl
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from horovod_tpu.runner.network import AckResponse, BasicService, BasicClient
from horovod_tpu.utils import logging as hvd_logging

PROBE_TIMEOUT_S = 5.0


def local_interface_addresses() -> Dict[str, str]:
    """``{interface: ipv4}`` for every up interface (reference
    ``get_local_host_addresses`` / psutil.net_if_addrs; implemented with
    the SIOCGIFCONF ioctl — no psutil dependency)."""
    max_ifaces = 64
    bufsz = max_ifaces * 40
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        buf = array.array("B", b"\0" * bufsz)
        ifconf = struct.pack("iL", bufsz, buf.buffer_info()[0])
        outbytes = struct.unpack("iL", fcntl.ioctl(
            s.fileno(), 0x8912, ifconf))[0]   # SIOCGIFCONF
    raw = buf.tobytes()[:outbytes]
    out: Dict[str, str] = {}
    # each record: 16-byte name + sockaddr_in (40 bytes/entry on 64-bit)
    for off in range(0, len(raw), 40):
        name = raw[off:off + 16].split(b"\0", 1)[0].decode()
        ip = socket.inet_ntoa(raw[off + 20:off + 24])
        out[name] = ip
    return out


class RegisterProbeTaskRequest:
    """Task → driver: my index and per-interface (ip, port) listeners."""

    def __init__(self, index: int, addresses: Dict[str, Tuple[str, int]]):
        self.index = index
        self.addresses = addresses


class GetProbeTargetRequest:
    """Task → driver: whom should I probe?  Blocks via polling until all
    tasks registered; the driver answers with task (index+1)'s
    addresses, or None while the ring is incomplete."""

    def __init__(self, index: int):
        self.index = index


class ProbeTargetResponse:
    def __init__(self, addresses: Optional[Dict[str, Tuple[str, int]]]):
        self.addresses = addresses


class ProbeResultRequest:
    """Task → driver: interfaces of my ring successor I could connect
    to."""

    def __init__(self, index: int, reachable_ifaces: List[str]):
        self.index = index
        self.reachable_ifaces = reachable_ifaces


class ProbeCompleteQuery:
    """Task → driver: has the whole ring reported?  Tasks must keep
    their listeners open until then — closing after one's own probe
    races the predecessor's probe of *this* task (it would see
    connection-refused and the common set would collapse to empty)."""


class ProbeCompleteResponse:
    def __init__(self, done: bool):
        self.done = done


class ProbeDriver:
    """Driver side of the ring probe (reference ``_driver_fn``)."""

    def __init__(self, ntasks: int, secret_key: Optional[str] = None):
        self._ntasks = ntasks
        self._lock = threading.Lock()
        self._addresses: Dict[int, Dict[str, Tuple[str, int]]] = {}
        self._results: Dict[int, List[str]] = {}
        self._done = threading.Event()
        self._service = BasicService("probe_driver", secret_key,
                                     self._handle, host="0.0.0.0")
        self._service.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._service.address

    def _handle(self, req):
        if isinstance(req, RegisterProbeTaskRequest):
            with self._lock:
                self._addresses[req.index] = dict(req.addresses)
            return AckResponse()
        if isinstance(req, GetProbeTargetRequest):
            with self._lock:
                if len(self._addresses) < self._ntasks:
                    return ProbeTargetResponse(None)
                succ = (req.index + 1) % self._ntasks
                return ProbeTargetResponse(self._addresses[succ])
        if isinstance(req, ProbeResultRequest):
            with self._lock:
                self._results[req.index] = list(req.reachable_ifaces)
                if len(self._results) == self._ntasks:
                    self._done.set()
            return AckResponse()
        if isinstance(req, ProbeCompleteQuery):
            return ProbeCompleteResponse(self._done.is_set())
        raise ValueError(f"unexpected request {type(req).__name__}")

    def wait_common_interfaces(self, timeout_s: float = 60.0) -> List[str]:
        """Block until every ring probe reported; return the interfaces
        reachable on EVERY hop (reference ``get_common_interfaces``,
        ``driver_service.py:193``)."""
        if not self._done.wait(timeout_s):
            with self._lock:
                missing = [i for i in range(self._ntasks)
                           if i not in self._results]
            raise TimeoutError(
                f"NIC probe incomplete after {timeout_s}s; no result from "
                f"task(s) {missing} — host(s) unreachable or blocked. "
                f"If a previous launch cached discovery results for "
                f"these hosts (~/.cache/horovod_tpu/"
                f"discovery_cache.json), a stale entry may be "
                f"addressing a moved host: retry with --disable-cache "
                f"or delete the cache file")
        with self._lock:
            common = None
            for ifaces in self._results.values():
                s = set(ifaces)
                common = s if common is None else (common & s)
        if not common:
            raise RuntimeError(
                "No network interface is routable between all hosts "
                "(reference driver_service.py mutual-routability check)")
        return sorted(common)

    def task_address(self, index: int) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            return dict(self._addresses[index])

    def shutdown(self) -> None:
        self._service.shutdown()


def _connect_driver(driver_addrs: str, secret_key: Optional[str]
                    ) -> BasicClient:
    """Try each candidate driver address (comma-separated ``ip:port``)
    until one answers a ping — the driver advertises every local
    interface because its hostname may not resolve from worker hosts
    (the reference hands tasks the full driver address list,
    ``driver_service.py:49-84``).  The scan is retried with
    backoff+jitter under the unified policy: a probe task often races
    the driver's own bind, and one refused connect must not fail the
    whole NIC discovery."""
    from horovod_tpu.runtime.retry import RetryPolicy

    def scan() -> BasicClient:
        from horovod_tpu import faults

        # chaos hook: a transient OSError here exercises the retry
        # policy exactly as a refused connect during driver bind does
        faults.inject("probe.connect")
        last_err: Optional[Exception] = None
        for addr in driver_addrs.split(","):
            host, port = addr.rsplit(":", 1)
            client = BasicClient((host, int(port)), secret_key,
                                 timeout_s=5.0)
            try:
                if client.ping():
                    return client
            except OSError as e:
                last_err = e
        raise ConnectionError(
            f"probe task could not reach the driver at any of "
            f"[{driver_addrs}]: {last_err}")

    return RetryPolicy(name="driver-probe", retry_on=(OSError,),
                       deadline_s=30.0).call(scan)


def run_probe_task(driver_addrs: str, index: int,
                   secret_key: Optional[str] = None) -> None:
    """Task side: bind one listener per interface, register, probe the
    ring successor, report (reference ``task_fn.py`` + routability probe
    ``driver_service.py:124-190``)."""
    listeners: Dict[str, socket.socket] = {}
    addresses: Dict[str, Tuple[str, int]] = {}
    for iface, ip in local_interface_addresses().items():
        try:
            srv = socket.socket()
            srv.bind((ip, 0))
            srv.listen(8)
            listeners[iface] = srv
            addresses[iface] = (ip, srv.getsockname()[1])
        except OSError:
            continue

    accepting = True

    def accept_loop(srv: socket.socket) -> None:
        srv.settimeout(0.5)
        while accepting:
            try:
                conn, _ = srv.accept()
                conn.close()
            except socket.timeout:
                continue
            except OSError:
                return

    threads = [threading.Thread(target=accept_loop, args=(srv,),
                                daemon=True) for srv in listeners.values()]
    for t in threads:
        t.start()

    client = _connect_driver(driver_addrs, secret_key)
    client.request(RegisterProbeTaskRequest(index, addresses))
    target = None
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        resp = client.request(GetProbeTargetRequest(index))
        if resp.addresses is not None:
            target = resp.addresses
            break
        time.sleep(0.2)
    reachable = []
    if target is not None:
        for iface, (ip, tport) in target.items():
            try:
                with socket.create_connection((ip, tport),
                                              timeout=PROBE_TIMEOUT_S):
                    reachable.append(iface)
            except OSError:
                hvd_logging.debug("probe: %s (%s:%d) unreachable",
                                  iface, ip, tport)
    client.request(ProbeResultRequest(index, reachable))
    # hold listeners until the whole ring reported — the predecessor may
    # not have probed this task yet
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        try:
            if client.request(ProbeCompleteQuery()).done:
                break
        except OSError:
            break   # driver gone: discovery concluded or aborted
        time.sleep(0.2)
    accepting = False
    for srv in listeners.values():
        srv.close()


def discover_common_interfaces(hostnames: List[str], spawn_task,
                               secret_key: Optional[str] = None,
                               timeout_s: float = 60.0):
    """Run the full ring probe: start the driver, spawn one probe task
    per host via ``spawn_task(host, index, driver_addrs)``, and return
    ``(common_interfaces, driver)``.  ``driver_addrs`` is the
    comma-separated candidate list of every driver interface IP — the
    launcher's hostname may not resolve from worker hosts.  The caller
    reads coordinator addressing from ``driver.task_address(0)``
    restricted to the common set, then shuts the driver down."""
    driver = ProbeDriver(len(hostnames), secret_key)
    port = driver.address[1]
    daddrs = ",".join(f"{ip}:{port}"
                      for ip in local_interface_addresses().values())
    for idx, host in enumerate(hostnames):
        spawn_task(host, idx, daddrs)
    common = driver.wait_common_interfaces(timeout_s)
    return common, driver


def probe_common_and_rank0(hostnames: List[str], spawn_task,
                           secret_key: Optional[str] = None,
                           timeout_s: float = 60.0, cache=None,
                           validate_port: int = 22):
    """``(common_interfaces, {iface: rank0_ip})`` — the two facts a
    launcher consumes from the ring probe — with an optional on-disk TTL
    cache (reference ``runner/util/cache.py``: repeated launches against
    the same host set skip the ssh + probe round trip; an expired or
    missing entry re-probes).  Only interface/IP facts are cached —
    ports are per-run ephemera.

    A hit is trusted only after a cheap TCP connect to a cached rank-0
    IP (``validate_port``, normally the ssh port the launcher will use
    anyway): hosts can re-IP inside the TTL, and a stale address would
    otherwise surface as a full startup-timeout hang instead of one
    extra probe round trip."""
    params = {"probe": hostnames}
    if cache is not None:
        hit = cache.get(params)
        if hit is not None:
            from horovod_tpu.runner.cache import tcp_reachable

            ips = sorted(set(hit["rank0"].values()))
            if any(tcp_reachable(ip, validate_port) for ip in ips):
                hvd_logging.debug("NIC discovery: warm cache hit for %s",
                                  hostnames)
                return hit["common"], hit["rank0"]
            hvd_logging.info(
                "NIC discovery: cached rank-0 IP(s) %s failed the TCP "
                "liveness check; falling through to a fresh probe", ips)
    common, driver = discover_common_interfaces(
        hostnames, spawn_task, secret_key, timeout_s)
    try:
        rank0 = {iface: addr[0]
                 for iface, addr in driver.task_address(0).items()}
    finally:
        driver.shutdown()
    if cache is not None:
        cache.put(params, {"common": common, "rank0": rank0})
    return common, rank0
