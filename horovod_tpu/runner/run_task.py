"""Worker-side bootstrap for :func:`horovod_tpu.runner.run` (reference
``horovod/runner/task_fn.py`` role)."""

from horovod_tpu.runner.api import _task_main

if __name__ == "__main__":
    _task_main()
