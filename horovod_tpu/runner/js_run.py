"""jsrun/LSF launch path.

Reference: ``horovod/runner/js_run.py`` — on LSF clusters the launcher
does not ssh-fan-out itself; it composes a single ``jsrun`` command with
an ERF rankfile (``generate_jsrun_rankfile``, ``js_run.py:96``) that
pins each rank to a host and a cpu range, and jsrun places the
processes.  The TPU edition keeps the exact ERF format and the command
shape; instead of ``--smpiargs`` MPI plumbing the workers get their
identity from the PMIx/JSM environment (``cluster_env.jsm_identity``)
and rendezvous through ``HOROVOD_COORDINATOR_ADDR`` like every other
launch path.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import List, Optional

from horovod_tpu.runner.cluster_env import LSFUtils
from horovod_tpu.runner.hosts import HostInfo


def is_jsrun_installed() -> bool:
    """True if the ``jsrun`` launcher exists (reference
    ``is_jsrun_installed``)."""
    return shutil.which("jsrun") is not None


def generate_jsrun_rankfile(hosts: List[HostInfo], np: int,
                            path: Optional[str] = None,
                            cores_per_node: Optional[int] = None,
                            threads_per_core: Optional[int] = None,
                            accelerators_per_node: Optional[int] = None,
                            ) -> str:
    """Write the ERF rankfile splitting cores among ranks (reference
    ``generate_jsrun_rankfile`` — same header directives and ``rank:``
    line format, with slot validation against the per-node accelerator
    count)."""
    cores = cores_per_node or LSFUtils.get_num_cores()
    threads = threads_per_core or LSFUtils.get_num_threads()
    accels = accelerators_per_node or LSFUtils.get_num_accelerators()
    cpu_per_slot = max((cores * threads) // max(accels, 1), 1)

    validated: List[HostInfo] = []
    remaining = np
    for h in hosts:
        if h.slots > accels:
            raise ValueError(
                f"host '{h.hostname}' requests {h.slots} slots but each "
                f"node exposes only {accels} accelerator(s); cap its slot "
                f"count at the per-node accelerator count")
        needed = min(h.slots, remaining)
        validated.append(HostInfo(h.hostname, needed))
        remaining -= needed
        if remaining == 0:
            break
    if remaining != 0:
        raise ValueError(
            f"the host list provides too few slots for -np {np}: "
            f"{np - remaining} available across {len(validated)} host(s)")

    if path is None:
        fd, path = tempfile.mkstemp(prefix="hvd_jsrun_", suffix=".erf")
        os.close(fd)
    with open(path, "w") as f:
        f.write("overlapping_rs: allow\n")
        f.write("cpu_index_using: logical\n")
        rank = 0
        for h in validated:
            cpu = 0
            f.write("\n")
            for _ in range(h.slots):
                f.write(f"rank: {rank}: {{ hostname: {h.hostname}; "
                        f"cpu: {{{cpu}-{cpu + cpu_per_slot - 1}}} ; "
                        f"gpu: * ; mem: * }}\n")
                rank += 1
                cpu += cpu_per_slot
    return path


def js_run_command(command: List[str], rankfile: str,
                   output_filename: Optional[str] = None,
                   smpiargs: Optional[str] = None) -> List[str]:
    """Compose the jsrun invocation (reference ``js_run`` command
    string, ``js_run.py:73-84``) as an argv list."""
    cmd = ["jsrun", "--erf_input", rankfile]
    if output_filename:
        cmd += ["--stdio_stderr", output_filename,
                "--stdio_stdout", output_filename]
    if smpiargs:
        # argv goes to exec without a shell — pass the value raw (the
        # reference shell-quotes because it builds a shell string)
        cmd += ["--smpiargs", smpiargs]
    cmd += list(command)
    return cmd


def js_run(args, hosts: List[HostInfo], env: dict,
           stdout=None, stderr=None) -> int:
    """Launch the training command through jsrun (reference ``js_run``).

    The env carries ``HOROVOD_COORDINATOR_ADDR`` + ``HOROVOD_SIZE``;
    per-rank identity comes from the PMIx/JSM variables jsrun sets
    (``cluster_env.jsm_identity``)."""
    from horovod_tpu.runner import safe_shell_exec

    if not is_jsrun_installed():
        raise RuntimeError(
            "horovod_tpu does not find the jsrun command.\n\n"
            "Please, make sure you are running on a cluster with jsrun "
            "installed or use one of the other launchers.")
    rankfile = generate_jsrun_rankfile(hosts, args.np)
    cmd = js_run_command(args.command, rankfile,
                         output_filename=args.output_filename)
    if args.verbose:
        import sys

        print("[launcher] " + " ".join(cmd), file=sys.stderr)
    return safe_shell_exec.execute(cmd, env=env, stdout=stdout,
                                   stderr=stderr)
