"""TCP service/client plumbing for launcher ⇄ worker control traffic.

Reference: ``horovod/runner/common/util/network.py`` (``BasicService`` /
``BasicClient`` — threaded TCP servers exchanging pickled ``Wire`` frames
authenticated with an HMAC key from ``secret.py:36``) and
``runner/elastic/worker.py`` (HostsUpdated notification channel).

The data plane never touches this layer — it only carries launcher
control messages (worker registration, host-update pings, run-command
RPCs), so a simple length-prefixed pickle-with-HMAC frame is adequate and
mirrors the reference's wire format decision.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Optional, Tuple

from horovod_tpu.utils import logging as hvd_logging

_HMAC_DIGEST = hashlib.sha256
_HMAC_LEN = 32
_MAX_FRAME = 64 * 1024 * 1024


def make_secret_key() -> str:
    """Random per-run HMAC key (reference ``secret.py:make_secret_key``)."""
    return os.urandom(32).hex()


class Wire:
    """Length-prefixed pickle frame with HMAC (reference ``network.py`` Wire)."""

    def __init__(self, key: Optional[str]):
        self._key = key.encode() if key else b""

    def write(self, sock: socket.socket, obj: Any) -> None:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hmac.new(self._key, payload, _HMAC_DIGEST).digest()
        sock.sendall(struct.pack("!I", len(payload)) + digest + payload)

    def read(self, sock: socket.socket) -> Any:
        header = self._read_exact(sock, 4 + _HMAC_LEN)
        (length,) = struct.unpack("!I", header[:4])
        if length > _MAX_FRAME:
            raise IOError(f"frame too large: {length}")
        digest = header[4:]
        payload = self._read_exact(sock, length)
        expected = hmac.new(self._key, payload, _HMAC_DIGEST).digest()
        if not hmac.compare_digest(digest, expected):
            raise PermissionError("HMAC verification failed — secret key "
                                  "mismatch between launcher and worker")
        return pickle.loads(payload)

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise EOFError("connection closed mid-frame")
            buf += chunk
        return buf


class PingRequest:
    pass


class PingResponse:
    def __init__(self, service_name: str):
        self.service_name = service_name


class AckResponse:
    pass


class HostsUpdatedRequest:
    """Driver → worker: the discovered host set changed (reference
    ``runner/elastic/worker.py`` HostsUpdatedRequest)."""

    def __init__(self, timestamp: int, res: int = 0):
        self.timestamp = timestamp
        self.res = res


class RegisterWorkerRequest:
    """Worker → driver: notification-service address registration."""

    def __init__(self, rank: int, address: Tuple[str, int]):
        self.rank = rank
        self.address = address


class WorkerReadyRequest:
    """Worker → driver: this worker finished startup and entered the
    elastic training loop (reference ``WorkerStateRegistry`` READY
    barrier, ``runner/elastic/registration.py`` — worker-reported, so a
    worker hung in startup is distinguishable from a healthy one)."""

    def __init__(self, host: str, local_rank: int):
        self.host = host
        self.local_rank = local_rank


class HeartbeatRequest:
    """Worker → driver: periodic liveness beat, piggybacking the
    training step counter so the driver's progress watchdog can tell a
    hung-but-alive rank from a healthy one (``elastic/health.py``) and,
    when telemetry is enabled, the rank's counter snapshot so the
    driver aggregates per-worker metrics with no extra RPC
    (docs/metrics.md; the driver reads ``metrics`` via ``getattr`` so
    old workers interoperate)."""

    def __init__(self, host: str, local_rank: int, step: int = -1,
                 metrics: Optional[dict] = None):
        self.host = host
        self.local_rank = local_rank
        self.step = step
        self.metrics = metrics


class PlannedDepartureRequest:
    """Worker → driver: this worker is being preempted and will exit
    after committing a priority checkpoint (guard/preempt.py).  The
    driver marks it departing so the HealthMonitor stops counting it
    toward death verdicts and its exit skips blacklist/quarantine."""

    def __init__(self, host: str, local_rank: int, step: int = -1):
        self.host = host
        self.local_rank = local_rank
        self.step = step


class GetHealthyPeerRequest:
    """Diverged worker → driver: name a healthy peer (another rank,
    not suspect/departing) whose notification service can serve a
    state snapshot for peer repair (guard/repair.py)."""

    def __init__(self, host: str, local_rank: int, rank: int):
        self.host = host
        self.local_rank = local_rank
        self.rank = rank


class PeerAddressResponse:
    """Driver → worker: a healthy peer's rank and notification address
    (``address`` None when no healthy peer exists)."""

    def __init__(self, rank: int = -1,
                 address: Optional[Tuple[str, int]] = None):
        self.rank = rank
        self.address = address


class FetchStateRequest:
    """Diverged worker → healthy peer: send your committed state."""


class StateSnapshotResponse:
    """Healthy peer → diverged worker: committed ``(step, state)``
    snapshot (``state`` None when the peer has nothing committed)."""

    def __init__(self, step: int = -1, state: Any = None):
        self.step = step
        self.state = state


class BasicService:
    """Threaded TCP server dispatching pickled requests to a handler
    (reference ``BasicService``, ``network.py:268``)."""

    def __init__(self, name: str, key: Optional[str],
                 handler: Callable[[Any], Any], host: str = "0.0.0.0"):
        self._name = name
        self._wire = Wire(key)
        self._handler = handler
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = outer._wire.read(self.request)
                    if isinstance(req, PingRequest):
                        resp = PingResponse(outer._name)
                    else:
                        resp = outer._handler(req)
                    outer._wire.write(self.request, resp)
                except (EOFError, ConnectionError):
                    pass
                except PermissionError as e:
                    hvd_logging.warning("%s: rejected request: %s",
                                        outer._name, e)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, 0), _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"hvd_tpu_{name}_service")

    def start(self) -> None:
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self._server.server_address[:2]
        if host == "0.0.0.0":
            host = socket.gethostname()
        return (host, port)

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class BasicClient:
    """One-shot request/response client (reference ``BasicClient``)."""

    def __init__(self, address: Tuple[str, int], key: Optional[str],
                 timeout_s: float = 30.0):
        self._address = tuple(address)
        self._wire = Wire(key)
        self._timeout_s = timeout_s

    def request(self, obj: Any) -> Any:
        with socket.create_connection(self._address,
                                      timeout=self._timeout_s) as sock:
            self._wire.write(sock, obj)
            return self._wire.read(sock)

    def ping(self) -> bool:
        try:
            return isinstance(self.request(PingRequest()), PingResponse)
        except OSError:
            return False


class NotificationServer:
    """Worker-side listener for HostsUpdated pings (reference
    ``WorkerNotificationService``)."""

    def __init__(self, manager, key: Optional[str]):
        def handle(req):
            if isinstance(req, HostsUpdatedRequest):
                manager.handle_hosts_updated(req.timestamp, req.res)
                return AckResponse()
            if isinstance(req, FetchStateRequest):
                # peer-repair fetch (guard/repair.py) — served from the
                # provider the manager registered, if any
                fetch = getattr(manager, "handle_fetch_state", None)
                snap = fetch() if fetch is not None else None
                if snap is None:
                    return StateSnapshotResponse()
                return StateSnapshotResponse(step=snap[0], state=snap[1])
            raise ValueError(f"unexpected request {type(req).__name__}")

        self._service = BasicService("worker_notification", key, handle)

    def start(self) -> None:
        self._service.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._service.address

    def shutdown(self) -> None:
        self._service.shutdown()


def notify_worker_registered(driver_addr: str, worker_addr: Tuple[str, int],
                             key: Optional[str]) -> None:
    """Register this worker's notification address with the elastic driver.

    ``driver_addr`` is "host:port" from ``HOROVOD_ELASTIC_DRIVER_ADDR``.
    """
    host, port = driver_addr.rsplit(":", 1)
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    BasicClient((host, int(port)), key).request(
        RegisterWorkerRequest(rank, tuple(worker_addr)))


def notify_hosts_updated(worker_addr: Tuple[str, int], key: Optional[str],
                         timestamp: int, res: int = 0) -> None:
    """Driver-side: ping one worker that the host set changed."""
    BasicClient(tuple(worker_addr), key).request(
        HostsUpdatedRequest(timestamp, res))


def notify_worker_ready(driver_addr: str, key: Optional[str],
                        host: str, local_rank: int) -> None:
    """Worker-side: report READY to the elastic driver's registry."""
    dhost, port = driver_addr.rsplit(":", 1)
    BasicClient((dhost, int(port)), key).request(
        WorkerReadyRequest(host, local_rank))


def notify_planned_departure(driver_addr: str, key: Optional[str],
                             host: str, local_rank: int,
                             step: int = -1) -> None:
    """Worker-side: announce a preemption-driven departure so the
    driver treats the coming exit as planned (no blacklist, no
    quarantine, no death verdict)."""
    dhost, port = driver_addr.rsplit(":", 1)
    BasicClient((dhost, int(port)), key, timeout_s=5.0).request(
        PlannedDepartureRequest(host, local_rank, step))


def notify_heartbeat(driver_addr: str, key: Optional[str],
                     host: str, local_rank: int, step: int = -1,
                     metrics: Optional[dict] = None) -> None:
    """Worker-side: one liveness beat to the elastic driver (short
    timeout — a slow beat must not back the sender thread up)."""
    dhost, port = driver_addr.rsplit(":", 1)
    BasicClient((dhost, int(port)), key, timeout_s=5.0).request(
        HeartbeatRequest(host, local_rank, step, metrics=metrics))
