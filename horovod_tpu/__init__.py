"""horovod_tpu: a TPU-native distributed deep-learning training framework.

A ground-up re-design of the capabilities of Horovod v0.19.2 (reference:
``prpankajsingh/horovod``) for TPU hardware on JAX/XLA: the user-facing
contract — ``init()``/``rank()``/``size()``, five collectives with named
tensors and async handles, ``DistributedOptimizer``/gradient-tape
ergonomics, elastic training, a launcher, timeline tracing, autotuning —
rebuilt on SPMD compilation, ``jax.sharding.Mesh`` and XLA collectives
instead of a C++ negotiation thread over NCCL/MPI/Gloo.

Identity model (differs from the reference by design, see
``runtime/state.py``): ``size()`` is the number of *chips* (the
data-parallel degree — scale your LR by it, as reference examples do with
GPU count); ``process_rank()``/``process_count()`` give host-process
identity; ``rank() == 0`` on process 0 so "checkpoint on rank 0" carries
over.

Typical use (mirrors reference README.rst "Usage" 5-step recipe)::

    import horovod_tpu as hvd

    hvd.init()
    step = hvd.DistributedTrainStep(loss_fn, optax.adam(1e-3 * hvd.size()))
    params = hvd.broadcast_variables(params, root_rank=0)
    ...

Reference API parity map: ``horovod/common/basics.py`` (init/rank/size/
probes), ``horovod/torch/mpi_ops.py`` + ``tensorflow/mpi_ops.py``
(collectives), ``torch/optimizer.py`` + ``tensorflow/__init__.py``
(DistributedOptimizer), ``horovod/common/elastic.py`` (elastic State).
"""

from __future__ import annotations

from typing import Optional

from horovod_tpu import compat as _compat  # noqa: F401  (installs jax shims)
from horovod_tpu.ops import (
    Adasum,
    Average,
    Compression,
    Handle,
    HorovodInternalError,
    ReduceOp,
    Sum,
    allgather,
    allgather_async,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    join,
    poll,
    synchronize,
)
from horovod_tpu.runtime import state as _state
from horovod_tpu.runtime.topology import AXIS_DCN, AXIS_ICI, GLOBAL_AXES

__version__ = "0.1.0"


# ---------------------------------------------------------------------------
# basics (reference horovod/common/basics.py)
# ---------------------------------------------------------------------------

def init(ranks: Optional[list] = None, comm=None):
    """Initialize the runtime (reference ``HorovodBasics.init``,
    ``basics.py:33``; C ``horovod_init`` ``operations.cc:679``).

    ``ranks``/``comm`` are accepted for signature parity; process membership
    on TPU comes from the launcher env contract + jax.distributed.
    """
    _state.init(ranks)
    return True


def shutdown():
    """Tear down the runtime (reference ``horovod_shutdown``)."""
    _state.shutdown()


def is_initialized() -> bool:
    return _state.is_initialized()


def start_timeline(file_path: str, mark_cycles: bool = False):
    """Start timeline recording at runtime (reference
    ``horovod_start_timeline``).

    Every process may pass the same (shared) path: non-root ranks record
    to ``<file_path>.<rank>`` so two writers never share a file, and
    :func:`stop_timeline` merges everything back into ``file_path`` on
    rank 0."""
    from horovod_tpu.utils.timeline import Timeline

    st = _state.global_state()
    if st.timeline is not None:
        st.timeline.close()
    if st.process_count > 1 and st.process_rank:
        file_path = f"{file_path}.{st.process_rank}"
    st.timeline = Timeline(file_path, mark_cycles=mark_cycles)


def stop_timeline():
    """Stop recording; in a multi-process world rank 0 then gathers every
    process's events into ONE merged Chrome trace (reference rank-0
    aggregated timeline, ``timeline.cc``)."""
    from horovod_tpu.utils.timeline import aggregate_after_close

    st = _state.global_state()
    if st.timeline is not None:
        fname = getattr(st.timeline, "filename", None)
        origin = getattr(st.timeline, "wall_origin_us", None)
        st.timeline.close()
        st.timeline = None
        if fname:
            aggregate_after_close(fname, origin)


def rank() -> int:
    """Global chip-rank of this process's first device; 0 on process 0."""
    return _state.global_state().rank


def size() -> int:
    """Total number of chips == data-parallel degree."""
    return _state.global_state().size


def local_rank() -> int:
    return _state.global_state().local_rank


def local_size() -> int:
    """Chips driven by this process."""
    return _state.global_state().local_size


def cross_rank() -> int:
    """Slice index of this process (reference CROSS communicator rank)."""
    return _state.global_state().cross_rank


def cross_size() -> int:
    """Number of slices (reference CROSS communicator size)."""
    return _state.global_state().cross_size


def process_rank() -> int:
    return _state.global_state().process_rank


def process_count() -> int:
    return _state.global_state().process_count


def is_homogeneous() -> bool:
    """True when every process drives the same number of chips (reference
    ``horovod_is_homogeneous``; checked in ``mpi_controller.cc:26``)."""
    return _state.global_state().is_homogeneous


def mesh():
    """The global (dcn, ici) runtime mesh for SPMD training."""
    return _state.global_state().mesh


# -- capability probes (reference basics.py:71-233 *_built/enabled) --------

def xla_built() -> bool:
    return True


def tpu_available() -> bool:
    import jax

    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False


def native_built() -> bool:
    """True when the C++ runtime components (timeline writer, rendezvous
    KV store) compiled and loaded."""
    from horovod_tpu import native

    return native.native_built()


def mpi_built() -> bool:
    return False


def mpi_enabled() -> bool:
    return False


def gloo_built() -> bool:
    return False


def gloo_enabled() -> bool:
    return False


def nccl_built() -> bool:
    return False


def ddl_built() -> bool:
    return False


def ccl_built() -> bool:
    return False


def cuda_built() -> bool:
    return False


def rocm_built() -> bool:
    return False


def mpi_threads_supported() -> bool:
    return False


def current_operations() -> str:
    """Name of the eager data plane in use ("XLA" or "HOST"), selected by
    ``HOROVOD_TPU_OPERATIONS`` / ``--tpu-operations`` — the introspection
    probe for the op-manager priority chain (reference
    ``HOROVOD_CPU_OPERATIONS`` + ``horovod_*_built`` probes,
    ``operations.cc:784``)."""
    from horovod_tpu.ops import op_manager

    return op_manager.current_operations()


def cache_stats() -> dict:
    """Compiled-executable cache counters (reference response-cache
    observability, ``response_cache.{h,cc}``): ``hits``/``misses``
    count the in-memory signature caches (eager negotiation layer and
    each ``DistributedTrainStep``'s executable LRU, bounded by
    ``HOROVOD_CACHE_CAPACITY``); ``aot_disk_hits``/``aot_disk_misses``
    count the persistent warm-start AOT store
    (:mod:`horovod_tpu.runtime.compile_cache`).  ``bench.py`` surfaces
    all four in the BENCH JSON."""
    from horovod_tpu.runtime import state as _state

    if not _state.is_initialized():
        return {"hits": 0, "misses": 0,
                "aot_disk_hits": 0, "aot_disk_misses": 0}
    return dict(_state.global_state().cache_stats)


# ---------------------------------------------------------------------------
# higher-level API re-exports (populated by submodule imports)
# ---------------------------------------------------------------------------

from horovod_tpu.functions import (  # noqa: E402
    allgather_object,
    broadcast_object,
    broadcast_optimizer_state,
    broadcast_parameters,
    broadcast_variables,
)
from horovod_tpu.optim import (  # noqa: E402
    DistributedAdasumOptimizer,
    DistributedGradientTape,
    DistributedOptimizer,
    DistributedTrainStep,
    SyncBatchNorm,
)
from horovod_tpu import callbacks  # noqa: E402,F401
from horovod_tpu import checkpoint  # noqa: E402,F401
from horovod_tpu import data  # noqa: E402,F401
from horovod_tpu import elastic  # noqa: E402,F401
from horovod_tpu import faults  # noqa: E402,F401
from horovod_tpu import guard  # noqa: E402,F401

__all__ = [
    # basics
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "process_rank", "process_count",
    "is_homogeneous", "mesh", "start_timeline", "stop_timeline",
    # probes
    "xla_built", "tpu_available", "native_built", "mpi_built", "mpi_enabled", "gloo_built",
    "gloo_enabled", "nccl_built", "ddl_built", "ccl_built", "cuda_built",
    "rocm_built", "mpi_threads_supported", "current_operations",
    "cache_stats",
    # collectives
    "allreduce", "allreduce_async", "allgather", "allgather_async",
    "alltoall", "alltoall_async", "broadcast_async", "barrier",
    "broadcast", "join", "poll", "synchronize",
    "Average", "Sum", "Adasum", "ReduceOp", "Compression", "Handle",
    "HorovodInternalError",
    # axes
    "AXIS_DCN", "AXIS_ICI", "GLOBAL_AXES",
    # functions
    "broadcast_variables", "broadcast_parameters", "broadcast_object",
    "broadcast_optimizer_state", "allgather_object",
    # optimizer layer
    "DistributedOptimizer", "DistributedAdasumOptimizer",
    "DistributedGradientTape", "DistributedTrainStep",
    "SyncBatchNorm",
    # callbacks + checkpoint + data pipeline + elastic + integrity plane
    "callbacks", "checkpoint", "data", "elastic", "guard",
]
