"""Pipeline parallelism over the ``pp`` mesh axis: GPipe and
interleaved-1F1B schedules.

Extension beyond the reference (SURVEY §2.3: no pipeline code exists
there).  TPU-first formulation: every stage is one mesh shard holding
its stage's parameters; activations advance stage-to-stage with
``lax.ppermute`` (neighbor ICI hops) inside a ``lax.scan`` over
pipeline ticks.  All shards execute the same program every tick —
bubbles are masked computation, not control flow — which is exactly
what SPMD compilation wants.  Autodiff through the scan + ppermute
yields the reverse pipeline schedule for the backward pass.

:func:`gpipe` fills the pipe once: ``m + s - 1`` ticks for ``m``
microbatches over ``s`` stages, bubble fraction ``(s-1)/(m+s-1)``.
:func:`interleaved_1f1b` cuts the bubble by giving every rank ``v``
*virtual* stage chunks (rank ``r`` owns global chunks ``j*s + r``):
each microbatch now crosses the ring ``v`` times doing ``1/v``-sized
chunks of work, so the same ``s - 1`` warm-up ticks amortize over
``v*m`` work ticks — bubble ``(s-1)/(v*m+s-1)``, the interleaved-1F1B
schedule (docs/parallelism.md derives the tick algebra).  ``v=1``
reduces exactly to GPipe.

Call inside ``shard_map`` with stage parameters sharded over ``axis``
(stacked on a leading stage dimension) and the input replicated.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import AXIS_PP


def pipeline_ticks(stages: int, microbatches: int,
                   virtual_stages: int = 1) -> int:
    """Scan length of the schedule: ``v*m + s - 1`` ticks (``v=1`` is
    GPipe's ``m + s - 1``)."""
    return virtual_stages * microbatches + stages - 1


def bubble_fraction(stages: int, microbatches: int,
                    virtual_stages: int = 1) -> float:
    """Idle share of the schedule, ``(s-1)/(v*m+s-1)`` — the quantity
    the cost model prices and the bench pipeline probe reports."""
    return (stages - 1) / pipeline_ticks(stages, microbatches,
                                         virtual_stages)


def gpipe(stage_fn: Callable, stage_params, x: jax.Array,
          num_microbatches: int, axis: str = AXIS_PP) -> jax.Array:
    """Run ``x`` through ``world`` pipeline stages.

    Args:
      stage_fn: ``f(params, h) -> h`` — one stage; activation shapes must
        be identical across stages (uniform pipelines only).
      stage_params: this shard's stage parameters (shard the stacked
        stage dimension over ``axis`` with ``P("pp", ...)`` specs and
        index/squeeze it away in the caller, or pass per-stage trees).
      x: ``(batch, ...)`` input, replicated across the axis; ``batch``
        must divide by ``num_microbatches``.
      num_microbatches: pipeline depth M; wall-clock is
        ``M + world - 1`` ticks, bubble fraction ``(world-1)/(M+world-1)``.

    Returns:
      ``(batch, ...)`` output of the final stage, replicated across the
      axis (masked psum — only the last stage contributes).
    """
    world = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches={num_microbatches}")
    mb = b // num_microbatches
    mbs = x.reshape((num_microbatches, mb) + x.shape[1:])

    fwd_perm = [(i, (i + 1) % world) for i in range(world)]
    ticks = num_microbatches + world - 1

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t; later stages consume what arrived
        inject = mbs[jnp.clip(t, 0, num_microbatches - 1)]
        h_in = jnp.where(idx == 0, inject, state)
        my_mb = t - idx                    # microbatch this stage works on
        active = (my_mb >= 0) & (my_mb < num_microbatches)
        h_out = stage_fn(stage_params, h_in)
        h_out = jnp.where(active, h_out, jnp.zeros_like(h_out))
        # last stage banks its finished microbatch into its output slot
        done = active & (idx == world - 1)
        slot = jnp.clip(my_mb, 0, num_microbatches - 1)
        cur = lax.dynamic_slice_in_dim(outputs, slot, 1, axis=0)
        outputs = lax.dynamic_update_slice_in_dim(
            outputs, jnp.where(done, h_out[None], cur), slot, axis=0)
        # advance the pipeline: my output becomes the next stage's input
        state = lax.ppermute(h_out, axis, fwd_perm)
        return (state, outputs), None

    state0 = jnp.zeros((mb,) + mbs.shape[2:], x.dtype)
    outputs0 = jnp.zeros_like(mbs)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(ticks))
    # outputs are only valid on the last stage; fan them out
    outputs = lax.psum(
        jnp.where(idx == world - 1, outputs, jnp.zeros_like(outputs)), axis)
    return outputs.reshape((b,) + x.shape[1:])


def interleaved_1f1b(stage_fn: Callable, stage_params, x: jax.Array,
                     num_microbatches: int, virtual_stages: int = 1,
                     axis: str = AXIS_PP) -> jax.Array:
    """Interleaved pipeline schedule: rank ``r`` runs the ``v`` virtual
    chunks ``{j*s + r}`` of a ``v*s``-stage pipeline.

    Tick algebra (each quantity per rank ``r``): microbatch ``i``
    (group ``g = i // s``, slot ``k = i % s``) reaches chunk ``j`` on
    rank ``r`` at tick ``t = g*v*s + j*s + k + r``.  Decoding
    ``tr = t - r`` recovers ``(g, j, k)`` uniquely, so every rank does
    exactly one chunk of one microbatch per tick — collision-free —
    and both hops cost exactly one tick (rank ``r → r+1`` same chunk;
    the ring wrap ``s-1 → 0`` carries the activation into chunk
    ``j+1``).  Wall-clock is ``v*m + s - 1`` ticks, bubble
    ``(s-1)/(v*m+s-1)``.

    Args:
      stage_fn: ``f(chunk_params, h) -> h`` — one *virtual* chunk
        (``1/(v*s)`` of the model); activation shapes must be identical
        across chunks.
      stage_params: this rank's ``v`` chunk parameter trees, stacked on
        a leading ``virtual_stages`` dimension (chunk ``j`` of rank
        ``r`` is global stage ``j*s + r``).
      x: ``(batch, ...)`` input, replicated across the axis.
      num_microbatches: pipeline depth ``m``; must divide the batch and
        be a multiple of the stage count ``s`` (the interleave pattern
        tiles microbatches in groups of ``s``).
      virtual_stages: chunks per rank ``v``; ``v=1`` is exactly
        :func:`gpipe`'s schedule.

    Returns:
      ``(batch, ...)`` output of the final chunk, replicated across the
      axis.
    """
    world = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    m, v = num_microbatches, virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    b = x.shape[0]
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches={m}")
    if m % world != 0:
        raise ValueError(
            f"interleaved schedule needs num_microbatches ({m}) "
            f"divisible by the stage count ({world}): microbatches "
            f"tile in groups of s across the v chunks")
    mb = b // m
    mbs = x.reshape((m, mb) + x.shape[1:])

    fwd_perm = [(i, (i + 1) % world) for i in range(world)]
    ticks = pipeline_ticks(world, m, v)
    groups = m // world

    def tick(carry, t):
        state, outputs = carry
        tr = t - idx
        g = tr // (v * world)
        j = (tr % (v * world)) // world
        k = tr % world
        i = g * world + k               # microbatch at this rank now
        active = (tr >= 0) & (g < groups)
        # rank 0 injects a fresh microbatch whenever it starts chunk 0;
        # every other (rank, chunk) consumes what the ring delivered
        inject = mbs[jnp.clip(i, 0, m - 1)]
        h_in = jnp.where((idx == 0) & (j == 0), inject, state)
        params_j = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(
                p, jnp.clip(j, 0, v - 1), axis=0, keepdims=False),
            stage_params)
        h_out = stage_fn(params_j, h_in)
        h_out = jnp.where(active, h_out, jnp.zeros_like(h_out))
        # the last rank's last chunk banks the finished microbatch
        done = active & (idx == world - 1) & (j == v - 1)
        slot = jnp.clip(i, 0, m - 1)
        cur = lax.dynamic_slice_in_dim(outputs, slot, 1, axis=0)
        outputs = lax.dynamic_update_slice_in_dim(
            outputs, jnp.where(done, h_out[None], cur), slot, axis=0)
        # one ring hop per tick; the s-1 → 0 wrap lands exactly when
        # rank 0 re-injects (j == 0), so a finished microbatch's wrap
        # value is always ignored
        state = lax.ppermute(h_out, axis, fwd_perm)
        return (state, outputs), None

    state0 = jnp.zeros((mb,) + mbs.shape[2:], x.dtype)
    outputs0 = jnp.zeros_like(mbs)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(ticks))
    outputs = lax.psum(
        jnp.where(idx == world - 1, outputs, jnp.zeros_like(outputs)), axis)
    return outputs.reshape((b,) + x.shape[1:])
