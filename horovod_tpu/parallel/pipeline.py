"""GPipe-style pipeline parallelism over the ``pp`` mesh axis.

Extension beyond the reference (SURVEY §2.3: no pipeline code exists
there).  TPU-first formulation: every stage is one mesh shard holding
its stage's parameters; activations advance stage-to-stage with
``lax.ppermute`` (neighbor ICI hops) inside a ``lax.scan`` over
pipeline ticks.  All shards execute the same program every tick —
bubbles are masked computation, not control flow — which is exactly
what SPMD compilation wants.  Autodiff through the scan + ppermute
yields the reverse pipeline schedule for the backward pass.

Call inside ``shard_map`` with stage parameters sharded over ``axis``
(stacked on a leading stage dimension) and the input replicated.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import AXIS_PP


def gpipe(stage_fn: Callable, stage_params, x: jax.Array,
          num_microbatches: int, axis: str = AXIS_PP) -> jax.Array:
    """Run ``x`` through ``world`` pipeline stages.

    Args:
      stage_fn: ``f(params, h) -> h`` — one stage; activation shapes must
        be identical across stages (uniform pipelines only).
      stage_params: this shard's stage parameters (shard the stacked
        stage dimension over ``axis`` with ``P("pp", ...)`` specs and
        index/squeeze it away in the caller, or pass per-stage trees).
      x: ``(batch, ...)`` input, replicated across the axis; ``batch``
        must divide by ``num_microbatches``.
      num_microbatches: pipeline depth M; wall-clock is
        ``M + world - 1`` ticks, bubble fraction ``(world-1)/(M+world-1)``.

    Returns:
      ``(batch, ...)`` output of the final stage, replicated across the
      axis (masked psum — only the last stage contributes).
    """
    world = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches={num_microbatches}")
    mb = b // num_microbatches
    mbs = x.reshape((num_microbatches, mb) + x.shape[1:])

    fwd_perm = [(i, (i + 1) % world) for i in range(world)]
    ticks = num_microbatches + world - 1

    def tick(carry, t):
        state, outputs = carry
        # stage 0 injects microbatch t; later stages consume what arrived
        inject = mbs[jnp.clip(t, 0, num_microbatches - 1)]
        h_in = jnp.where(idx == 0, inject, state)
        my_mb = t - idx                    # microbatch this stage works on
        active = (my_mb >= 0) & (my_mb < num_microbatches)
        h_out = stage_fn(stage_params, h_in)
        h_out = jnp.where(active, h_out, jnp.zeros_like(h_out))
        # last stage banks its finished microbatch into its output slot
        done = active & (idx == world - 1)
        slot = jnp.clip(my_mb, 0, num_microbatches - 1)
        cur = lax.dynamic_slice_in_dim(outputs, slot, 1, axis=0)
        outputs = lax.dynamic_update_slice_in_dim(
            outputs, jnp.where(done, h_out[None], cur), slot, axis=0)
        # advance the pipeline: my output becomes the next stage's input
        state = lax.ppermute(h_out, axis, fwd_perm)
        return (state, outputs), None

    state0 = jnp.zeros((mb,) + mbs.shape[2:], x.dtype)
    outputs0 = jnp.zeros_like(mbs)
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(ticks))
    # outputs are only valid on the last stage; fan them out
    outputs = lax.psum(
        jnp.where(idx == world - 1, outputs, jnp.zeros_like(outputs)), axis)
    return outputs.reshape((b,) + x.shape[1:])
