"""Expert parallelism: top-1-routed MoE over the ``ep`` mesh axis.

Extension beyond the reference (SURVEY §2.3: EP absent; the
variable-split ``alltoall`` it ships — ``operations.cc:979`` — is
precisely the dispatch primitive).  TPU-first formulation: static
capacity buckets (no dynamic shapes under jit) — each shard scatters
its tokens into an ``(experts, capacity, d)`` dispatch buffer, one
``all_to_all`` moves expert slots to the shards that own them, expert
FFNs run as one batched matmul (MXU-friendly), and the inverse
``all_to_all`` brings results home for the gate-weighted combine.
Tokens beyond an expert's capacity are dropped (contribute zero), the
standard Switch-Transformer policy.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import AXIS_EP


def top1_routing(scores: jax.Array, capacity: int):
    """Greedy top-1 assignment with per-expert capacity.

    Args:
      scores: (tokens, num_experts) gate logits.
      capacity: max tokens per expert on this shard's batch.

    Returns:
      (expert_idx, slot, keep, gate): chosen expert, position inside its
      capacity bucket, whether the token fit, and its softmax gate weight.
    """
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)
    gate = jnp.max(probs, axis=-1)
    one_hot = jax.nn.one_hot(expert_idx, scores.shape[-1], dtype=jnp.int32)
    slot = (jnp.cumsum(one_hot, axis=0) - 1)
    slot = jnp.take_along_axis(slot, expert_idx[:, None], axis=1)[:, 0]
    keep = slot < capacity
    return expert_idx, slot, keep, gate


def expert_parallel_ffn(x: jax.Array, gate_kernel: jax.Array,
                        expert_fn: Callable, num_experts_total: int,
                        capacity_factor: float = 1.25,
                        axis: str = AXIS_EP,
                        scores: Optional[jax.Array] = None,
                        fused: bool = False,
                        interpret: bool = False):
    """Mixture-of-experts FFN with experts sharded over ``axis``.

    Call inside ``shard_map``.  Args:
      x: (tokens_local, d) this shard's tokens.
      gate_kernel: (d, num_experts_total) router weights (replicated).
      expert_fn: ``f(local_expert_params_selector) -> (E_local, C_world,
        d) -> (E_local, C_world, d)`` — actually invoked as
        ``expert_fn(buffers)`` where ``buffers`` is (E_local, world*C, d)
        (unfused) or one (E_local, C, d) source tile at a time (fused);
        must apply this shard's local experts batched over dim 0 and be
        token-wise (each slot independent) so both schedules agree.
      num_experts_total: E; must divide by the axis size.
      capacity_factor: per-expert capacity = ceil(cf * tokens/E).
      fused: route the dispatch/combine through the tile-fused
        ``a2a ⊗ expert-matmul`` ring
        (:func:`~horovod_tpu.ops.pallas_kernels.expert_alltoall_ffn`)
        instead of two boundary-wide ``all_to_all``\\ s — identical
        numerics (forward and grads), overlapped wire.  Resolve the
        ``"auto"|"on"|"off"`` knob with
        :func:`~horovod_tpu.ops.pallas_kernels.resolve_fused_collectives`
        before calling.

    Returns:
      (tokens_local, d) gate-weighted expert outputs (dropped tokens get
      zeros) and the fraction of dropped tokens (scalar, for aux losses).
    """
    world = lax.axis_size(axis)
    if num_experts_total % world != 0:
        raise ValueError(
            f"num_experts_total={num_experts_total} not divisible by "
            f"'{axis}' size {world}")
    e_local = num_experts_total // world
    t, d = x.shape
    capacity = int(max(1, -(-capacity_factor * t // num_experts_total)))

    # router in fp32 regardless of compute dtype: near-tie tokens
    # argmax differently in bf16 (measured ~0.2%), which would make
    # the dispatched routing diverge from fp32-side accounting (aux
    # losses) and from local-mode execution.  Callers that already
    # computed fp32 scores (e.g. for the Switch aux loss) pass them in
    # — the DISPATCHED routing and the accounted routing must be the
    # same routing, and the gate matmul runs once.
    if scores is None:
        scores = x.astype(jnp.float32) @ gate_kernel.astype(jnp.float32)
    expert_idx, slot, keep, gate = top1_routing(scores, capacity)

    # scatter tokens into (E, C, d) dispatch buckets
    dispatch = jnp.zeros((num_experts_total, capacity, d), x.dtype)
    safe_slot = jnp.where(keep, slot, 0)
    dispatch = dispatch.at[expert_idx, safe_slot].add(
        jnp.where(keep[:, None], x, 0.0))

    # (E, C, d) -> (world, E_local, C, d); dim 0 is the destination
    # shard.  The dispatch/combine exchange (two alltoalls, or the fused
    # ppermute ring that streams one tile per hop while the previous
    # tile's expert matmul computes) lives in ops.pallas_kernels.
    from horovod_tpu.ops.pallas_kernels import expert_alltoall_ffn
    dispatch = dispatch.reshape(world, e_local, capacity, d)
    combined = expert_alltoall_ffn(dispatch, expert_fn, axis,
                                   fused=fused, interpret=interpret)
    combined = combined.reshape(num_experts_total, capacity, d)

    # gather each token's result from its (expert, slot) and weight by gate
    y = combined[expert_idx, safe_slot]
    y = jnp.where(keep[:, None], y * gate[:, None].astype(y.dtype), 0.0)
    drop_fraction = 1.0 - jnp.mean(keep.astype(jnp.float32))
    return y, drop_fraction
