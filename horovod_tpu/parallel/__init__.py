"""Model-parallelism strategies over the device mesh.

The reference implements data parallelism only (SURVEY §2.3); its closest
primitive to sequence/expert parallelism is the first-class variable-split
``alltoall`` (``operations.cc:979``, ``nccl_operations.cc:569``) — exactly
what DeepSpeed-Ulysses-style sequence parallelism is built on.  This
package goes from that primitive to the strategies themselves, TPU-first:

* :mod:`~horovod_tpu.parallel.mesh` — multi-axis mesh factory
  (dp/fsdp/pp/ep/sp/tp) laid out so the most communication-intensive axes
  ride ICI neighbors;
* :mod:`~horovod_tpu.parallel.plan` — the declarative
  :class:`~horovod_tpu.parallel.plan.ShardingPlan` (``HOROVOD_PLAN``
  grammar) driving the train step, the exchange scope, checkpoint
  resharding and the AOT cache key (docs/parallelism.md);
* :mod:`~horovod_tpu.parallel.pipeline` — GPipe and interleaved-1F1B
  pipeline schedules (``lax.scan`` + ``ppermute``, bubbles as masked
  compute);
* :mod:`~horovod_tpu.parallel.ring_attention` — blockwise ring attention
  over a sequence axis (``lax.ppermute`` rotation + online softmax);
* :mod:`~horovod_tpu.parallel.ulysses` — all-to-all sequence↔head
  exchange attention;
* :mod:`~horovod_tpu.parallel.tensor_parallel` — Megatron-style
  column/row-parallel Dense layers with a single ``psum`` per block;
* :mod:`~horovod_tpu.parallel.fsdp` — ZeRO-3-style fully-sharded data
  parallelism by parameter *placement* (GSPMD inserts the
  gather/reduce-scatter), wired into ``DistributedTrainStep`` via
  ``fsdp_axis=``.
"""

from horovod_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    make_parallel_mesh,
)
from horovod_tpu.parallel.expert import expert_parallel_ffn, top1_routing
from horovod_tpu.parallel.fsdp import (
    fsdp_sharding,
    resident_bytes,
    shard_params,
    sharding_specs,
)
from horovod_tpu.parallel.pipeline import (
    bubble_fraction,
    gpipe,
    interleaved_1f1b,
    pipeline_ticks,
)
from horovod_tpu.parallel.plan import ShardingPlan, as_plan
from horovod_tpu.parallel.ring_attention import ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention
from horovod_tpu.parallel.tensor_parallel import (
    ColumnParallelDense,
    RowParallelDense,
)

__all__ = [
    "make_parallel_mesh",
    "AXIS_DP", "AXIS_FSDP", "AXIS_PP", "AXIS_EP", "AXIS_SP", "AXIS_TP",
    "ShardingPlan", "as_plan",
    "ring_attention", "ulysses_attention", "gpipe", "interleaved_1f1b",
    "pipeline_ticks", "bubble_fraction",
    "expert_parallel_ffn", "top1_routing",
    "ColumnParallelDense", "RowParallelDense",
    "fsdp_sharding", "shard_params", "sharding_specs", "resident_bytes",
]
