"""Ring attention: exact long-context attention over a sequence axis.

Q stays put; K/V blocks rotate around the mesh axis with
``lax.ppermute`` while each shard folds the visiting block into a
numerically-stable online-softmax accumulator (the blockwise/flash
recurrence).  After ``world`` steps every query has attended to the full
global sequence, using only neighbor exchanges that ride the ICI torus —
no shard ever materializes the full K/V or the (T, T) score matrix, so
context length scales linearly with the number of chips.

This is an extension beyond the reference (SURVEY §5.7: sequence
parallelism is absent there; its ``alltoall`` primitive is the closest
building block — see :mod:`~horovod_tpu.parallel.ulysses` for the
alltoall formulation).

Call inside ``shard_map`` with the sequence dimension sharded over
``axis_name``.  Differentiable by construction: autodiff flows through
the scan and ``ppermute`` (whose transpose is the inverse rotation), so
the backward pass is itself a ring pass.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None) -> jax.Array:
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Args:
      q, k, v: per-shard blocks ``(batch, seq_local, heads, head_dim)``;
        the global sequence is the concatenation of shards in axis order.
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask in *global* sequence positions.
      scale: score scale; default ``head_dim ** -0.5``.

    Returns:
      Attention output ``(batch, seq_local, heads, head_dim)``, the exact
      softmax attention over the full global sequence.
    """
    world = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = d ** -0.5 if scale is None else scale

    qf = q.astype(jnp.float32)
    # send K/V to the next shard: after s steps we hold the block that
    # started at shard (my_idx - s) % world
    perm = [(i, (i + 1) % world) for i in range(world)]

    q_pos = my_idx * tq + jnp.arange(tq)

    def step(carry, s):
        o, m, l, k_cur, v_cur = carry
        kv_idx = (my_idx - s) % world
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = kv_idx * tk + jnp.arange(tk)
            allowed = q_pos[:, None] >= k_pos[None, :]        # (tq, tk)
            scores = jnp.where(allowed[None, None], scores, _NEG_INF)
            allowed_f = allowed.astype(jnp.float32)[None, None]
        else:
            allowed_f = jnp.float32(1.0)
        m_new = jnp.maximum(m, scores.max(axis=-1))           # (b, h, tq)
        # multiply by the mask so fully-masked blocks contribute exactly 0
        # even while m_new is still at the -inf sentinel
        p = jnp.exp(scores - m_new[..., None]) * allowed_f    # (b, h, tq, tk)
        corr = jnp.exp(m - m_new)                             # (b, h, tq)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        k_nxt, v_nxt = lax.ppermute((k_cur, v_cur), axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt), None

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v),
                                  jnp.arange(world))
    denom = jnp.maximum(l, jnp.float32(1e-30)).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain single-device softmax attention (the numerics oracle for
    ring/ulysses tests, and the local attention inside Ulysses)."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        allowed = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(allowed[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
