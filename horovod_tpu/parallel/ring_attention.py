"""Ring attention: exact long-context attention over a sequence axis.

Q stays put; K/V blocks rotate around the mesh axis with
``lax.ppermute`` while each shard folds the visiting block into a
numerically-stable online-softmax accumulator (the blockwise/flash
recurrence).  After ``world`` steps every query has attended to the full
global sequence, using only neighbor exchanges that ride the ICI torus —
no shard ever materializes the full K/V or the (T, T) score matrix, so
context length scales linearly with the number of chips.

Two formulations share this contract:

* the **fused** path (:func:`~horovod_tpu.ops.pallas_kernels.
  ring_flash_attention`) consumes each visiting K/V block with the
  Pallas flash kernels — no per-block score tensor, the next hop's
  ``ppermute`` double-buffered behind the current block's compute —
  gated by :func:`~horovod_tpu.ops.pallas_kernels.
  resolve_fused_collectives` (``HOROVOD_SP_FUSED_RING``, falling back
  to ``HOROVOD_FUSED_COLLECTIVES``);
* the **jnp** fallback below, the identical online-softmax math in
  plain jnp, kept for shards off the flash tiling contract and for
  CPU-twin oracles.

Both understand the ``contiguous`` and ``zigzag`` sequence layouts
(``HOROVOD_SP_LAYOUT``): under zigzag each shard holds an early and a
late chunk of the global sequence so causal mask work load-balances
across ranks (docs/fused_kernels.md "Ring-flash attention").

This is an extension beyond the reference (SURVEY §5.7: sequence
parallelism is absent there; its ``alltoall`` primitive is the closest
building block — see :mod:`~horovod_tpu.parallel.ulysses` for the
alltoall formulation).

Call inside ``shard_map`` with the sequence dimension sharded over
``axis_name``.  Differentiable by construction: the jnp path's autodiff
flows through the scan and ``ppermute`` (whose transpose is the inverse
rotation), and the fused path carries its own ``custom_vjp`` ring.
"""

from __future__ import annotations

import os
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _resolve_fused(fused: Union[bool, str, None]) -> bool:
    """Normalize the ``fused`` knob to a bool.

    ``None`` reads ``HOROVOD_SP_FUSED_RING`` then
    ``HOROVOD_FUSED_COLLECTIVES`` (default ``auto`` = TPU only); a bool
    passes through; a mode string goes to ``resolve_fused_collectives``.
    """
    from horovod_tpu.ops.pallas_kernels import resolve_fused_collectives

    if isinstance(fused, bool):
        return fused
    if fused is None:
        fused = os.environ.get(
            "HOROVOD_SP_FUSED_RING",
            os.environ.get("HOROVOD_FUSED_COLLECTIVES", "auto"))
    return resolve_fused_collectives(fused)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str, causal: bool = False,
                   scale: Optional[float] = None,
                   fused: Union[bool, str, None] = None,
                   layout: Optional[str] = None,
                   block_q: int = 512, block_k: int = 512,
                   interpret: bool = False) -> jax.Array:
    """Exact attention with K/V ring-rotated over ``axis_name``.

    Args:
      q, k, v: per-shard blocks ``(batch, seq_local, heads, head_dim)``;
        the global sequence is the concatenation of shards in axis order
        (chunk order under ``layout="zigzag"`` — see
        :func:`~horovod_tpu.ops.pallas_kernels.ring_layout_positions`).
      axis_name: mesh axis the sequence is sharded over.
      causal: apply a causal mask in *global* sequence positions.
      scale: score scale; default ``head_dim ** -0.5``.
      fused: ``True``/``False``, an ``"auto"|"on"|"off"`` mode string,
        or ``None`` to read ``HOROVOD_SP_FUSED_RING`` (fallback
        ``HOROVOD_FUSED_COLLECTIVES``, default ``auto``).  Even when
        resolved on, shards off the flash tiling contract silently take
        the jnp formulation — same numerics, same ring wire.
      layout: ``"contiguous"`` (default; env ``HOROVOD_SP_LAYOUT``) or
        ``"zigzag"``.
      block_q, block_k: flash tile sizes for the fused path.
      interpret: run the fused path's Pallas kernels in interpreter
        mode (CPU tests).

    Returns:
      Attention output ``(batch, seq_local, heads, head_dim)``, the exact
      softmax attention over the full global sequence.
    """
    from horovod_tpu.ops import pallas_kernels as _pk

    if layout is None:
        layout = os.environ.get("HOROVOD_SP_LAYOUT", "contiguous")
    if layout not in _pk.RING_LAYOUTS:
        raise ValueError(
            f"sp layout must be one of {_pk.RING_LAYOUTS}, got {layout!r}")

    world = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = d ** -0.5 if scale is None else scale

    fits = (tq == tk and k.shape == q.shape and v.shape == q.shape
            and _pk.fit_flash_block(tq, block_q) is not None
            and _pk.fit_flash_block(tk, block_k) is not None
            and not (layout == "zigzag" and tq % 2))
    if fits and _resolve_fused(fused) and (interpret or _pk._on_tpu()):
        return _pk.ring_flash_attention(
            q, k, v, axis_name, causal=causal, scale=scale,
            layout=layout, block_q=block_q, block_k=block_k,
            interpret=interpret)

    qf = q.astype(jnp.float32)
    # send K/V to the next shard: after s steps we hold the block that
    # started at shard (my_idx - s) % world
    perm = [(i, (i + 1) % world) for i in range(world)]

    q_pos = _pk.ring_layout_positions(my_idx, world, tq, layout)
    kpos0 = (q_pos if tq == tk
             else _pk.ring_layout_positions(my_idx, world, tk, layout))

    def step(carry, _):
        o, m, l, k_cur, v_cur, kp_cur = carry
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf,
                            k_cur.astype(jnp.float32)) * scale
        if causal:
            # global positions travel with the block (layout-aware)
            allowed = q_pos[:, None] >= kp_cur[None, :]        # (tq, tk)
            scores = jnp.where(allowed[None, None], scores, _NEG_INF)
            allowed_f = allowed.astype(jnp.float32)[None, None]
        else:
            allowed_f = jnp.float32(1.0)
        m_new = jnp.maximum(m, scores.max(axis=-1))           # (b, h, tq)
        # multiply by the mask so fully-masked blocks contribute exactly 0
        # even while m_new is still at the -inf sentinel
        p = jnp.exp(scores - m_new[..., None]) * allowed_f    # (b, h, tq, tk)
        corr = jnp.exp(m - m_new)                             # (b, h, tq)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
        k_nxt, v_nxt, kp_nxt = lax.ppermute((k_cur, v_cur, kp_cur),
                                            axis_name, perm)
        return (o_new, m_new, l_new, k_nxt, v_nxt, kp_nxt), None

    o0 = jnp.zeros((b, tq, h, d), jnp.float32)
    m0 = jnp.full((b, h, tq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, tq), jnp.float32)
    (o, m, l, _, _, _), _ = lax.scan(step, (o0, m0, l0, k, v, kpos0),
                                     jnp.arange(world))
    denom = jnp.maximum(l, jnp.float32(1e-30)).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain single-device softmax attention (the numerics oracle for
    ring/ulysses tests, and the local attention inside Ulysses)."""
    d = q.shape[-1]
    scale = d ** -0.5 if scale is None else scale
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        allowed = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        scores = jnp.where(allowed[None, None], scores, _NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
