"""Fully-sharded data parallelism (ZeRO-3 analogue), TPU formulation.

The reference framework replicates parameters on every worker and
allreduces gradients — its memory ceiling is one full model + optimizer
state per accelerator.  FSDP shards parameters (and, by propagation,
optimizer state) across a mesh axis; each step all-gathers a parameter
right before use and reduce-scatters its gradient right after — trading
one extra all-gather per step for an O(world) reduction in resident
state.

TPU formulation: there is no wrapper module and no hand-written
gather/scatter.  Parameters are *placed* sharded (`NamedSharding` over
the ``fsdp``/``ici`` axis, largest divisible dimension) and the step is
jitted without replicated-input constraints — GSPMD then inserts
exactly the all-gather-on-use and reduce-scatter-on-grad collectives
the hand-rolled ZeRO-3 schedules perform, scheduled and overlapped by
the compiler (the "sharding is placement" recipe of the scaling book).
Optimizer state inherits the sharding automatically because
``optimizer.init`` runs under jit on the sharded parameters.

Entry points: :func:`fsdp_sharding` (per-leaf placement rule),
:func:`shard_params` (place a pytree), and
``DistributedTrainStep(fsdp_axis=...)`` which wires both into the
training step.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# leaves smaller than this stay replicated: sharding a bias vector saves
# bytes measured in KB but adds a collective to the step
DEFAULT_MIN_WEIGHT_SIZE = 1 << 14


def fsdp_sharding(shape, mesh: Mesh, axis: str,
                  min_weight_size: int = DEFAULT_MIN_WEIGHT_SIZE
                  ) -> NamedSharding:
    """Placement rule for one parameter: partition the largest dimension
    divisible by the axis size; replicate small or indivisible leaves.

    Partitioning the largest dim maximizes the bytes saved per leaf and
    keeps every shard's tile contiguous in its minor dims (layout- and
    MXU-friendly: the minor-most dims stay whole).
    """
    n = mesh.shape[axis]
    size = int(np.prod(shape)) if shape else 1
    if n == 1 or size < min_weight_size:
        return NamedSharding(mesh, P())
    # largest dimension with the needed divisibility
    candidates = [(d, i) for i, d in enumerate(shape) if d % n == 0]
    if not candidates:
        return NamedSharding(mesh, P())
    _, dim = max(candidates)
    spec = [None] * len(shape)
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))


def shard_params(params, mesh: Mesh, axis: str,
                 min_weight_size: int = DEFAULT_MIN_WEIGHT_SIZE):
    """``device_put`` a parameter pytree with per-leaf FSDP placement.
    Returns the sharded tree; leaves keep their values, only residency
    changes."""
    def place(x):
        return jax.device_put(
            x, fsdp_sharding(np.shape(x), mesh, axis, min_weight_size))

    return jax.tree_util.tree_map(place, params)


def sharding_specs(params, mesh: Mesh, axis: str,
                   min_weight_size: int = DEFAULT_MIN_WEIGHT_SIZE):
    """The pytree of `NamedSharding`s :func:`shard_params` would use —
    for inspection/tests and for passing to explicit ``in_shardings``."""
    return jax.tree_util.tree_map(
        lambda x: fsdp_sharding(getattr(x, "shape", np.shape(x)), mesh,
                                axis, min_weight_size),
        params)


def resident_bytes(params) -> int:
    """Per-device bytes actually resident for a (possibly sharded)
    pytree — the number FSDP shrinks.  Computed as one shard's bytes per
    leaf (every device holds exactly one shard; replicated leaves' shard
    is the whole array)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        if isinstance(leaf, jax.Array) and leaf.addressable_shards:
            shard = leaf.addressable_shards[0]
            total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
    return total
