"""Seeded parallelism-plan smoke for ``hvdci`` (analysis/ci.py gate 6).

A sub-second, CPU-only, virtual-device walk of the sharding-plan
compiler: one :class:`~horovod_tpu.parallel.plan.ShardingPlan`
(``dp=2,tp=2,pp=2,v=2``) is parsed, resolved against an 8-rank
virtual grid, and executed as a numpy lockstep simulation of every
extent it drives — column-parallel tensor shards (bit-exact vs the
dense matmul), a fixed-order data-parallel gradient average over the
plan's :attr:`data_axes`, and the interleaved-1F1B tick schedule
(bit-exact vs stacked sequential apply, closing in exactly
``pipeline_ticks`` ticks with every microbatch visiting its v*s
stages in order).  Run twice and required bit-identical, so plan
determinism itself is gated.

Returns error strings (empty = pass) in the same idiom as
``guard.smoke`` / ``serve.smoke`` so ci.py folds it straight into its
exit code.  Budget: well under a second — pure numpy, 8 virtual
ranks, four microbatches.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from horovod_tpu.parallel.plan import ShardingPlan

PLAN = "dp=2,tp=2,pp=2,v=2"
WORLD = 8
MICROBATCHES = 4   # per pipeline group; must divide by pp
WIDTH = 6          # activation/feature width of the toy stages
SEED = 4242


def _stage(params: Tuple[np.ndarray, np.ndarray],
           x: np.ndarray) -> np.ndarray:
    w, b = params
    return np.tanh(x @ w + b).astype(np.float32)


def _pipeline_1f1b(params: List[Tuple[np.ndarray, np.ndarray]],
                   x: List[np.ndarray], m: int, s: int,
                   v: int) -> Dict[str, Any]:
    """Lockstep interleaved-1F1B over ``s`` virtual ranks: rank ``r``
    holds global chunks ``{j*s + r}``; microbatch ``i`` (``g=i//s``,
    ``k=i%s``) fires at chunk ``j`` on rank ``r`` at tick
    ``g*v*s + j*s + k + r`` — the same algebra
    ``parallel/pipeline.interleaved_1f1b`` runs under ``lax.scan``."""
    groups = m // s
    state = [xi.copy() for xi in x]
    visits: List[List[int]] = [[] for _ in range(m)]
    last_fire = -1
    for t in range(v * m + s - 1):
        for r in range(s):
            tr = t - r
            if tr < 0:
                continue
            g = tr // (v * s)
            if g >= groups:
                continue
            j = (tr % (v * s)) // s
            k = tr % s
            i = g * s + k
            stage = j * s + r
            state[i] = _stage(params[stage], state[i])
            visits[i].append(stage)
            last_fire = t
    return {"state": state, "visits": visits, "ticks": last_fire + 1}


def _scenario() -> Dict[str, Any]:
    from horovod_tpu.parallel import bubble_fraction, pipeline_ticks

    plan = ShardingPlan.from_string(PLAN).resolve(WORLD)
    s, v, m = plan.pp, plan.virtual_stages, MICROBATCHES
    rng = np.random.RandomState(SEED)

    # -- tensor extent: column-parallel matmul, bit-exact vs dense ----
    xt = rng.rand(3, WIDTH).astype(np.float32)
    wt = rng.rand(WIDTH, 2 * WIDTH).astype(np.float32)
    cols = 2 * WIDTH // plan.tp
    shards = [xt @ wt[:, r * cols:(r + 1) * cols]
              for r in range(plan.tp)]
    tp_exact = bool(np.array_equal(np.concatenate(shards, axis=1),
                                   xt @ wt))

    # -- data extent: fixed-rank-order gradient average ---------------
    grads = [np.sin(np.arange(WIDTH, dtype=np.float32) * (1.0 + 0.1 * r))
             for r in range(plan.dp)]
    acc = grads[0].copy()
    for g in grads[1:]:
        acc = acc + g
    dp_avg = acc / plan.dp

    # -- pipeline extent: 1F1B schedule vs stacked sequential apply ---
    params = [(rng.rand(WIDTH, WIDTH).astype(np.float32) * 0.5,
               rng.rand(WIDTH).astype(np.float32))
              for _ in range(v * s)]
    micro = [rng.rand(2, WIDTH).astype(np.float32) for _ in range(m)]
    pipe = _pipeline_1f1b(params, micro, m, s, v)
    seq = []
    for xi in micro:
        y = xi.copy()
        for p in params:
            y = _stage(p, y)
        seq.append(y)
    pipe_exact = all(np.array_equal(a, b)
                     for a, b in zip(pipe["state"], seq))
    visits_ok = all(vs == list(range(v * s)) for vs in pipe["visits"])

    return {
        "plan": plan.to_string(),
        "data_axes": plan.data_axes,
        "model_axes": plan.model_axes,
        "total": plan.total,
        "tp_exact": tp_exact,
        "dp_avg": [round(float(x), 6) for x in dp_avg],
        "pipe_exact": pipe_exact,
        "visits_ok": visits_ok,
        "ticks": pipe["ticks"],
        "ticks_expected": pipeline_ticks(s, m, virtual_stages=v),
        "ticks_gpipe": pipeline_ticks(s, m),
        "bubble_1f1b": round(bubble_fraction(s, m, virtual_stages=v), 6),
        "bubble_gpipe": round(bubble_fraction(s, m), 6),
        "final": [round(float(y.sum()), 6) for y in pipe["state"]],
    }


def run_smoke() -> List[str]:
    """Run the seeded plan scenario twice; returns a list of error
    strings (empty = pass)."""
    errors: List[str] = []
    try:
        r1, r2 = _scenario(), _scenario()
    except Exception as e:          # noqa: BLE001 — a crash IS a failure
        return [f"plan-smoke: scenario crashed: "
                f"{type(e).__name__}: {e}"]
    if r1["plan"] != "dp=2,pp=2,tp=2,v=2":
        errors.append(f"plan-smoke: canonical plan string is "
                      f"{r1['plan']!r}, expected 'dp=2,pp=2,tp=2,v=2'")
    if r1["total"] != WORLD:
        errors.append(f"plan-smoke: plan covers {r1['total']} devices, "
                      f"expected {WORLD}")
    if r1["data_axes"] != ("dp",) or "tp" not in r1["model_axes"]:
        errors.append(f"plan-smoke: axis split data={r1['data_axes']} "
                      f"model={r1['model_axes']} does not isolate the "
                      f"exchange to the data extent")
    if not r1["tp_exact"]:
        errors.append("plan-smoke: column-parallel tensor shards do not "
                      "reproduce the dense matmul bit-exactly")
    if not r1["pipe_exact"] or not r1["visits_ok"]:
        errors.append("plan-smoke: interleaved-1F1B schedule diverged "
                      "from stacked sequential apply")
    if r1["ticks"] != r1["ticks_expected"]:
        errors.append(f"plan-smoke: schedule closed in {r1['ticks']} "
                      f"ticks, cost model says {r1['ticks_expected']}")
    if not r1["bubble_1f1b"] < r1["bubble_gpipe"]:
        errors.append(f"plan-smoke: 1F1B bubble {r1['bubble_1f1b']} not "
                      f"below the GPipe bubble {r1['bubble_gpipe']}")
    if r1 != r2:
        errors.append("plan-smoke: two seeded runs were not identical")
    return errors
