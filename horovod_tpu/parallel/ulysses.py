"""Ulysses-style sequence parallelism: all-to-all sequence↔head exchange.

DeepSpeed-Ulysses observes that attention is embarrassingly parallel over
*heads*: shards holding sequence slices all-to-all their Q/K/V so each
shard holds the FULL sequence for a subset of heads, run ordinary (or
flash) attention locally, then all-to-all back to sequence shards.  Two
``all_to_all`` pairs per attention — the collective the reference added
as a first-class op in this very version (``operations.cc:979``,
``nccl_operations.cc:569``; SURVEY §5.7 names it as the primitive SP
builds on).  On TPU the exchange is one XLA ``all_to_all`` riding ICI.

Trade-off vs :mod:`~horovod_tpu.parallel.ring_attention`: Ulysses moves
activations twice but runs one dense local attention (better MXU
utilization, needs ``heads % world == 0``); ring keeps activations put
and pipelines K/V around the torus (unbounded context, any head count).

Call inside ``shard_map`` with the sequence dimension sharded over
``axis_name``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

from horovod_tpu.parallel.ring_attention import reference_attention


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str, causal: bool = False,
                      attn_fn: Optional[Callable] = None) -> jax.Array:
    """Attention over the global sequence via head-sharded local attention.

    Args:
      q, k, v: per-shard blocks ``(batch, seq_local, heads, head_dim)``
        with ``heads`` divisible by the axis size.
      axis_name: mesh axis the sequence is sharded over.
      causal: causal masking (positions are global after the exchange, so
        the local mask is exact).
      attn_fn: ``f(q, k, v, causal) -> out`` over full-sequence inputs;
        defaults to dense softmax attention.

    Returns:
      ``(batch, seq_local, heads, head_dim)`` exact global attention.
    """
    world = lax.axis_size(axis_name)
    heads = q.shape[2]
    if heads % world != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({heads}) divisible by the "
            f"'{axis_name}' axis size ({world}); use ring_attention for "
            f"arbitrary head counts")
    attn_fn = attn_fn or (lambda q_, k_, v_, c: reference_attention(
        q_, k_, v_, causal=c))

    # (b, t_local, h, d) -> (b, t_global, h_local, d): scatter heads,
    # gather sequence
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = attn_fn(qh, kh, vh, causal)
    # inverse exchange: back to sequence shards with all heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
