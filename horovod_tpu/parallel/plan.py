"""Declarative parallelism plans: one object, four consumers.

ROADMAP item 3 — the sharding-plan compiler.  A :class:`ShardingPlan`
names the parallel degree of every mesh axis (``dp``/``pp``/``fsdp``/
``ep``/``sp``/``tp``, the :data:`~horovod_tpu.parallel.mesh.AXIS_ORDER`
axes) plus the interleaved-1F1B virtual-stage count, parsed from the
``HOROVOD_PLAN`` grammar::

    HOROVOD_PLAN="dp=4,tp=2"          # 4-way data x 2-way tensor
    HOROVOD_PLAN="dp=2,pp=2,v=2"      # pipeline, 2 virtual stages/rank
    HOROVOD_PLAN="fsdp=8"             # pure ZeRO placement

The same plan object is the single source of truth for:

* ``optim/train_step.py`` — ``DistributedTrainStep(plan=...)`` builds
  the mesh from the plan, shards the batch over :attr:`data_axes`, and
  stamps :meth:`to_string` into ``_aot_extras`` so a warm start never
  serves an executable compiled for a different plan;
* ``ops/collectives.py`` — the ZeRO gradient exchange (RS → shard
  update → AG) runs only over the plan's data axes, never the model
  axes;
* ``checkpoint.py`` — sharded save/restore records the plan and
  reshards across *plan* changes (the data extent — including ``sp``,
  which shards activations, never parameters — may change; the
  pp/ep/tp factorization must not);
* ``parallel/mesh.py`` — :meth:`build_mesh` lays the plan out
  DCN-outer/ICI-inner per ``AXIS_ORDER``.

The module body is stdlib-only (JAX is imported lazily inside
:meth:`build_mesh`) so the plan grammar is usable from the analysis
layer's cost model and CLI without a device runtime.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Tuple, Union

#: Mesh axes in DCN-outer → ICI-inner order.  Mirrors
#: ``parallel/mesh.AXIS_ORDER`` by value (that module imports JAX at
#: module scope; this one must not).
PLAN_AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")

#: Grammar keys: the six mesh axes plus ``v`` (interleaved-1F1B virtual
#: stages per pipeline rank, ``parallel/pipeline.interleaved_1f1b``).
PLAN_KEYS = PLAN_AXES + ("v",)

ENV_PLAN = "HOROVOD_PLAN"


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """One parallelism plan: per-axis extents + pipeline schedule.

    ``dp=None`` means "absorb whatever device count the other axes
    leave over" — resolved against a concrete device count by
    :meth:`resolve` (or implicitly by :meth:`build_mesh`).
    """

    dp: Optional[int] = None
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    virtual_stages: int = 1

    def __post_init__(self):
        for ax in PLAN_AXES:
            v = getattr(self, ax)
            if ax == "dp" and v is None:
                continue
            if not isinstance(v, int) or v < 1:
                raise ValueError(
                    f"plan axis {ax} must be a positive int, got {v!r}")
        if not isinstance(self.virtual_stages, int) \
                or self.virtual_stages < 1:
            raise ValueError(
                f"virtual_stages must be a positive int, got "
                f"{self.virtual_stages!r}")
        if self.virtual_stages > 1 and self.pp == 1:
            raise ValueError(
                f"v={self.virtual_stages} needs a pipeline axis: "
                f"virtual stages interleave over pp ranks, but pp=1")

    # -- construction -------------------------------------------------------

    @classmethod
    def from_string(cls, text: str) -> "ShardingPlan":
        """Parse the ``HOROVOD_PLAN`` grammar: comma-separated
        ``axis=extent`` pairs, axes from :data:`PLAN_KEYS`."""
        if not isinstance(text, str) or not text.strip():
            raise ValueError(
                "empty plan: expected comma-separated axis=extent "
                f"pairs over {', '.join(PLAN_KEYS)} "
                f"(e.g. \"dp=4,tp=2\")")
        seen: Dict[str, int] = {}
        for item in text.split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, val = item.partition("=")
            key = key.strip()
            if not sep or key not in PLAN_KEYS:
                raise ValueError(
                    f"bad plan term {item!r}: expected axis=extent "
                    f"with axis in {', '.join(PLAN_KEYS)}")
            if key in seen:
                raise ValueError(f"duplicate plan axis {key!r} in "
                                 f"{text!r}")
            try:
                extent = int(val.strip())
            except ValueError:
                raise ValueError(
                    f"bad plan extent {val.strip()!r} for axis "
                    f"{key!r}: expected a positive int") from None
            seen[key] = extent
        kwargs = {("virtual_stages" if k == "v" else k): v
                  for k, v in seen.items()}
        return cls(**kwargs)

    @classmethod
    def from_env(cls) -> Optional["ShardingPlan"]:
        """The ``HOROVOD_PLAN`` plan, or None when the knob is unset."""
        text = os.environ.get(ENV_PLAN)
        return cls.from_string(text) if text else None

    def resolve(self, n_devices: int) -> "ShardingPlan":
        """Concrete plan for ``n_devices``: infer ``dp`` when unset,
        verify the factorization covers the device count exactly."""
        fixed = self.pp * self.fsdp * self.ep * self.sp * self.tp
        dp = self.dp
        if dp is None:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"cannot infer dp: {n_devices} devices not "
                    f"divisible by pp*fsdp*ep*sp*tp={fixed}")
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"plan {self.to_string(allow_unresolved=True)} covers "
                f"{dp * fixed} devices, not {n_devices}")
        return dataclasses.replace(self, dp=dp)

    # -- views --------------------------------------------------------------

    def to_string(self, allow_unresolved: bool = False) -> str:
        """Canonical plan string — the AOT-cache-key / checkpoint /
        perf-gate-comparability representation.  ``dp`` is always
        emitted (so ``parse(to_string())`` round-trips exactly); other
        axes appear only at extent > 1, in :data:`PLAN_AXES` order."""
        if self.dp is None and not allow_unresolved:
            raise ValueError(
                "plan has dp=None (unresolved): call resolve(n_devices) "
                "before using the canonical string")
        parts = [f"dp={'?' if self.dp is None else self.dp}"]
        parts += [f"{ax}={getattr(self, ax)}" for ax in PLAN_AXES[1:]
                  if getattr(self, ax) > 1]
        if self.virtual_stages > 1:
            parts.append(f"v={self.virtual_stages}")
        return ",".join(parts)

    @property
    def total(self) -> int:
        """Device count the plan covers (requires a resolved ``dp``)."""
        if self.dp is None:
            raise ValueError("plan has dp=None: call resolve(n_devices)")
        return self.dp * self.pp * self.fsdp * self.ep * self.sp * self.tp

    @property
    def extents(self) -> Dict[str, int]:
        """Axis → extent in ``AXIS_ORDER`` (dp may be None)."""
        return {ax: getattr(self, ax) for ax in PLAN_AXES}

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Axes the gradient exchange (and batch sharding) rides: the
        replica axes dp/fsdp at extent > 1; plain ``("dp",)`` for a
        fully model-parallel plan (a size-1 exchange is free and the
        sharding specs stay uniform)."""
        axes = tuple(ax for ax in ("dp", "fsdp")
                     if (getattr(self, ax) or 1) > 1)
        return axes or ("dp",)

    @property
    def model_axes(self) -> Tuple[str, ...]:
        """Model-parallel axes at extent > 1 (pp/ep/sp/tp).

        ``sp`` is deliberately here even though it shards activations
        rather than parameters: a live job cannot change its sequence
        factorization (the ring's exchange schedule and the batch's
        token sharding are compiled in), so degrade transitions must
        keep the sp extent — only checkpoint resharding, where the
        job restarts anyway, treats sp as data extent
        (``checkpoint._check_plan_reshard``)."""
        return tuple(ax for ax in ("pp", "ep", "sp", "tp")
                     if getattr(self, ax) > 1)

    @property
    def model_extent(self) -> int:
        """Product of the model-parallel extents — the load-bearing
        factor a degrade transition must never change (checkpoint
        resharding only covers the data extent; docs/elastic.md)."""
        return self.pp * self.ep * self.sp * self.tp

    def degrade_candidates(self, n_devices: int
                           ) -> Tuple["ShardingPlan", ...]:
        """Feasible plans for ``n_devices`` surviving devices, keeping
        every model-parallel extent (and the pipeline schedule) fixed.

        Only the data extents move: ``dp' <= dp`` and ``fsdp' <= fsdp``
        with ``dp' * fsdp' * model_extent <= n_devices``.  Ordered
        best-first: largest surviving world wins, and among equal
        worlds the plan that shrinks ``dp`` (cheap — replicas are
        interchangeable) is preferred over one that shrinks ``fsdp``
        (re-slices every parameter shard).  Empty when even
        ``dp=1,fsdp=1`` does not fit — the model extent itself needs
        the lost capacity, so the caller must wait for it to return
        rather than degrade (docs/elastic.md wait-vs-shrink table).
        """
        if self.dp is None:
            raise ValueError(
                "plan has dp=None (unresolved): call resolve(n_devices) "
                "before enumerating degrade candidates")
        model = self.model_extent
        out = []
        for dp in range(1, self.dp + 1):
            for fsdp in range(1, self.fsdp + 1):
                if dp * fsdp * model <= int(n_devices):
                    out.append(dataclasses.replace(self, dp=dp,
                                                   fsdp=fsdp))
        out.sort(key=lambda p: (-p.total, self.fsdp - p.fsdp,
                                self.dp - p.dp))
        return tuple(out)

    # -- consumers ----------------------------------------------------------

    def build_mesh(self, devices=None):
        """Lay the plan out as a ``jax.sharding.Mesh`` via
        :func:`~horovod_tpu.parallel.mesh.make_parallel_mesh` —
        DCN-tolerant axes outermost, ICI-hungry axes innermost
        (``AXIS_ORDER``)."""
        from horovod_tpu.parallel.mesh import make_parallel_mesh

        return make_parallel_mesh(dp=self.dp, pp=self.pp, fsdp=self.fsdp,
                                  ep=self.ep, sp=self.sp, tp=self.tp,
                                  devices=devices)

    def matches_mesh(self, mesh) -> bool:
        """True when ``mesh`` carries exactly this plan's factorization
        (every plan axis present at the plan's extent)."""
        shape = dict(mesh.shape)
        return all(shape.get(ax) == getattr(self, ax)
                   for ax in PLAN_AXES)


def candidate_plans(n_devices: int,
                    axes: Tuple[str, ...] = ("dp", "fsdp", "tp")
                    ) -> Tuple["ShardingPlan", ...]:
    """Every exact factorization of ``n_devices`` over ``axes`` —
    the enumeration the HBM planner (``memory/planner.py``) and the
    budget-aware autotune walk.

    Deterministic order: dp-heaviest first (the pure-data plan is the
    presumptive speed winner; the budget search then works toward the
    sharded-parameter end), then lexicographic on the remaining
    extents.  ``axes`` must be plan axes; ``n_devices`` must be >= 1.
    """
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    for ax in axes:
        if ax not in PLAN_AXES:
            raise ValueError(
                f"unknown plan axis {ax!r}: expected one of "
                f"{', '.join(PLAN_AXES)}")
    out = []

    def factor(remaining: int, idx: int, extents: Dict[str, int]):
        if idx == len(axes) - 1:
            out.append(ShardingPlan(**{**extents, axes[idx]: remaining}))
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0:
                factor(remaining // d, idx + 1,
                       {**extents, axes[idx]: d})
            d += 1
        return

    factor(n, 0, {})
    out.sort(key=lambda p: tuple(-getattr(p, ax) if ax == "dp"
                                 else getattr(p, ax) for ax in axes))
    return tuple(out)


PlanLike = Union[str, ShardingPlan]


def as_plan(plan: Optional[PlanLike]) -> Optional[ShardingPlan]:
    """Coerce a plan argument: a grammar string parses, a
    :class:`ShardingPlan` passes through, None stays None."""
    if plan is None or isinstance(plan, ShardingPlan):
        return plan
    if isinstance(plan, str):
        return ShardingPlan.from_string(plan)
    raise TypeError(
        f"plan must be a ShardingPlan or a HOROVOD_PLAN string, got "
        f"{type(plan).__name__}")
