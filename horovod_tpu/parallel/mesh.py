"""Multi-axis parallelism mesh factory.

The runtime's (dcn, ici) mesh (``runtime/topology.py``) models the
reference's CROSS×LOCAL communicator split (``common.h:113-117``) and is
all data parallelism needs.  Model parallelism needs finer axes.  This
factory builds an N-D ``jax.sharding.Mesh`` whose axis order encodes the
hardware hierarchy: the outermost axes change slowest across the device
list (cheap, infrequent collectives — dp, pp ride DCN), the innermost
axes map to ICI neighbors (tp does per-layer collectives and needs the
fastest links) — the "How to Scale Your Model" mesh recipe.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_DP = "dp"       # data parallel: gradient psum once per step
AXIS_PP = "pp"       # pipeline stages: p2p activations between neighbors
AXIS_FSDP = "fsdp"   # fully-sharded dp: param all-gather + grad reduce-scatter
AXIS_EP = "ep"       # expert parallel: all_to_all token dispatch
AXIS_SP = "sp"       # sequence/context parallel: ring ppermute / all_to_all
AXIS_TP = "tp"       # tensor parallel: psum per transformer block

# outermost (slowest-varying, DCN-tolerant) → innermost (ICI neighbors)
AXIS_ORDER = (AXIS_DP, AXIS_PP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)


def make_parallel_mesh(dp: Optional[int] = None, pp: int = 1, fsdp: int = 1,
                       ep: int = 1, sp: int = 1, tp: int = 1,
                       devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a mesh with the requested parallel degrees.

    ``dp=None`` absorbs whatever device count the other axes leave over.
    Axes of extent 1 are kept in the mesh (size-1 collectives are free and
    sharding specs stay uniform across configurations).

    ::

        mesh = make_parallel_mesh(tp=4, sp=2)      # dp fills the rest
        with mesh:
            ...
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = pp * fsdp * ep * sp * tp
    if dp is None:
        if n % fixed != 0:
            raise ValueError(
                f"cannot infer dp: {n} devices not divisible by "
                f"pp*fsdp*ep*sp*tp={fixed}")
        dp = n // fixed
    total = dp * fixed
    if total != n:
        raise ValueError(
            f"mesh {dp}x{pp}x{fsdp}x{ep}x{sp}x{tp}={total} does not cover "
            f"{n} devices")
    shape = dict(zip(AXIS_ORDER, (dp, pp, fsdp, ep, sp, tp)))
    dev_array = np.asarray(devices).reshape(tuple(shape.values()))
    return Mesh(dev_array, AXIS_ORDER)
