"""Megatron-style tensor parallelism, TPU-idiomatic.

Column-parallel Dense shards the output features over the ``tp`` axis
(no communication in forward); row-parallel Dense shards the input
features and finishes with one ``psum``.  The classic pairing — column
then row around a pointwise nonlinearity — costs exactly one psum per
MLP block and one per attention block.

Two API levels:

* **pjit/GSPMD path** (idiomatic default): flax modules whose kernels
  carry ``nn.with_partitioning`` metadata; under ``pjit`` over a mesh
  with a ``tp`` axis XLA inserts the collectives automatically, and the
  psum materializes as a fused reduce-scatter/all-gather where profitable.
* **shard_map path** (explicit control): plain functions taking local
  shards, for use inside ``shard_map`` where the collective placement is
  hand-written (the Horovod-style explicit mode).

Extension beyond the reference: SURVEY §2.3 — no model partitioning
exists anywhere in Horovod; TP here rides the same mesh machinery as
everything else.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec

from horovod_tpu.parallel.mesh import AXIS_TP
from horovod_tpu.utils import logging as hvd_logging

Dtype = Any
AxisSpec = Union[str, Sequence[str]]

# one-time flag: a partitioned module running with no constrainable
# ambient mesh silently computes fully replicated (see _constrain);
# warn on the first occurrence only — the condition repeats every
# trace and per-layer spam would bury the signal
_warned_no_ambient_mesh = False


# ---------------------------------------------------------------------------
# pjit/GSPMD modules — sharding by annotation
# ---------------------------------------------------------------------------

def _constrainable_axes() -> Optional[set]:
    """Mesh axis names a sharding constraint may legally name, or None.

    Inside ``shard_map`` the abstract mesh marks every axis Manual —
    constraints are illegal there (values are already per-shard; the
    TransformerLM docstring's unboxed-params mode), so Manual axes are
    excluded.  The classic ``with mesh:`` context has no public
    accessor, so ``jax._src.mesh.thread_resources`` is read as the
    fallback — pinned against the image's jax, same stance as
    ``runtime/distributed.py``."""
    try:        # use_mesh / shard_map-style contexts carry axis types
        am = jax.sharding.get_abstract_mesh()
        if am is not None and not am.empty:
            return {name for name, typ in zip(am.axis_names,
                                              am.axis_types)
                    if "Manual" not in str(typ)}
    except Exception:
        pass
    try:
        from jax._src import mesh as _jmesh

        m = _jmesh.thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return set(m.axis_names)
    except Exception:
        pass
    return None


def _constrain(x, *spec):
    """Pin a partition spec on a value inside the module.

    flax's ``nn.with_partitioning`` only *boxes* metadata onto the
    param tree — nothing applies it during ``apply``, so without this
    constraint a jit over a tp mesh is free to replicate the kernels
    and the "tensor-parallel" module silently computes fully
    replicated (measured: the compiled module had zero collectives).
    The constraint is skipped ONLY when no ambient mesh exists, the
    mesh lacks the requested axis, or the axis is Manual (shard_map
    body — constraining there is illegal); real sharding errors on a
    live mesh — e.g. features not divisible by the axis size — must
    propagate, not silently replicate."""
    mesh_axes = _constrainable_axes()
    wanted = {s for s in spec if isinstance(s, str)}
    if mesh_axes is None:
        global _warned_no_ambient_mesh
        if not _warned_no_ambient_mesh:
            _warned_no_ambient_mesh = True
            hvd_logging.warning(
                "tensor-parallel module executed with no ambient mesh: "
                "kernel sharding constraints for axes %s were skipped, "
                "so the module computes fully REPLICATED (no tensor "
                "parallelism). Run it under `with mesh:` / "
                "`jax.sharding.use_mesh(mesh)` over a mesh carrying "
                "those axes, or inside shard_map with hand-placed "
                "collectives.", sorted(wanted))
        return x
    if not wanted <= mesh_axes:
        return x
    return jax.lax.with_sharding_constraint(x, PartitionSpec(*spec))


class ColumnParallelDense(nn.Module):
    """Dense with output features sharded over ``axis`` (kernel partition
    spec ``(None, axis)``).  Forward needs no collective; pair with
    :class:`RowParallelDense` to close the block with one psum.

    **Ambient-mesh requirement**: the sharding constraints that make
    the module actually tensor-parallel only apply when it executes
    under an ambient mesh carrying ``axis`` — ``with mesh:`` or
    ``jax.sharding.use_mesh(mesh)`` around the jitted ``apply`` (see
    :func:`horovod_tpu.parallel.mesh.make_parallel_mesh`).  With no
    ambient mesh the module still computes correct values but fully
    replicated, and a one-time warning is logged.  Inside ``shard_map``
    the axes are Manual and constraints are skipped by design — use the
    explicit :func:`column_parallel_dense` there."""

    features: int
    axis: str = AXIS_TP
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (None, self.axis)),
            (x.shape[-1], self.features))
        kernel = _constrain(jnp.asarray(kernel, self.dtype),
                            None, self.axis)
        y = jnp.dot(x.astype(self.dtype), kernel)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(self.bias_init, (self.axis,)),
                (self.features,))
            y = y + _constrain(jnp.asarray(bias, self.dtype), self.axis)
        return y


class RowParallelDense(nn.Module):
    """Dense with input features sharded over ``axis`` (kernel partition
    spec ``(axis, None)``); the partial products are summed by XLA's
    inserted collective under pjit.  Bias is added after the reduction.

    Same **ambient-mesh requirement** as :class:`ColumnParallelDense`:
    without a ``with mesh:`` / ``use_mesh`` context carrying ``axis``
    the constraints are skipped (one-time warning) and the module runs
    replicated; inside ``shard_map`` use the explicit
    :func:`row_parallel_dense` instead."""

    features: int
    axis: str = AXIS_TP
    use_bias: bool = True
    dtype: Optional[Dtype] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.kernel_init, (self.axis, None)),
            (x.shape[-1], self.features))
        kernel = _constrain(jnp.asarray(kernel, self.dtype),
                            self.axis, None)
        y = jnp.dot(x.astype(self.dtype), kernel)
        if self.use_bias:
            bias = self.param(
                "bias", nn.with_partitioning(self.bias_init, (None,)),
                (self.features,))
            y = y + jnp.asarray(bias, self.dtype)
        return y


# ---------------------------------------------------------------------------
# shard_map functions — explicit local shards + hand-placed psum
# ---------------------------------------------------------------------------

def column_parallel_dense(x: jax.Array, kernel: jax.Array,
                          bias: Optional[jax.Array] = None) -> jax.Array:
    """Local shard of a column-parallel matmul: ``kernel`` is this shard's
    ``(in, out_local)`` slice; output stays feature-sharded."""
    y = jnp.dot(x, kernel)
    return y + bias if bias is not None else y


def row_parallel_dense(x: jax.Array, kernel: jax.Array,
                       bias: Optional[jax.Array] = None,
                       axis: AxisSpec = AXIS_TP) -> jax.Array:
    """Local shard of a row-parallel matmul closed by a psum: ``x`` is
    feature-sharded ``(…, in_local)``, ``kernel`` the matching
    ``(in_local, out)`` slice; output is replicated over ``axis``."""
    y = lax.psum(jnp.dot(x, kernel), axis)
    return y + bias if bias is not None else y


# ---------------------------------------------------------------------------
# tile-fused sequence-parallel boundary layers (docs/fused_kernels.md)
# ---------------------------------------------------------------------------
#
# The classic column→row pairing above closes each block with one
# boundary-wide psum — a serial collective no compute hides.  The
# Megatron-SP restructuring replaces it with a reduce-scatter over
# tokens at the row boundary and an all-gather over tokens at the next
# column boundary, and the tile-fused kernels
# (ops/pallas_kernels.matmul_reducescatter / allgather_matmul) overlap
# each boundary's wire with the matmul itself — tile k's exchange rides
# under tile k+1's MXU compute, so no full-width serial collective
# remains at either boundary (the HLO guard pins ring permutes, zero
# all-reduces).  Token layout contract: rows are RANK-MAJOR flattened
# tokens — the gather concatenates rank chunks along dim 0 and the
# scatter hands rank r rows [r·m/world, (r+1)·m/world); callers holding
# (batch, seq, d) natural layout transpose chunks accordingly
# (models/transformer.fused_tp_apply shows the idiom).

def column_parallel_dense_ag(x: jax.Array, kernel: jax.Array,
                             bias: Optional[jax.Array] = None,
                             axis: str = AXIS_TP,
                             fused: bool = True,
                             interpret: bool = False) -> jax.Array:
    """Column-parallel Dense over a token-sharded input: gathers the
    ``(m_local, in)`` rank-major row shard across ``axis`` *inside* the
    matmul (:func:`~horovod_tpu.ops.pallas_kernels.allgather_matmul`)
    and applies this rank's ``(in, out_local)`` column shard; returns
    the full-token ``(world·m_local, out_local)`` activation."""
    from horovod_tpu.ops.pallas_kernels import allgather_matmul

    y = allgather_matmul(x, kernel, axis, fused=fused,
                         interpret=interpret)
    return y + bias if bias is not None else y


def row_parallel_dense_rs(x: jax.Array, kernel: jax.Array,
                          bias: Optional[jax.Array] = None,
                          axis: str = AXIS_TP,
                          fused: bool = True,
                          interpret: bool = False) -> jax.Array:
    """Row-parallel Dense closed by a tile-fused reduce-scatter over
    tokens: ``x`` is the full-token feature-sharded ``(m, in_local)``
    activation (rows rank-major), ``kernel`` this rank's
    ``(in_local, out)`` row slice; returns this rank's reduced
    ``(m/world, out)`` token block
    (:func:`~horovod_tpu.ops.pallas_kernels.matmul_reducescatter`).
    The bias (full ``(out,)``) is added after the reduction, on the
    owned token block only."""
    from horovod_tpu.ops.pallas_kernels import matmul_reducescatter

    y = matmul_reducescatter(x, kernel, axis, fused=fused,
                             interpret=interpret)
    return y + bias if bias is not None else y
