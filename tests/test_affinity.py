"""CPU affinity knob (reference ``HOROVOD_THREAD_AFFINITY``,
``common.cc parse_and_set_affinity``)."""

import pytest

from horovod_tpu.utils.affinity import parse_affinity, set_affinity_from_env


class TestParse:
    def test_ranges_and_lists(self):
        assert parse_affinity("0-3;4,6;7") == [
            {0, 1, 2, 3}, {4, 6}, {7}]

    def test_rejects_junk(self):
        with pytest.raises(ValueError):
            parse_affinity("3-1")
        with pytest.raises(ValueError):
            parse_affinity(";")


class TestApply:
    def test_local_rank_selects_set(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_THREAD_AFFINITY", "0-1;2-3")
        applied = {}
        set_affinity_from_env(1, setter=lambda c: applied.update(c=c))
        assert applied["c"] == {2, 3}

    def test_too_few_sets_never_shares(self, monkeypatch):
        """A spec shorter than the local world must not silently pin two
        workers to the same cores (the contention pinning prevents)."""
        monkeypatch.setenv("HOROVOD_THREAD_AFFINITY", "0-1;2-3")
        assert set_affinity_from_env(2, setter=lambda c: 1 / 0) is None

    def test_unset_is_noop(self, monkeypatch):
        monkeypatch.delenv("HOROVOD_THREAD_AFFINITY", raising=False)
        assert set_affinity_from_env(0, setter=lambda c: 1 / 0) is None

    def test_bad_spec_warns_not_raises(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_THREAD_AFFINITY", "not-cores")
        assert set_affinity_from_env(0, setter=lambda c: 1 / 0) is None
