"""Real multi-process distributed tests (SURVEY §7 hard part #5).

The reference's universal trick is the same pytest file under ``mpirun
-np 2``; the analogue here: launch 2 real worker processes through the
``hvdrun`` CLI, each initializing ``jax.distributed`` against the
launcher-allocated coordinator, and run eager collectives across the
2-process world (XLA CPU collectives over gloo underneath).
"""

import os
import subprocess
import sys
import textwrap

import pytest

# cross-process collectives: jax 0.4.37's CPU backend cannot run them
# (pre-existing, documented in CHANGES.md), so this suite is excluded
# from tier-1 by the slow mark and runs where real worlds exist
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def launch(script_body: str, tmp_path, np=2, timeout=180):
    worker = tmp_path / "worker.py"
    worker.write_text(textwrap.dedent(script_body))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    # workers must not inherit the test session's virtual-mesh forcing
    env.pop("XLA_FLAGS", None)
    env.pop("HOROVOD_TPU_MESH_SHAPE", None)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch",
         "-np", str(np), "--", sys.executable, str(worker)],
        capture_output=True, text=True, timeout=timeout, env=env)


class TestTwoProcessWorld:
    def test_allreduce_broadcast_allgather(self, tmp_path):
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            assert hvd.process_count() == 2
            r = hvd.process_rank()

            # allreduce: 1 + 2 = 3
            s = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                              name="ar")
            np.testing.assert_allclose(np.asarray(s), 3.0)

            # broadcast from rank 1
            b = hvd.broadcast(jnp.full((3,), float(r * 10)), root_rank=1,
                              name="bc")
            np.testing.assert_allclose(np.asarray(b), 10.0)

            # variable-size allgather: rank r contributes r+1 rows
            g = hvd.allgather(jnp.full((r + 1, 2), float(r)), name="ag")
            assert g.shape == (3, 2)
            np.testing.assert_allclose(np.asarray(g[:1]), 0.0)
            np.testing.assert_allclose(np.asarray(g[1:]), 1.0)

            # alltoall with splits
            t = hvd.alltoall(jnp.arange(4.0) + 10 * r, splits=[2, 2],
                             name="a2a")
            expected = [0 + 10 * 0, 1 + 10 * 0, 0 + 10 * 1, 1 + 10 * 1] \\
                if r == 0 else [2, 3, 12, 13]
            np.testing.assert_allclose(
                np.asarray(t), np.asarray(expected, np.float32)
                if r else np.asarray([0., 1., 10., 11.]))

            # async variants of the non-allreduce collectives: handles
            # resolve to the same results across a real 2-process world
            hg = hvd.allgather_async(jnp.full((r + 1, 2), float(r)),
                                     name="ag_async")
            hb = hvd.broadcast_async(jnp.full((3,), float(r * 10)),
                                     root_rank=1, name="bc_async")
            ht = hvd.alltoall_async(jnp.arange(4.0) + 10 * r,
                                    splits=[2, 2], name="a2a_async")
            assert hvd.synchronize(hg).shape == (3, 2)
            np.testing.assert_allclose(np.asarray(hvd.synchronize(hb)),
                                       10.0)
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(ht)),
                np.asarray(t))

            # interleaving a bucketed (deferred-dispatch) allreduce with
            # an immediate-negotiation async collective must not
            # misalign the negotiation order across processes: both
            # processes run identical program order, the broadcast
            # negotiates at submit, the allreduce at its flush — same
            # wire sequence everywhere, either synchronize order
            ar_h = hvd.allreduce_async(jnp.full((2,), float(r + 1)),
                                       op=hvd.Sum, name="ilv_ar")
            bc_h = hvd.broadcast_async(jnp.full((2,), float(r + 5)),
                                       root_rank=0, name="ilv_bc")
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(ar_h)), 3.0)
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(bc_h)), 5.0)
            ar_h = hvd.allreduce_async(jnp.full((2,), float(r + 1)),
                                       op=hvd.Sum, name="ilv_ar2")
            bc_h = hvd.broadcast_async(jnp.full((2,), float(r + 6)),
                                       root_rank=1, name="ilv_bc2")
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(bc_h)), 7.0)
            np.testing.assert_allclose(
                np.asarray(hvd.synchronize(ar_h)), 3.0)

            # barrier + object exchange
            hvd.barrier()
            objs = hvd.allgather_object({"rank": r})
            assert objs == [{"rank": 0}, {"rank": 1}]
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_fused_async_and_metrics(self, tmp_path):
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            # many async submissions fuse into grouped collectives
            handles = [hvd.allreduce_async(
                jnp.full((5,), float(i + r)), name=f"g.{i}", op=hvd.Average)
                for i in range(10)]
            for i, h in enumerate(handles):
                np.testing.assert_allclose(
                    np.asarray(hvd.synchronize(h)), i + 0.5)
            # join: both processes arrive
            last = hvd.join()
            assert last in (0, 1)
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_join_allreduce_uneven_batches(self, tmp_path):
        """Joined ranks contribute zeros to collectives other ranks still
        issue; join() returns the exact last rank (reference
        ``test_horovod_join_allreduce`` in test/test_torch.py;
        zero synthesis ``controller.cc:263-274``)."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            # rank 0 has 2 batches, rank 1 has 5: after rank 0 joins, its
            # zero contribution must make SUM return rank 1's value alone
            # and AVERAGE divide by the full world size.
            n_batches = 2 if r == 0 else 5
            for i in range(n_batches):
                s = hvd.allreduce(jnp.full((3,), float(r + 1)),
                                  op=hvd.Sum, name=f"j.{i}")
                if i < 2:  # both ranks present
                    np.testing.assert_allclose(np.asarray(s), 3.0)
                else:      # rank 0 joined: zeros + 2.0
                    np.testing.assert_allclose(np.asarray(s), 2.0)
            if r == 1:
                a = hvd.allreduce(jnp.full((3,), 2.0), op=hvd.Average,
                                  name="j.avg")
                # (0 + 2) / world_size=2, reference postscale-1/size rule
                np.testing.assert_allclose(np.asarray(a), 1.0)
            last = hvd.join()
            assert last == 1, f"last joiner must be rank 1, got {last}"
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_join_allgather_unsupported(self, tmp_path):
        """Allgather issued while another rank joined raises the
        reference's error on the active rank (``controller.cc:487-497``)
        AND on the joined rank — errors are delivered on every rank, so
        a fatally-erroring active rank cannot leave joined processes
        blocking forever in their service loop.  The error cycle
        completes its wire exchanges on all ranks first, so processes
        that catch the error stay aligned and can re-enter join()."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            if r == 1:
                try:
                    hvd.allgather(jnp.ones((2, 2)), name="ag.join")
                except hvd.HorovodInternalError as e:
                    assert "not supported with Join" in str(e), e
                    print("CAUGHT_OK", r)
                last = hvd.join()
            else:
                try:
                    last = hvd.join()
                except hvd.HorovodInternalError as e:
                    assert "not supported with Join" in str(e), e
                    print("CAUGHT_OK", r)
                    last = hvd.join()
            assert last == 1
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2
        assert out.stdout.count("CAUGHT_OK") == 2

    def test_cross_rank_shape_mismatch_errors(self, tmp_path):
        """Rank-specific wrong shape must produce a catchable
        HorovodInternalError, not a hang (reference cross-rank error
        injection, test_tensorflow.py:601-671)."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            shape = (4,) if r == 0 else (5,)       # rank 1 diverges
            try:
                hvd.allreduce(jnp.ones(shape), name="bad")
            except hvd.HorovodInternalError as e:
                print("CAUGHT_OK", r)
            else:
                print("NO_ERROR", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("CAUGHT_OK") == 2, out.stdout

    def test_collective_output_feeds_next_collective(self, tmp_path):
        """The natural training loop — w -= lr * allreduce(grad(w)) —
        feeds a replicated (non-fully-addressable) result straight back
        into the next eager collective; intake must localize it instead
        of crashing in device_put (regression: found by
        examples/adasum_small_model.py)."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            w = jnp.zeros((4,))
            for i in range(3):
                g = hvd.allreduce(w + (r + 1), op=hvd.Average,
                                  name=f"loop.{i}")
                w = w - 0.5 * g      # w now spans the global mesh
            np.testing.assert_allclose(np.asarray(w)[0], -1.3125)
            # the looped array also feeds broadcast/allgather intakes
            b = hvd.broadcast(w, root_rank=0, name="loop.bc")
            gth = hvd.allgather(w[None], name="loop.ag")
            assert gth.shape == (2, 4)
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_host_data_plane(self, tmp_path):
        """HOROVOD_TPU_OPERATIONS=HOST routes every eager collective over
        the coordination-service KV store (the Gloo-CPU analogue) with
        identical numerics — the op-manager knob made real (reference
        ``HOROVOD_CPU_OPERATIONS``, ``operation_manager.cc:40-100``)."""
        out = launch("""
            import os
            os.environ["HOROVOD_TPU_OPERATIONS"] = "HOST"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            assert hvd.current_operations() == "HOST", hvd.current_operations()
            r = hvd.process_rank()

            s = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                              name="h.ar")
            np.testing.assert_allclose(np.asarray(s), 3.0)
            a = hvd.allreduce(jnp.full((3,), float(r)), op=hvd.Adasum,
                              name="h.ad")
            assert np.asarray(a).shape == (3,)
            b = hvd.broadcast(jnp.full((3,), float(r * 7)), root_rank=1,
                              name="h.bc")
            np.testing.assert_allclose(np.asarray(b), 7.0)
            g = hvd.allgather(jnp.full((r + 1, 2), float(r)), name="h.ag")
            assert g.shape == (3, 2)
            t = hvd.alltoall(jnp.arange(4.0) + 10 * r, splits=[2, 2],
                             name="h.a2a")
            expected = [0., 1., 10., 11.] if r == 0 else [2., 3., 12., 13.]
            np.testing.assert_allclose(np.asarray(t), expected)
            hvd.barrier()
            stats = hvd.cache_stats()
            assert stats["misses"] > 0
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_negotiation_observability(self, tmp_path):
        """The timeline records one NEGOTIATE instant per controller
        cycle with the cache outcome, and hvd.cache_stats() counts hits
        and misses (reference NEGOTIATE phases + response-cache stats)."""
        out = launch(f"""
            import os
            os.environ["HOROVOD_TIMELINE"] = \
                str({str(tmp_path)!r}) + "/tl." + \
                os.environ["HOROVOD_RANK"] + ".json"
            os.environ["HOROVOD_TIMELINE_PYTHON"] = "1"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import horovod_tpu as hvd

            hvd.init()
            for i in range(3):
                hvd.allreduce(jnp.ones(2), op=hvd.Sum, name="obs")
            stats = hvd.cache_stats()
            assert stats["misses"] >= 1 and stats["hits"] >= 2, stats
            hvd.shutdown()
            print("WORKER_OK")
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2
        import json

        events = json.loads((tmp_path / "tl.0.json").read_text())
        neg = [e for e in events
               if e.get("name") == "NEGOTIATE" and e["ph"] == "i"]
        assert len(neg) >= 3
        outcomes = {e["args"]["cache"] for e in neg}
        assert outcomes == {"hit", "miss"}, outcomes
        assert all("cycle" in e["args"] and "joined" in e["args"]
                   for e in neg)
        # per-tensor negotiation phases: each of the 3 allreduces opens a
        # NEGOTIATE span on the tensor's own timeline row at enqueue and
        # closes it at agreement (reference timeline.h:77-131).  The
        # rank-0 file is the AGGREGATED trace, so each process's lane
        # carries its own 3 spans
        spans = [e for e in events
                 if e.get("name") == "NEGOTIATE" and e["ph"] == "B"]
        for pid in (0, 1):
            assert len([e for e in spans if e["pid"] == pid]) == 3
        assert all(e["tid"] == "obs" for e in spans)

    def test_train_step_across_processes(self, tmp_path):
        """DistributedTrainStep on a real 2-process world: host batches
        are sharded by addressable rows (make_array_from_callback path)
        and both ranks step to the identical loss."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import jax.numpy as jnp
            import optax
            import horovod_tpu as hvd

            hvd.init()

            def loss_fn(params, batch):
                return jnp.mean((batch["x"] @ params - batch["y"]) ** 2)

            step = hvd.DistributedTrainStep(loss_fn, optax.sgd(0.1))
            params, opt_state = step.init(jnp.zeros((4,)))
            x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
            y = x @ np.ones(4, np.float32)
            losses = []
            for _ in range(3):
                b = step.shard_batch({"x": x, "y": y})
                params, opt_state, loss = step(params, opt_state, b)
                losses.append(float(loss))
            assert losses[0] > losses[-1] > 0
            agreed = hvd.allgather_object(losses)
            assert agreed[0] == agreed[1], agreed
            # shard_batch is idempotent on already-global arrays
            b2 = step.shard_batch(step.shard_batch({"x": x, "y": y}))
            params, _, _ = step(params, opt_state, b2)
            print("WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_estimator_distributed_fit(self, tmp_path):
        """Estimator.fit on a real 2-process world: the run id is
        broadcast from rank 0, store writes happen on rank 0 only, and
        both ranks converge to identical parameters."""
        store_dir = tmp_path / "store"
        out = launch(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import pandas as pd
            import flax.linen as nn
            import horovod_tpu as hvd
            from horovod_tpu.spark import Estimator, Store

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

            rng = np.random.RandomState(0)
            x = rng.rand(64, 4).astype(np.float32)
            y = (x @ rng.rand(4, 3)).argmax(1).astype(np.int32)
            df = pd.DataFrame({{"f1": x[:, 0], "f2": x[:, 1],
                                "f3": x[:, 2], "f4": x[:, 3], "label": y}})
            store = Store.create({str(store_dir)!r})
            est = Estimator(Net(), feature_cols=["f1", "f2", "f3", "f4"],
                            label_col="label", batch_size=4, epochs=2,
                            store=store, validation_fraction=0.25)
            model = est.fit(df)
            # params must be identical across ranks (broadcast + synced
            # training); compare a digest via allgather
            leaf = np.asarray(jax.tree_util.tree_leaves(model.params)[0],
                              np.float32)
            digests = hvd.allgather_object(float(np.abs(leaf).sum()))
            assert digests[0] == digests[1], digests
            print("WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2
        # rank-0-only store writes produced exactly one run layout
        runs = sorted((store_dir / "runs").iterdir())
        assert [r.name for r in runs] == ["run_001"], runs
        assert (store_dir / "runs/run_001/metadata.json").exists()
        # run-scoped intermediates are cleaned up after a successful fit
        assert not (store_dir / "intermediate_train_data.run_001").exists()

    def test_multidevice_processes_hierarchical_mesh(self, tmp_path):
        """2 processes x 2 virtual devices each: the (dcn, ici) = (2, 2)
        hierarchical mesh with partially-addressable batch arrays —
        each process feeds only its own devices' shards, training stays
        bit-identical across ranks."""
        out = launch("""
            import os
            os.environ["XLA_FLAGS"] = \
                "--xla_force_host_platform_device_count=2"
            os.environ["HOROVOD_TPU_MESH_SHAPE"] = "2,2"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import optax
            import horovod_tpu as hvd

            hvd.init()
            assert hvd.process_count() == 2
            assert hvd.size() == 4, hvd.size()
            assert jax.local_device_count() == 2

            def loss_fn(params, batch):
                pred = batch["x"] @ params
                return jnp.mean((pred - batch["y"]) ** 2)

            step = hvd.DistributedTrainStep(loss_fn, optax.sgd(0.1))
            params, opt_state = step.init(jnp.zeros((4,)))
            rng = np.random.RandomState(0)
            x = rng.rand(8, 4).astype(np.float32)
            y = (x @ np.ones(4, np.float32))
            losses = []
            for _ in range(3):
                b = step.shard_batch({"x": x, "y": y})
                params, opt_state, loss = step(params, opt_state, b)
                losses.append(float(loss))
            assert losses[0] > losses[-1] > 0
            agreed = hvd.allgather_object(losses)
            assert agreed[0] == agreed[1], agreed
            print("WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_streaming_fit_on_multidevice_processes(self, tmp_path):
        """Streaming fit on 2 processes x 2 devices: shard_local_batch
        must lay each process's locally-read rows across its own two
        devices (make_array_from_process_local_data path) while row
        groups stay sharded per process."""
        store_dir = tmp_path / "store"
        import numpy as np
        import pandas as pd

        from horovod_tpu.spark import Store

        rng = np.random.RandomState(0)
        x = rng.rand(96, 4).astype(np.float32)
        y = (x @ rng.rand(4, 3)).argmax(1).astype(np.int32)
        df = pd.DataFrame({"f1": x[:, 0], "f2": x[:, 1], "f3": x[:, 2],
                           "f4": x[:, 3], "label": y})
        store = Store.create(str(store_dir))
        store.write_dataframe(df, store.get_train_data_path(),
                              rows_per_group=12)

        out = launch(f"""
            import os
            os.environ["XLA_FLAGS"] = \
                "--xla_force_host_platform_device_count=2"
            os.environ["HOROVOD_TPU_MESH_SHAPE"] = "2,2"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import flax.linen as nn
            import horovod_tpu as hvd
            from horovod_tpu.spark import Estimator, Store

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

            store = Store.create({str(store_dir)!r})
            est = Estimator(Net(), feature_cols=["f1", "f2", "f3", "f4"],
                            label_col="label", batch_size=4, epochs=2)
            model = est.fit_on_parquet(store.get_train_data_path())
            assert jax.local_device_count() == 2
            leaf = np.asarray(jax.tree_util.tree_leaves(model.params)[0],
                              np.float32)
            digests = hvd.allgather_object(float(np.abs(leaf).sum()))
            assert digests[0] == digests[1], digests
            print("WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_zero_splits_and_integer_dtypes(self, tmp_path):
        """Reference edge cases: alltoall with zero-row splits
        (``test_tensorflow.py`` zero-splits cases) and integer-dtype
        allreduce survive the wire across a real 2-process world."""
        out = launch("""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()

            # rank 0 sends everything to rank 1; rank 1 sends nothing
            rows = 3 if r == 0 else 0
            t = hvd.alltoall(jnp.full((rows, 2), float(r)),
                             splits=[0, rows], name="z.a2a")
            if r == 0:
                assert t.shape == (0, 2), t.shape
            else:
                np.testing.assert_allclose(np.asarray(t),
                                           np.zeros((3, 2)))

            # integer allreduce: SUM of int32 stays exact
            s = hvd.allreduce(jnp.full((4,), 7 + r, jnp.int32),
                              op=hvd.Sum, name="z.int")
            assert s.dtype == jnp.int32
            np.testing.assert_array_equal(np.asarray(s), 15)

            # int32 variable allgather
            g = hvd.allgather(jnp.arange(r + 1, dtype=jnp.int32),
                              name="z.ag")
            np.testing.assert_array_equal(np.asarray(g), [0, 0, 1])
            print("WORKER_OK", r)
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2

    def test_estimator_streaming_shards_are_disjoint(self, tmp_path):
        """fit_on_parquet across 2 processes: each process materializes
        only its round-robin row groups (read accounting), never the
        full dataset — the petastorm-reader contract
        (reference ``spark/keras/remote.py:336``)."""
        store_dir = tmp_path / "store"
        # write the sharded parquet once, before the workers launch
        import numpy as np
        import pandas as pd

        from horovod_tpu.spark import Store

        rng = np.random.RandomState(0)
        x = rng.rand(96, 4).astype(np.float32)
        y = (x @ rng.rand(4, 3)).argmax(1).astype(np.int32)
        df = pd.DataFrame({"f1": x[:, 0], "f2": x[:, 1], "f3": x[:, 2],
                           "f4": x[:, 3], "label": y})
        store = Store.create(str(store_dir))
        store.write_dataframe(df, store.get_train_data_path(),
                              rows_per_group=12)   # 8 groups / 2 procs

        out = launch(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import flax.linen as nn
            import horovod_tpu as hvd
            from horovod_tpu.spark import Estimator, Store
            from horovod_tpu.spark.store import RowGroupReader

            reads = []
            orig = RowGroupReader.read_group
            RowGroupReader.read_group = \\
                lambda self, i: (reads.append(i), orig(self, i))[1]

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

            store = Store.create({str(store_dir)!r})
            est = Estimator(Net(), feature_cols=["f1", "f2", "f3", "f4"],
                            label_col="label", batch_size=4, epochs=2)
            model = est.fit_on_parquet(store.get_train_data_path())
            leaf = np.asarray(jax.tree_util.tree_leaves(model.params)[0],
                              np.float32)
            digests = hvd.allgather_object(float(np.abs(leaf).sum()))
            assert digests[0] == digests[1], digests
            import json
            with open({str(tmp_path)!r} +
                      f"/groups.{{hvd.process_rank()}}.json", "w") as f:
                json.dump(sorted(set(reads)), f)
            print("WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("WORKER_OK") == 2
        import json

        groups = {r: set(json.load(open(tmp_path / f"groups.{r}.json")))
                  for r in range(2)}
        # round-robin ownership: disjoint shards covering all 8 groups
        assert groups[0] == {0, 2, 4, 6}, groups
        assert groups[1] == {1, 3, 5, 7}, groups

    def test_worker_failure_fails_job(self, tmp_path):
        out = launch("""
            import os, sys
            if os.environ["HOROVOD_RANK"] == "1":
                sys.exit(3)
            print("rank0 alive")
        """, tmp_path)
        assert out.returncode != 0

    def test_stall_attribution_names_laggard(self, tmp_path):
        """When one rank delays a collective past the warning threshold,
        the waiting rank's stall warning names the laggard process
        (reference CheckForStalledTensors missing-rank report)."""
        out = launch("""
            import os
            os.environ["HOROVOD_STALL_CHECK_TIME_SECONDS"] = "1"
            import time
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import numpy as np
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            if r == 1:
                time.sleep(4.0)   # past rank 0's 1s warning threshold
            s = hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                              name="late_op")
            np.testing.assert_allclose(np.asarray(s), 3.0)
            # both ranks recover and finish normally after the stall
            print("STALL_TEST_OK", r)
            hvd.shutdown()
        """, tmp_path, timeout=240)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("STALL_TEST_OK") == 2
        blob = out.stdout + out.stderr
        assert "late_op" in blob and "not completed" in blob, blob[-2000:]
        # the attribution line names process 1 as not having submitted
        assert "process(es) 1 have not submitted" in blob, blob[-2000:]

    def test_timeline_aggregates_to_rank0(self, tmp_path):
        """stop_timeline gathers every process's events into ONE Chrome
        trace on rank 0 with a consistent time origin (reference rank-0
        aggregated timeline, timeline.cc)."""
        tldir = tmp_path / "tl"
        tldir.mkdir()
        out = launch(f"""
            import os
            import jax
            jax.config.update("jax_platforms", "cpu")
            import jax.numpy as jnp
            import horovod_tpu as hvd

            hvd.init()
            r = hvd.process_rank()
            # every rank passes the SAME shared path; non-root ranks
            # record to <path>.<rank> and rank 0 merges back into it
            hvd.start_timeline({str(str(tldir))!r} + "/tl.0.json")
            hvd.allreduce(jnp.full((4,), float(r + 1)), op=hvd.Sum,
                          name="agg_ar")
            hvd.allgather(jnp.ones((2, 2)) * r, name="agg_ag")
            hvd.stop_timeline()
            print("TL_OK", r)
            hvd.shutdown()
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("TL_OK") == 2
        import json as _json

        merged = _json.loads((tldir / "tl.0.json").read_text())
        pids = {e["pid"] for e in merged if e.get("ph") in ("B", "E")}
        assert pids == {0, 1}, pids
        names = {e["args"]["name"] for e in merged
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert names == {"process 0", "process 1"}
        # both processes' spans for the same collectives, one time axis
        for p in (0, 1):
            tids = {e["tid"] for e in merged
                    if e.get("ph") == "B" and e["pid"] == p}
            assert {"agg_ar", "agg_ag"} <= tids, (p, tids)
        ts = [e["ts"] for e in merged if "ts" in e]
        assert min(ts) >= 0
        # rebased origins: both processes' events interleave within the
        # same few-second window, not offset by an epoch
        span_us = max(ts) - min(ts)
        assert span_us < 60e6, span_us

    def test_prepared_store_fit_across_processes(self, tmp_path):
        """The reference flow end-to-end: prepare the DataFrame into the
        store ONCE on the driver, then every training process streams
        its own disjoint row-group shard from the store (no process
        materializes the dataset; ref util.py:697 + keras/remote.py)."""
        store_dir = tmp_path / "store"
        import numpy as np
        import pandas as pd

        from horovod_tpu.spark import Store

        rng = np.random.RandomState(0)
        x = rng.rand(96, 4).astype(np.float32)
        y = (x @ rng.rand(4, 3)).argmax(1).astype(np.int32)
        df = pd.DataFrame({"f1": x[:, 0], "f2": x[:, 1], "f3": x[:, 2],
                           "f4": x[:, 3], "label": y})
        store = Store.create(str(store_dir))
        prepared = store.prepare_data(
            df, ["f1", "f2", "f3", "f4"], "label",
            validation_fraction=0.25, rows_per_group=9)  # 8 train groups
        out = launch(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import flax.linen as nn
            import horovod_tpu as hvd
            from horovod_tpu.spark import Estimator
            from horovod_tpu.spark.store import RowGroupReader

            reads = []
            orig_init = RowGroupReader.__init__
            def _init(self, path):
                orig_init(self, path)
                self._hvd_test_path = path
            RowGroupReader.__init__ = _init
            orig = RowGroupReader.read_group
            RowGroupReader.read_group = \\
                lambda self, i: (reads.append((self._hvd_test_path, i)),
                                 orig(self, i))[1]

            class Net(nn.Module):
                @nn.compact
                def __call__(self, x):
                    return nn.Dense(3)(nn.relu(nn.Dense(8)(x)))

            est = Estimator(Net(), feature_cols=["f1", "f2", "f3", "f4"],
                            label_col="label", batch_size=4, epochs=2)
            # fit straight from the prepared store path: schema comes
            # from the sidecar, shards stream per process
            model = est.fit({str(prepared.train_path)!r})
            leaf = np.asarray(jax.tree_util.tree_leaves(model.params)[0],
                              np.float32)
            digests = hvd.allgather_object(float(np.abs(leaf).sum()))
            assert digests[0] == digests[1], digests
            train_reads = sorted({{i for p, i in reads
                                 if "train" in p}})
            import json
            with open({str(tmp_path)!r} +
                      f"/pgroups.{{hvd.process_rank()}}.json", "w") as f:
                json.dump(train_reads, f)
            print("PREP_WORKER_OK", hvd.process_rank())
        """, tmp_path)
        assert out.returncode == 0, out.stderr[-3000:]
        assert out.stdout.count("PREP_WORKER_OK") == 2
        import json

        groups = {r: set(json.load(open(tmp_path / f"pgroups.{r}.json")))
                  for r in range(2)}
        assert groups[0] & groups[1] == set(), groups
        assert groups[0] | groups[1] == set(range(8)), groups
