"""Fast two-level exchange smoke — tier-1's proof that the
topology-aware hierarchical exchange equals the flat one.

Runs entirely on the 8-device virtual CPU mesh (2 slices x 4 chips, the
conftest default): reduce-scatter within each "ICI slice", cross-slice
phase on the 1/4-sized shards, intra-slice allgather — and asserts
parameter parity with the flat PR-1 exchange, tolerance-pinned in the
same style as the allreduce-vs-RS/AG parity tests
(``test_optimizer.py::TestShardedOptimizerStates``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops import collectives as C
from horovod_tpu.runtime.topology import (
    AXIS_DCN,
    AXIS_ICI,
    GLOBAL_AXES,
    resolve_hierarchy,
)


@pytest.fixture(autouse=True)
def runtime():
    hvd.init()
    yield
    hvd.shutdown()


def make_mesh():
    devs = np.asarray(jax.devices("cpu")[:8]).reshape(2, 4)
    return Mesh(devs, GLOBAL_AXES)


def loss_fn(params, batch):
    h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
    pred = h @ params["w2"] + params["b2"]
    return jnp.mean((pred - batch["y"]) ** 2)


def make_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (4, 16)) * 0.1,
        "b1": jnp.zeros((16,)),
        "w2": jax.random.normal(k2, (16, 1)) * 0.1,
        "b2": jnp.zeros((1,)),
    }


def make_batch(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) > 0).astype(np.float32)
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}


class TestResolveHierarchy:
    def test_auto_picks_two_level_on_factored_mesh(self):
        assert resolve_hierarchy("auto", (2, 4)) == "two_level"

    def test_auto_flattens_degenerate_axes(self):
        assert resolve_hierarchy("auto", (1, 8)) == "flat"
        assert resolve_hierarchy("auto", (8, 1)) == "flat"
        assert resolve_hierarchy("auto", (8,)) == "flat"

    def test_explicit_modes(self):
        assert resolve_hierarchy("flat", (2, 4)) == "flat"
        assert resolve_hierarchy("two_level", (2, 4)) == "two_level"
        # an explicit two_level request must not silently flatten
        with pytest.raises(ValueError, match="2-axis"):
            resolve_hierarchy("two_level", (8,))
        with pytest.raises(ValueError, match="hierarchy"):
            resolve_hierarchy("bogus", (2, 4))


class TestHierarchicalExchangeNumerics:
    """RS -> AG roundtrip of the two-level exchange equals the flat
    exchange and the closed-form psum, leaf for leaf."""

    def _leaves(self):
        r = C.axis_index(GLOBAL_AXES)
        return [jnp.arange(10, dtype=jnp.float32) * (r + 1),
                jnp.ones((3, 5), jnp.float32) * (r + 1),
                jnp.full((7,), 2.0, jnp.float32) * (r + 1)]

    def test_roundtrip_matches_flat_and_psum(self):
        def inner():
            leaves = self._leaves()
            f_shards, f_spec = C.grouped_reducescatter(
                leaves, op=C.Sum, axis=GLOBAL_AXES)
            flat = C.grouped_allgather(f_shards, f_spec, axis=GLOBAL_AXES)
            h_shards, h_spec = C.hierarchical_reducescatter(
                leaves, op=C.Sum, outer_axis=AXIS_DCN, inner_axis=AXIS_ICI)
            two = C.hierarchical_allgather(h_shards, h_spec,
                                           outer_axis=AXIS_DCN,
                                           inner_axis=AXIS_ICI)
            exact = [lax_psum(x) for x in leaves]
            return tuple(x[None] for x in two + flat + exact)

        def lax_psum(x):
            return jax.lax.psum(x, GLOBAL_AXES)

        n = 3
        out = jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=(P(GLOBAL_AXES),) * (3 * n), check_vma=False))()
        two, flat, exact = out[:n], out[n:2 * n], out[2 * n:]
        for t, f, e in zip(two, flat, exact):
            np.testing.assert_allclose(np.asarray(t), np.asarray(e),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(t), np.asarray(f),
                                       rtol=1e-6)

    def test_average_and_bucketed(self):
        def inner():
            leaves = self._leaves()
            h_shards, h_spec = C.hierarchical_reducescatter(
                leaves, op=C.Average, bucket_bytes=64)
            two = C.hierarchical_allgather(h_shards, h_spec)
            exact = [jax.lax.psum(x, GLOBAL_AXES) / 8.0 for x in leaves]
            return tuple(x[None] for x in two + exact)

        out = jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=(P(GLOBAL_AXES),) * 6, check_vma=False))()
        for t, e in zip(out[:3], out[3:]):
            np.testing.assert_allclose(np.asarray(t), np.asarray(e),
                                       rtol=1e-6)

    def test_param_shards_align_with_ownership(self):
        """local_fusion_shards over the exchange's (inner, outer)
        linearization must slice exactly the parameter block whose
        gradients this rank received — pin by reassembling the param
        slices through the hierarchical allgather."""
        def inner():
            leaves = [jnp.arange(16, dtype=jnp.float32),
                      jnp.arange(8, dtype=jnp.float32) + 100.0]
            spec = C.make_fusion_spec(leaves, 8)
            own = C.exchange_index_axes()
            p_shards = C.local_fusion_shards(leaves, spec, axis=own)
            back = C.hierarchical_allgather(p_shards, spec)
            return tuple(x[None] for x in back)

        out = jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=(P(GLOBAL_AXES),) * 2, check_vma=False))()
        for got, want in zip(out, [np.arange(16, dtype=np.float32),
                                   np.arange(8, dtype=np.float32) + 100]):
            for r in range(8):
                np.testing.assert_allclose(np.asarray(got)[r], want)

    def test_int8_dcn_wire_close_to_exact(self):
        """quantized_bits=8 compresses the cross-slice hop only; the
        result stays within the shared-scale codec's error bound."""
        rng = np.random.RandomState(3)
        data = rng.randn(8, 24).astype(np.float32)

        def inner():
            r = C.axis_index(GLOBAL_AXES)
            leaves = [jnp.asarray(data)[r]]
            shards, spec = C.hierarchical_reducescatter(
                leaves, op=C.Average, quantized_bits=8)
            (two,) = C.hierarchical_allgather(shards, spec)
            return two[None]

        out = np.asarray(jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=P(GLOBAL_AXES), check_vma=False))())
        exact = data.mean(axis=0)
        # the ICI phase is exact; only the 2-way DCN hop quantizes the
        # partial sums, so the bound is one absmax/127 rounding of the
        # 4-way partials (divided back by world)
        tol = np.abs(data).sum(axis=0).max() / 127.0
        np.testing.assert_allclose(out[0], exact, atol=tol)


class TestTwoLevelTrainStepParity:
    """The acceptance pin: two-level exchange == flat exchange == the
    PR-1 baseline, on parameters, after real optimizer steps."""

    def _train(self, hierarchy, steps=8, bucket_bytes=None,
               opt=None):
        step = hvd.DistributedTrainStep(
            loss_fn, opt or optax.adamw(1e-2), mode="shard_map",
            donate=False, shard_optimizer_states=True,
            exchange_bucket_bytes=bucket_bytes, hierarchy=hierarchy)
        params, opt_state = step.init(make_params(jax.random.PRNGKey(7)))
        batch = step.shard_batch(make_batch())
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
        return jax.device_get(params), float(loss)

    def test_two_level_matches_flat(self):
        two, loss_t = self._train("two_level")
        flat, loss_f = self._train("flat")
        for k in flat:
            np.testing.assert_allclose(np.asarray(two[k]),
                                       np.asarray(flat[k]),
                                       rtol=1e-5, atol=1e-6)
        assert abs(loss_t - loss_f) < 1e-5

    def test_auto_resolves_two_level_on_this_mesh(self):
        step = hvd.DistributedTrainStep(
            loss_fn, optax.sgd(0.1), mode="shard_map",
            shard_optimizer_states=True, hierarchy="auto")
        assert step.exchange_hierarchy == "two_level"
        flat = hvd.DistributedTrainStep(
            loss_fn, optax.sgd(0.1), mode="shard_map",
            shard_optimizer_states=True, hierarchy="flat")
        assert flat.exchange_hierarchy == "flat"

    def test_bucketed_two_level_matches(self):
        two, _ = self._train("two_level", bucket_bytes=64)
        flat, _ = self._train("flat")
        for k in flat:
            np.testing.assert_allclose(np.asarray(two[k]),
                                       np.asarray(flat[k]),
                                       rtol=1e-5, atol=1e-6)

    def test_momentum_state_shards_commute(self):
        opt = optax.sgd(0.05, momentum=0.9)
        two, _ = self._train("two_level", opt=opt)
        flat, _ = self._train("flat", opt=opt)
        for k in flat:
            np.testing.assert_allclose(np.asarray(two[k]),
                                       np.asarray(flat[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_hierarchy_knob_validation(self):
        with pytest.raises(ValueError, match="hierarchy"):
            hvd.DistributedTrainStep(loss_fn, optax.sgd(0.1),
                                     hierarchy="two_level")
        with pytest.raises(ValueError, match="hierarchy"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     hierarchy="two_level")
        with pytest.raises(ValueError, match="hierarchy"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     shard_optimizer_states=True,
                                     hierarchy="diagonal")

    def test_overlap_probe_reports_per_level_structure(self):
        """measure_overlap(hierarchy='auto') on the 2x4 runtime mesh:
        resolves two_level, times both levels, and reports the two
        reduce-scatter scopes it found in the exchange program's HLO —
        the fields bench.py emits into BENCH JSON."""
        from jax.sharding import NamedSharding
        from horovod_tpu.runtime import state as rt_state
        from horovod_tpu.utils.overlap_probe import measure_overlap

        mesh = rt_state.global_state().mesh
        params = jax.device_put(make_params(jax.random.PRNGKey(0)),
                                NamedSharding(mesh, P()))
        batch = jax.device_put(make_batch(),
                               NamedSharding(mesh, P(GLOBAL_AXES)))
        rep = measure_overlap(loss_fn, params, batch, iters=1, warmup=0)
        assert rep.hierarchy == "two_level"
        assert rep.rs_scopes == (2, 4)          # dcn and ici scopes
        assert rep.grad_sized_allreduces == 0
        assert rep.exchange_intra_s is not None
        assert rep.exchange_cross_s is not None
        fields = rep.as_bench_fields()
        assert fields["exchange_hierarchy"] == "two_level"
        assert fields["exchange_rs_scopes"] == [2, 4]
        assert "overlap_exchange_intra_s" in fields
        # flat request on the same mesh: single world-sized scope
        flat = measure_overlap(loss_fn, params, batch, hierarchy="flat",
                               iters=1, warmup=0)
        assert flat.hierarchy == "flat" and flat.rs_scopes == (8,)
        assert flat.exchange_intra_s is None

    def test_optimizer_factory_two_level_matches_flat(self):
        """DistributedOptimizer(hierarchy=...) inside a hand-written
        shard_map: one update, both topologies, identical results."""
        data = np.linspace(-1, 1, 8 * 12).reshape(8, 12).astype(np.float32)

        def f(hierarchy):
            def inner():
                r = C.axis_index(GLOBAL_AXES)
                tx = hvd.DistributedOptimizer(
                    optax.adam(0.1), shard_optimizer_states=True,
                    hierarchy=hierarchy)
                params = {"a": jnp.ones((8,)), "b": jnp.zeros((4,))}
                g = {"a": jnp.asarray(data)[r, :8],
                     "b": jnp.asarray(data)[r, 8:]}
                u, _ = tx.update(g, tx.init(params), params)
                return u["a"][None], u["b"][None]

            return map(np.asarray, jax.jit(jax.shard_map(
                inner, mesh=make_mesh(), in_specs=(),
                out_specs=(P(GLOBAL_AXES), P(GLOBAL_AXES)),
                check_vma=False))())

        ta, tb = f("two_level")
        fa, fb = f("flat")
        np.testing.assert_allclose(ta, fa, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(tb, fb, rtol=1e-5, atol=1e-6)


class TestWireDtypeCodec:
    """HOROVOD_EXCHANGE_WIRE_DTYPE satellite (ISSUE 9): the fp8 e4m3
    wire option for the shared-scale DCN codec, next to the PR-2 int8
    default."""

    def test_fp8_dcn_wire_close_to_exact(self):
        """The fp8 e4m3 wire compresses the cross-slice hop only
        (mirror of the int8 test above, codec swapped via the runtime
        knob); the result stays within the e4m3 error bound."""
        from horovod_tpu.runtime import state as rt_state

        rng = np.random.RandomState(3)
        data = rng.randn(8, 24).astype(np.float32)
        cfg = rt_state.global_state().config
        old = cfg.exchange_wire_dtype
        cfg.exchange_wire_dtype = "fp8_e4m3"
        try:
            def inner():
                r = C.axis_index(GLOBAL_AXES)
                leaves = [jnp.asarray(data)[r]]
                shards, spec = C.hierarchical_reducescatter(
                    leaves, op=C.Average, quantized_bits=8)
                (two,) = C.hierarchical_allgather(shards, spec)
                return two[None]

            out = np.asarray(jax.jit(jax.shard_map(
                inner, mesh=make_mesh(), in_specs=(),
                out_specs=P(GLOBAL_AXES), check_vma=False))())
        finally:
            cfg.exchange_wire_dtype = old
        exact = data.mean(axis=0)
        # the ICI phase is exact; only the 2-way DCN hop quantizes the
        # 4-way partials.  e4m3's 3-bit mantissa rounds each quantized
        # partial within 1/16 relative of the shared absmax range
        # (divided back by world)
        tol = np.abs(data).sum(axis=0).max() / 16.0
        np.testing.assert_allclose(out[0], exact, atol=tol)

    def test_fp8_segments_per_tensor_scales(self):
        """The fused-buffer per-segment scale machinery works at the
        fp8 wire too: a tiny-magnitude segment next to a large one is
        not flushed to the big segment's quantization step."""
        def inner():
            big = jnp.full((8,), 500.0)
            small = jnp.full((8,), 1e-3)
            flat = jnp.concatenate([big, small])
            red = C.quantized_reducescatter(
                flat, axis=GLOBAL_AXES, op=C.Average, segments=(8, 8),
                wire_dtype="fp8_e4m3")
            return red[None]

        out = np.asarray(jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=P(GLOBAL_AXES), check_vma=False))()).reshape(-1)
        np.testing.assert_allclose(out[:8], 500.0, rtol=0.1)
        # the small segment survives with its own scale (a shared
        # 500-range scale would round 1e-3 to 0)
        np.testing.assert_allclose(out[8:], 1e-3, rtol=0.1)

    def test_invalid_wire_dtype_raises(self):
        with pytest.raises(ValueError, match="wire dtype"):
            C._resolve_wire_dtype("fp4")

    def test_env_knob_reaches_config(self, monkeypatch):
        from horovod_tpu.runtime.config import Config

        monkeypatch.setenv("HOROVOD_EXCHANGE_WIRE_DTYPE", "fp8_e4m3")
        cfg = Config.from_env()
        assert cfg.exchange_wire_dtype == "fp8_e4m3"
        assert "exchange_wire_dtype" in cfg.fixed_knobs

    def test_config_knob_selects_codec(self):
        """The initialized runtime's exchange_wire_dtype drives the
        codec when no explicit wire_dtype is passed: the compiled
        exchange carries an f8e4m3fn conversion on the DCN hop."""
        from horovod_tpu.runtime import state as rt_state

        cfg = rt_state.global_state().config
        old = cfg.exchange_wire_dtype
        cfg.exchange_wire_dtype = "fp8_e4m3"
        try:
            def inner():
                flat = jnp.arange(16, dtype=jnp.float32)
                return C.quantized_reducescatter(
                    flat, axis=GLOBAL_AXES, op=C.Sum)[None]

            sm = jax.jit(jax.shard_map(
                inner, mesh=make_mesh(), in_specs=(),
                out_specs=P(GLOBAL_AXES), check_vma=False))
            assert "f8e4m3fn" in sm.lower().compile().as_text()
        finally:
            cfg.exchange_wire_dtype = old

    @pytest.mark.parametrize("wire", ["int8", "fp8_e4m3"])
    def test_two_level_matches_flat_param_parity(self, wire):
        """The acceptance pin at BOTH wire dtypes: training through the
        two-level exchange with the quantized DCN hop stays within the
        codec's error envelope of the flat full-precision baseline
        (measured deltas <= 1e-3 abs on this workload; pinned at 4x)."""
        from horovod_tpu.ops.compression import Compression
        from horovod_tpu.runtime import state as rt_state

        cfg = rt_state.global_state().config
        old = cfg.exchange_wire_dtype
        cfg.exchange_wire_dtype = wire
        try:
            def train(hierarchy, compression=None, steps=6):
                step = hvd.DistributedTrainStep(
                    loss_fn, optax.sgd(0.05), mode="shard_map",
                    donate=False, shard_optimizer_states=True,
                    hierarchy=hierarchy, compression=compression)
                params, opt_state = step.init(
                    make_params(jax.random.PRNGKey(7)))
                batch = step.shard_batch(make_batch())
                for _ in range(steps):
                    params, opt_state, _ = step(params, opt_state,
                                                batch)
                return jax.device_get(params)

            two = train("two_level", Compression.int8)
            flat = train("flat")
            for k in flat:
                np.testing.assert_allclose(
                    np.asarray(two[k]), np.asarray(flat[k]),
                    rtol=0.05, atol=4e-3, err_msg=f"{wire}/{k}")
        finally:
            cfg.exchange_wire_dtype = old


class TestErrorFeedback:
    """ISSUE 13 satellite: error-feedback residuals for the quantized
    reduce-scatter — each rank re-adds last step's rounding error
    before quantizing, so the compressed wire's bias (not just its
    variance) cancels over a trajectory (docs/parallelism.md)."""

    def _train(self, hierarchy, compression=None, error_feedback=False,
               steps=8):
        from horovod_tpu.ops.compression import Compression  # noqa: F401

        step = hvd.DistributedTrainStep(
            loss_fn, optax.sgd(0.05), mode="shard_map", donate=False,
            shard_optimizer_states=True, hierarchy=hierarchy,
            compression=compression, error_feedback=error_feedback)
        params, opt_state = step.init(make_params(jax.random.PRNGKey(7)))
        batch = step.shard_batch(make_batch())
        for _ in range(steps):
            params, opt_state, _ = step(params, opt_state, batch)
        return jax.device_get(params)

    @staticmethod
    def _max_err(a, b):
        return max(float(np.max(np.abs(np.asarray(a[k])
                                       - np.asarray(b[k]))))
                   for k in b)

    def test_ef_tightens_flat_quantized_trajectory(self):
        """After 8 int8-wire steps, the compensated flat trajectory
        sits closer to the fp32 reference than the uncompensated one —
        the residual telescopes the codec's bias away."""
        from horovod_tpu.ops.compression import Compression

        exact = self._train("flat")
        ef = self._train("flat", Compression.int8, error_feedback=True)
        raw = self._train("flat", Compression.int8)
        assert self._max_err(ef, exact) <= self._max_err(raw, exact)
        for k in exact:
            np.testing.assert_allclose(np.asarray(ef[k]),
                                       np.asarray(exact[k]),
                                       rtol=0.02, atol=2e-3)

    def test_two_level_ef_double_codec_stays_in_envelope(self):
        """Two-level EF quantizes BOTH hops (it turns the ICI codec
        on, where the raw path compresses DCN only) yet the
        compensated trajectory stays inside the single-codec error
        envelope — the feedback pays for the extra rounding."""
        from horovod_tpu.ops.compression import Compression

        exact = self._train("flat")
        ef = self._train("two_level", Compression.int8,
                         error_feedback=True)
        raw = self._train("two_level", Compression.int8)
        assert self._max_err(ef, exact) <= \
            1.25 * self._max_err(raw, exact)
        for k in exact:
            np.testing.assert_allclose(np.asarray(ef[k]),
                                       np.asarray(exact[k]),
                                       rtol=0.02, atol=2e-3)

    def test_residual_cancels_codec_bias(self):
        """Direct codec pin: quantizing the SAME vector repeatedly
        with the residual carried makes the running mean converge on
        the exact reduction — without it the rounding bias persists
        unchanged every round."""
        rng = np.random.RandomState(5)
        data = rng.randn(8, 24).astype(np.float32)
        rounds = 8

        def inner():
            r = C.axis_index(GLOBAL_AXES)
            x = jnp.asarray(data)[r]
            res = jnp.zeros_like(x)
            acc = jnp.zeros((3,))
            for _ in range(rounds):
                y, res = C.ef_quantized_reducescatter(
                    x, axis=GLOBAL_AXES, op=C.Average, residual=res)
                acc = acc + y
            plain = C.quantized_reducescatter(
                x, axis=GLOBAL_AXES, op=C.Average)
            return (acc / rounds)[None], plain[None]

        ef_mean, plain = jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=(P(GLOBAL_AXES), P(GLOBAL_AXES)),
            check_vma=False))()
        exact = data.mean(axis=0)
        err_ef = np.max(np.abs(np.asarray(ef_mean).reshape(-1) - exact))
        err_plain = np.max(np.abs(np.asarray(plain).reshape(-1)
                                  - exact))
        assert err_plain > 0.0          # the codec does round here
        assert err_ef < err_plain / 2.0

    def test_two_level_ef_quantizes_the_ici_hop(self):
        """Under EF the two-level exchange turns the inner (ICI)
        phase's codec ON: the residual-threaded
        hierarchical_reducescatter compiles int8 conversions for the
        4-wide ICI scope, not just the DCN hop."""
        def inner():
            leaves = [jnp.arange(16, dtype=jnp.float32)]
            res = {g.key: jnp.zeros((g.padded,), jnp.float32)
                   for g in C.make_fusion_spec(leaves, 8).groups}
            shards, spec, res = C.hierarchical_reducescatter(
                leaves, op=C.Average, quantized_bits=8,
                quantize_inner=True, inner_residuals=res)
            (out,) = C.hierarchical_allgather(shards, spec)
            return out[None]

        sm = jax.jit(jax.shard_map(
            inner, mesh=make_mesh(), in_specs=(),
            out_specs=P(GLOBAL_AXES), check_vma=False))
        hlo = sm.lower().compile().as_text()
        assert "s8" in hlo or "s32" in hlo

    def test_inner_codec_knob_validation(self):
        with pytest.raises(ValueError, match="quantized_bits"):
            C.hierarchical_reducescatter(
                [jnp.zeros(8)], op=C.Sum, quantize_inner=True)
        with pytest.raises(ValueError, match="quantize_inner"):
            C.hierarchical_reducescatter(
                [jnp.zeros(8)], op=C.Sum, quantized_bits=8,
                inner_residuals={})

    def test_ef_knob_validation(self):
        from horovod_tpu.ops.compression import Compression

        with pytest.raises(ValueError, match="error_feedback"):
            hvd.DistributedTrainStep(
                loss_fn, optax.sgd(0.1), error_feedback=True)
        with pytest.raises(ValueError, match="compression"):
            hvd.DistributedTrainStep(
                loss_fn, optax.sgd(0.1), mode="shard_map",
                shard_optimizer_states=True, error_feedback=True)
        with pytest.raises(ValueError, match="shard_optimizer_states"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     error_feedback=True)
        with pytest.raises(ValueError, match="quantized_bits"):
            from horovod_tpu.optim.optimizer import (
                sharded_distributed_update,
            )

            sharded_distributed_update(optax.sgd(0.1), world=8,
                                       error_feedback=True)
        # the valid spelling constructs cleanly
        hvd.DistributedTrainStep(
            loss_fn, optax.sgd(0.1), mode="shard_map",
            shard_optimizer_states=True,
            compression=Compression.int8, error_feedback=True)


class TestTreeExchange:
    """ISSUE 18 tentpole: the N-level tree exchange on a 2x2x2
    virtual mesh — parity with the flat exchange, exact degeneracy
    with two_level on the 2-axis runtime mesh, and the per-level wire
    codec bounds."""

    TREE_AXES = ("pod", "slice", "chip")    # outermost first

    def make_tree_mesh(self):
        devs = np.asarray(jax.devices("cpu")[:8]).reshape(2, 2, 2)
        return Mesh(devs, self.TREE_AXES)

    def _levels(self, pod_bits=None, chip_bits=None):
        # innermost first — the tree_reducescatter convention
        return (C.ExchangeLevel("chip", chip_bits),
                C.ExchangeLevel("slice"),
                C.ExchangeLevel("pod", pod_bits))

    def test_three_level_roundtrip_matches_flat_and_psum(self):
        """The 3-level flat-parity pin: RS -> AG through the tree
        equals the flat exchange and the closed-form psum, leaf for
        leaf."""
        def inner():
            r = C.axis_index(self.TREE_AXES)
            leaves = [jnp.arange(10, dtype=jnp.float32) * (r + 1),
                      jnp.ones((3, 5), jnp.float32) * (r + 1),
                      jnp.full((7,), 2.0, jnp.float32) * (r + 1)]
            levels = self._levels()
            t_shards, t_spec = C.tree_reducescatter(leaves, levels,
                                                    op=C.Sum)
            tree = C.tree_allgather(t_shards, t_spec, levels)
            f_shards, f_spec = C.grouped_reducescatter(
                leaves, op=C.Sum, axis=self.TREE_AXES)
            flat = C.grouped_allgather(f_shards, f_spec,
                                       axis=self.TREE_AXES)
            exact = [jax.lax.psum(x, self.TREE_AXES) for x in leaves]
            return tuple(x[None] for x in tree + flat + exact)

        n = 3
        out = jax.jit(jax.shard_map(
            inner, mesh=self.make_tree_mesh(), in_specs=(),
            out_specs=(P(self.TREE_AXES),) * (3 * n),
            check_vma=False))()
        tree, flat, exact = out[:n], out[n:2 * n], out[2 * n:]
        for t, f, e in zip(tree, flat, exact):
            np.testing.assert_allclose(np.asarray(t), np.asarray(e),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(t), np.asarray(f),
                                       rtol=1e-6)

    def _sharded_update(self, hierarchy, level_codecs=None,
                        quantized_bits=None):
        from horovod_tpu.optim.optimizer import (
            sharded_distributed_update,
        )

        data = np.linspace(-1, 1, 8 * 12).reshape(8, 12) \
            .astype(np.float32)

        def inner():
            r = C.axis_index(self.TREE_AXES)
            tx = sharded_distributed_update(
                optax.adam(0.1), axis=self.TREE_AXES, world=8,
                hierarchy=hierarchy, quantized_bits=quantized_bits,
                level_codecs=level_codecs)
            params = {"a": jnp.ones((8,)), "b": jnp.zeros((4,))}
            g = {"a": jnp.asarray(data)[r, :8],
                 "b": jnp.asarray(data)[r, 8:]}
            u, _ = tx.update(g, tx.init(params), params)
            return u["a"][None], u["b"][None]

        return [np.asarray(x) for x in jax.jit(jax.shard_map(
            inner, mesh=self.make_tree_mesh(), in_specs=(),
            out_specs=(P(self.TREE_AXES), P(self.TREE_AXES)),
            check_vma=False))()]

    def test_optimizer_tree_matches_flat(self):
        """sharded_distributed_update(hierarchy='tree') on the 3-axis
        mesh: same updates as the flat exchange — and 'auto' resolves
        to the same tree on a fully factored 3-axis spec."""
        ta, tb = self._sharded_update("tree")
        fa, fb = self._sharded_update("flat")
        np.testing.assert_allclose(ta, fa, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(tb, fb, rtol=1e-5, atol=1e-6)
        aa, ab = self._sharded_update("auto")
        np.testing.assert_array_equal(aa, ta)
        np.testing.assert_array_equal(ab, tb)

    def test_tree_degenerates_to_two_level_on_the_runtime_mesh(self):
        """A 2-axis tree IS two_level: hierarchy='tree' on the (2, 4)
        runtime mesh compiles the same exchange as 'two_level', so the
        trained parameters are bit-identical."""
        def train(hierarchy, steps=4):
            step = hvd.DistributedTrainStep(
                loss_fn, optax.adamw(1e-2), mode="shard_map",
                donate=False, shard_optimizer_states=True,
                hierarchy=hierarchy)
            assert step.exchange_hierarchy == "two_level"
            params, opt_state = step.init(
                make_params(jax.random.PRNGKey(7)))
            batch = step.shard_batch(make_batch())
            for _ in range(steps):
                params, opt_state, _ = step(params, opt_state, batch)
            return jax.device_get(params)

        tree, two = train("tree"), train("two_level")
        for k in two:
            np.testing.assert_array_equal(np.asarray(tree[k]),
                                          np.asarray(two[k]))

    def test_outermost_codec_close_to_exact(self):
        """quantized_bits on the tree compresses the outermost (pod)
        hop only — the 2-way quantized phase stays within the
        shared-scale codec's error bound."""
        rng = np.random.RandomState(3)
        data = rng.randn(8, 24).astype(np.float32)

        def inner():
            r = C.axis_index(self.TREE_AXES)
            leaves = [jnp.asarray(data)[r]]
            levels = self._levels(pod_bits=8)
            shards, spec = C.tree_reducescatter(leaves, levels,
                                                op=C.Average)
            (out,) = C.tree_allgather(shards, spec, levels)
            return out[None]

        out = np.asarray(jax.jit(jax.shard_map(
            inner, mesh=self.make_tree_mesh(), in_specs=(),
            out_specs=P(self.TREE_AXES), check_vma=False))())
        exact = data.mean(axis=0)
        tol = np.abs(data).sum(axis=0).max() / 127.0
        np.testing.assert_allclose(out[0], exact, atol=tol)

    def test_level_codecs_knob_places_the_wire_codec(self):
        """level_codecs={'pod': 8} through the sharded update equals
        the quantized_bits spelling exactly (same placement) and stays
        within the codec envelope of the full-precision tree."""
        ca, cb = self._sharded_update("tree",
                                      level_codecs={"pod": 8})
        qa, qb = self._sharded_update("tree", quantized_bits=8)
        np.testing.assert_array_equal(ca, qa)
        np.testing.assert_array_equal(cb, qb)
        fa, fb = self._sharded_update("tree")
        np.testing.assert_allclose(ca, fa, rtol=0.05, atol=4e-3)
        np.testing.assert_allclose(cb, fb, rtol=0.05, atol=4e-3)

    def test_innermost_codec_uses_per_segment_scales(self):
        """The innermost hop's codec rides the segment machinery (one
        scale per fused leaf), so a tiny leaf next to a large one
        survives — the same guarantee the flat quantized exchange
        gives."""
        def inner():
            leaves = [jnp.full((8,), 500.0), jnp.full((8,), 1e-3)]
            levels = self._levels(chip_bits=8)
            shards, spec = C.tree_reducescatter(leaves, levels,
                                                op=C.Average)
            big, small = C.tree_allgather(shards, spec, levels)
            return big[None], small[None]

        big, small = jax.jit(jax.shard_map(
            inner, mesh=self.make_tree_mesh(), in_specs=(),
            out_specs=(P(self.TREE_AXES), P(self.TREE_AXES)),
            check_vma=False))()
        np.testing.assert_allclose(
            np.asarray(big).reshape(-1), 500.0, rtol=0.1)
        np.testing.assert_allclose(
            np.asarray(small).reshape(-1), 1e-3, rtol=0.1)

    def test_tree_validation(self):
        with pytest.raises(ValueError, match="op=Sum/Average"):
            C.tree_reducescatter([jnp.zeros(8)],
                                 (C.ExchangeLevel("chip"),),
                                 op=C.Adasum)
        with pytest.raises(ValueError, match="quantized_bits"):
            C.tree_reducescatter([jnp.zeros(8)],
                                 (C.ExchangeLevel("chip"),),
                                 op=C.Sum, residuals={})
        with pytest.raises(ValueError, match=">= 1 level"):
            C.tree_reducescatter([jnp.zeros(8)], (), op=C.Sum)


class TestFusedTailExchange:
    """fused_collectives="on" (ISSUE 9 tentpole, ZeRO side): the
    tile-granular final-bucket exchange is numerically IDENTICAL to
    the monolithic one — only the schedule changes."""

    def _train(self, steps=6, **kw):
        step = hvd.DistributedTrainStep(
            loss_fn, optax.adamw(1e-2), mode="shard_map", donate=False,
            shard_optimizer_states=True, **kw)
        params, opt_state = step.init(make_params(jax.random.PRNGKey(7)))
        batch = step.shard_batch(make_batch())
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, batch)
        return jax.device_get(params), float(loss)

    @pytest.mark.parametrize("hierarchy", ["flat", "two_level"])
    def test_fused_tail_matches_unfused(self, hierarchy):
        on, loss_on = self._train(hierarchy=hierarchy,
                                  fused_collectives="on")
        off, loss_off = self._train(hierarchy=hierarchy,
                                    fused_collectives="off")
        for k in off:
            np.testing.assert_allclose(np.asarray(on[k]),
                                       np.asarray(off[k]),
                                       rtol=1e-6, atol=1e-7)
        assert abs(loss_on - loss_off) < 1e-6

    def test_bucketed_fused_tail_matches(self):
        on, _ = self._train(fused_collectives="on",
                            exchange_bucket_bytes=64)
        off, _ = self._train(fused_collectives="off",
                             exchange_bucket_bytes=64)
        for k in off:
            np.testing.assert_allclose(np.asarray(on[k]),
                                       np.asarray(off[k]),
                                       rtol=1e-6, atol=1e-7)

    def test_knob_validation(self):
        with pytest.raises(ValueError, match="fused_collectives"):
            hvd.DistributedTrainStep(loss_fn, optax.sgd(0.1),
                                     fused_collectives="on")
        with pytest.raises(ValueError, match="fused_collectives"):
            hvd.DistributedOptimizer(optax.sgd(0.1),
                                     fused_collectives="on")
        with pytest.raises(ValueError, match="fused_collectives"):
            hvd.DistributedTrainStep(loss_fn, optax.sgd(0.1),
                                     mode="shard_map",
                                     shard_optimizer_states=True,
                                     fused_collectives="sometimes")

    def test_probe_reports_tail_fields(self):
        """measure_overlap emits the tail quantities for both
        final-bucket schedules, and the serial-tail HLO scan returns a
        judgement (0 on this synchronous CPU backend)."""
        from jax.sharding import NamedSharding
        from horovod_tpu.runtime import state as rt_state
        from horovod_tpu.utils.overlap_probe import measure_overlap

        mesh = rt_state.global_state().mesh
        params = jax.device_put(make_params(jax.random.PRNGKey(0)),
                                NamedSharding(mesh, P()))
        batch = jax.device_put(make_batch(),
                               NamedSharding(mesh, P(GLOBAL_AXES)))
        rep = measure_overlap(loss_fn, params, batch,
                              fused_collectives="off",
                              iters=1, warmup=0)
        assert rep.fused_collectives == "off"
        assert rep.tail_exchange_s >= 0.0
        fields = rep.as_bench_fields()
        assert "tail_exchange_s" in fields
        assert fields["fused_collectives"] == "off"
        assert fields["exchange_serial_tail_collectives"] == 0
        fused = measure_overlap(loss_fn, params, batch,
                                fused_collectives="on",
                                iters=1, warmup=0)
        assert fused.fused_collectives == "on"
        assert fused.tail_exchange_s >= 0.0
        assert fused.as_bench_fields("x_")["x_fused_collectives"] == "on"
