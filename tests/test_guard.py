"""hvdguard unit coverage (docs/guardian.md): the numerics guardian's
EMA baseline and policies, checksum fingerprint/compare determinism and
bit-flip sensitivity, rollback bookkeeping with checkpoint pinning,
preemption-grace semantics, the peer-repair RPC round trip, and the
disabled-path overhead pin."""

import math
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu import faults, guard
from horovod_tpu.guard import (
    GuardAbort,
    GuardRollback,
    NumericsGuardian,
    PreemptionHandler,
    ReplicaChecker,
    RollbackManager,
    TrainingGuard,
    compare,
    fingerprint,
)


@pytest.fixture(autouse=True)
def _clean():
    faults.clear_plan()
    guard.clear_guard()
    yield
    faults.clear_plan()
    guard.clear_guard()


class TestNumericsGuardian:
    def test_warmup_limit_is_infinite(self):
        g = NumericsGuardian(warmup_steps=5)
        for _ in range(4):
            assert g.current_limit() == math.inf
            g.observe(1.0)
        assert g.current_limit() == math.inf   # 4 < warmup
        g.observe(1.0)
        assert math.isfinite(g.current_limit())

    def test_limit_tracks_baseline(self):
        g = NumericsGuardian(warmup_steps=3, zscore=6.0)
        for _ in range(20):
            g.observe(1.0)
        # flat history at norm 1.0: limit = exp(0 + 6 * std_floor)
        assert g.current_limit() == pytest.approx(math.exp(6.0 * 0.05))

    def test_nonfinite_detected_even_during_warmup(self):
        g = NumericsGuardian(policy="skip_step", warmup_steps=100)
        assert g.observe(float("nan")) == "nonfinite"
        assert g.observe(float("inf")) == "nonfinite"
        assert g.anomalies == 2

    def test_spike_detected_after_warmup(self):
        g = NumericsGuardian(policy="skip_step", warmup_steps=3)
        for _ in range(10):
            assert g.observe(1.0) == "ok"
        assert g.observe(100.0) == "spike"

    def test_anomaly_never_poisons_baseline(self):
        g = NumericsGuardian(policy="skip_step", warmup_steps=3)
        for _ in range(10):
            g.observe(1.0)
        limit = g.current_limit()
        n = g.observed_steps
        g.observe(float("nan"))
        g.observe(limit * 10)
        assert g.observed_steps == n           # anomalies not counted
        assert g.current_limit() == limit      # baseline unchanged

    def test_rollback_policy_raises(self):
        g = NumericsGuardian(policy="rollback", warmup_steps=1)
        g.observe(1.0)
        with pytest.raises(GuardRollback) as ei:
            g.observe(float("nan"))
        assert ei.value.kind == "nonfinite"

    def test_abort_policy_raises(self):
        g = NumericsGuardian(policy="abort", warmup_steps=1)
        with pytest.raises(GuardAbort):
            g.observe(float("inf"))

    def test_bad_policy_rejected(self):
        with pytest.raises(ValueError, match="policy"):
            NumericsGuardian(policy="ignore")
        with pytest.raises(ValueError, match="ema"):
            NumericsGuardian(ema=1.0)

    def test_explicit_limit_overrides_baseline(self):
        # the step ran with a stale limit (host baseline moved after
        # dispatch): the verdict must judge against what the step used
        g = NumericsGuardian(policy="skip_step", warmup_steps=1)
        g.observe(1.0)
        assert g.observe(5.0, limit=10.0) == "ok"
        assert g.observe(5.0, limit=2.0) == "spike"


class TestChecksum:
    def tree(self, v=1.0):
        return {"w": np.full((8, 8), v, np.float32),
                "b": np.arange(8, dtype=np.float32),
                "step": 7}

    def test_equal_trees_agree(self):
        assert fingerprint(self.tree()) == fingerprint(self.tree())

    def test_single_bit_flip_changes_fingerprint(self):
        a = self.tree()
        b = self.tree()
        raw = b["w"].view(np.uint32)
        raw[3, 3] ^= 1                      # one mantissa bit
        assert fingerprint(a) != fingerprint(b)

    def test_nan_payload_bits_distinguished(self):
        # equality-based comparison would call two NaNs equal; the
        # byte-level fingerprint must not
        a = np.array([float("nan")], np.float32)
        b = a.copy()
        b.view(np.uint32)[0] ^= 1           # different NaN payload
        assert fingerprint({"x": a}) != fingerprint({"x": b})

    def test_order_sensitivity(self):
        a = np.array([1.0, 2.0], np.float32)
        b = np.array([2.0, 1.0], np.float32)
        assert fingerprint({"x": a}) != fingerprint({"x": b})

    def test_compare_names_minority(self):
        f = fingerprint(self.tree())
        g = fingerprint(self.tree(2.0))
        assert compare([f, f, f, f]) == []
        assert compare([f, f, g, f]) == [2]
        assert compare([g, f, f]) == [0]

    def test_two_rank_tie_names_rank_one(self):
        # rank 0 is the checkpoint writer — recovery treats it as the
        # reference copy, so a 1v1 tie must name rank 1
        f = fingerprint(self.tree())
        g = fingerprint(self.tree(2.0))
        assert compare([f, g]) == [1]

    def test_checker_cadence(self):
        c = ReplicaChecker(interval=3)
        assert [s for s in range(1, 10) if c.due(s)] == [3, 6, 9]
        assert not ReplicaChecker(interval=0).due(10)

    def test_checker_reports_diverged_rank(self):
        trees = [self.tree(), self.tree(), self.tree(5.0)]
        fps = [fingerprint(t) for t in trees]
        c = ReplicaChecker(interval=1, gather_fn=lambda fp: fps)
        report = c.check(3, trees[0])
        assert report is not None and report.diverged == [2]
        assert report.rank == 2 and report.step == 3

    def test_checker_clean_returns_none(self):
        c = ReplicaChecker(interval=1,
                           gather_fn=lambda fp: [fp, fp, fp, fp])
        assert c.check(5, self.tree()) is None


class TestRollbackManager:
    EVERY = 2

    def make_state(self, tmp_path):
        ckpt = hvd.checkpoint.Checkpointer(str(tmp_path / "ck"),
                                           max_to_keep=2, use_orbax=False)
        return hvd.elastic.TpuState(
            params={"w": np.zeros((4,), np.float32)},
            checkpointer=ckpt, checkpoint_every=self.EVERY)

    def run_to(self, state, rb, steps, verify_at=()):
        for _ in range(steps):
            step = state._commit_count + 1
            state.params = {"w": np.full((4,), float(step), np.float32)}
            state.commit()
            rb.note_commit()
            if step in verify_at:
                rb.note_verified(step)
        state.wait()

    def test_note_commit_tracks_checkpoint_steps(self, tmp_path):
        state = self.make_state(tmp_path)
        rb = RollbackManager(state)
        self.run_to(state, rb, 5)
        assert rb.last_checkpoint_step == 4    # 5 % EVERY != 0
        assert rb.last_good_step is None       # nothing verified yet

    def test_note_verified_promotes_and_pins(self, tmp_path):
        state = self.make_state(tmp_path)
        rb = RollbackManager(state)
        self.run_to(state, rb, 5, verify_at=(4,))
        assert rb.last_good_step == 4
        assert state._checkpointer.pinned_steps() == [4]
        # a newer verified checkpoint takes the pin over
        self.run_to(state, rb, 1, verify_at=(6,))
        assert rb.last_good_step == 6
        assert state._checkpointer.pinned_steps() == [6]

    def test_verified_older_than_checkpoint_is_ignored(self, tmp_path):
        state = self.make_state(tmp_path)
        rb = RollbackManager(state)
        self.run_to(state, rb, 4)
        rb.note_verified(3)                    # checkpoint 4 is newer
        assert rb.last_good_step is None

    def test_rollback_restores_and_counts_replay(self, tmp_path):
        state = self.make_state(tmp_path)
        positions = {}
        rb = RollbackManager(state,
                             dataset_state_fn=lambda s: positions.get(s))
        positions.update({2: "pos@2", 4: "pos@4", 6: "pos@6"})
        self.run_to(state, rb, 7, verify_at=(4,))
        replayed = rb.rollback(reason="test")
        assert replayed == 3                   # 7 -> 4
        assert state._commit_count == 4
        np.testing.assert_allclose(np.asarray(state.params["w"]), 4.0)
        assert rb.last_data_position == "pos@4"
        assert rb.rollbacks == 1

    def test_rollback_without_verification_uses_last_checkpoint(
            self, tmp_path):
        state = self.make_state(tmp_path)
        rb = RollbackManager(state)
        self.run_to(state, rb, 3)
        assert rb.rollback() == 1              # 3 -> 2 (unverified)
        assert state._commit_count == 2

    def test_rollback_with_no_checkpoint_raises(self):
        state = hvd.elastic.TpuState(
            params={"w": np.zeros((2,), np.float32)})
        rb = RollbackManager(state)
        state.commit()
        with pytest.raises(RuntimeError, match="no checkpoint"):
            rb.rollback()


class TestPreemptionHandler:
    def test_drain_commit_notify_sequence(self):
        events = []
        h = PreemptionHandler(lambda: events.append("commit"),
                              notify_fn=lambda: events.append("notify"))
        assert not h.draining
        assert not h.finalize()                # nothing requested
        h.request()
        assert h.draining
        assert h.finalize()
        assert events == ["commit", "notify"]

    def test_finalize_is_idempotent(self):
        commits = []
        h = PreemptionHandler(lambda: commits.append(1))
        h.request()
        assert h.finalize()
        assert not h.finalize()
        assert len(commits) == 1

    def test_notify_failure_does_not_lose_commit(self):
        commits = []

        def bad_notify():
            raise OSError("driver gone")

        h = PreemptionHandler(lambda: commits.append(1),
                              notify_fn=bad_notify)
        h.request()
        assert h.finalize()                    # commit landed anyway
        assert len(commits) == 1

    def test_install_uninstall_restores_prior_handler(self):
        import signal

        prev = signal.getsignal(signal.SIGTERM)
        h = PreemptionHandler(lambda: None).install()
        assert signal.getsignal(signal.SIGTERM) == h._on_signal
        h.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev

    def test_chaos_site_fires_in_finalize(self):
        faults.set_plan(faults.FaultPlan(sim=True).add(
            "worker.preempt", "raise", "OSError"))
        h = PreemptionHandler(lambda: None)
        h.request()
        with pytest.raises(OSError):
            h.finalize()


class TestPeerRepairRPC:
    """The FetchStateRequest round trip over a real NotificationServer
    (the wire a diverged worker repairs through)."""

    KEY = "test-secret"

    def serve(self, provider):
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.runner.network import NotificationServer

        mgr = WorkerNotificationManager()
        mgr.set_state_provider(provider)
        server = NotificationServer(mgr, self.KEY)
        server.start()
        return server

    def test_fetch_committed_snapshot(self):
        from horovod_tpu.guard.repair import fetch_peer_state

        snap = {"w": np.arange(4, dtype=np.float32)}
        server = self.serve(lambda: (11, snap))
        try:
            addr = ("127.0.0.1", server.address[1])
            got = fetch_peer_state(addr, self.KEY)
            assert got is not None and got[0] == 11
            np.testing.assert_array_equal(got[1]["w"], snap["w"])
        finally:
            server.shutdown()

    def test_no_provider_returns_none(self):
        from horovod_tpu.elastic.worker import WorkerNotificationManager
        from horovod_tpu.guard.repair import fetch_peer_state
        from horovod_tpu.runner.network import NotificationServer

        server = NotificationServer(WorkerNotificationManager(), self.KEY)
        server.start()
        try:
            addr = ("127.0.0.1", server.address[1])
            assert fetch_peer_state(addr, self.KEY) is None
        finally:
            server.shutdown()

    def test_repair_chaos_site_fires(self):
        from horovod_tpu.guard.repair import fetch_peer_state

        faults.set_plan(faults.FaultPlan(sim=True).add(
            "guard.repair", "raise", "ConnectionResetError"))
        with pytest.raises(ConnectionResetError):
            fetch_peer_state(("127.0.0.1", 1), self.KEY)


class TestTrainingGuard:
    def test_from_config_off_returns_none(self):
        cfg = hvd.runtime.Config()
        assert TrainingGuard.from_config(cfg) is None

    def test_from_config_builds_wired_guard(self, tmp_path):
        cfg = hvd.runtime.Config(guard_enabled=True, guard_policy="abort",
                                 guard_check_interval=7, guard_zscore=4.0)
        ckpt = hvd.checkpoint.Checkpointer(str(tmp_path / "ck"),
                                           use_orbax=False)
        state = hvd.elastic.TpuState(params={"w": np.zeros(2)},
                                     checkpointer=ckpt)
        g = TrainingGuard.from_config(cfg, state=state)
        assert g is not None and g.policy == "abort"
        assert g.checker.interval == 7
        assert g.numerics.zscore == 4.0
        assert g.rollback_mgr is not None

    def test_check_replicas_raises_on_divergence(self):
        fps = []
        g = TrainingGuard(check_interval=2,
                          gather_fn=lambda fp: fps)
        params = {"w": np.ones(4, np.float32)}
        fps.extend([fingerprint(params),
                    fingerprint({"w": np.zeros(4, np.float32)})])
        assert g.check_replicas(1, params) is params   # not due
        with pytest.raises(GuardRollback, match="rank 1 diverged"):
            g.check_replicas(2, params)

    def test_corrupt_chaos_replaces_params(self):
        faults.set_plan(faults.FaultPlan(seed=3, sim=True).add(
            "guard.params", "corrupt", arg=2.0, at=1))
        g = TrainingGuard(check_interval=0)
        params = {"w": np.ones(4, np.float32)}
        out = g.check_replicas(1, params)
        assert out is not params
        assert not np.array_equal(out["w"], params["w"])

    def test_rollback_without_manager_raises(self):
        with pytest.raises(RuntimeError, match="RollbackManager"):
            TrainingGuard().rollback()


class TestModuleHook:
    def test_disabled_check_is_noop(self):
        assert guard.active_guard() is None
        assert guard.check(123) is None

    def test_armed_check_dispatches(self):
        fps = []
        g = guard.set_guard(TrainingGuard(check_interval=1,
                                          gather_fn=lambda fp: fps))
        assert guard.active_guard() is g
        params = {"w": np.ones(2, np.float32)}
        fps[:] = [fingerprint(params), fingerprint(params)]
        assert guard.check(1, params) is params
        guard.clear_guard()
        assert guard.active_guard() is None

    def test_disabled_check_is_cheap(self):
        # the hook sits on the per-step hot path: when no guard is
        # armed it must be one global None test (same contract and
        # same pin as faults.inject — docs/guardian.md)
        guard.clear_guard()
        t0 = time.perf_counter()
        for i in range(100_000):
            guard.check(i)
        per_call = (time.perf_counter() - t0) / 100_000
        assert per_call < 5e-6
